"""Deterministic fault injection — the testable half of fault tolerance.

None of the failure handling (retry-with-resume, graceful preemption,
corrupt-checkpoint fallback, heartbeat supervision) is trustworthy
unless a test can *cause* each failure at an exact step. ``FAULT_SPEC``
is that cause: an env/config grammar the train loop honors at every
step boundary, identical on the local path, the Ray worker path, and
the real multi-process harness (``tests/_multihost.py`` — the env
propagates to every worker process).

Grammar — ``;``-separated entries of ``:``-separated ``key=value``
fields::

    FAULT_SPEC="rank=1:kind=kill:step=5;rank=*:kind=sigterm:step=8"

Fields:

- ``kind`` (required): ``kill`` (raise — the worker process dies),
  ``hang`` (sleep ``seconds`` — a wedged collective), ``sigterm``
  (deliver a preemption, ``train/preempt.py``), ``ckpt_truncate``
  (corrupt the newest checkpoint step on disk — an interrupted async
  save's torn tail).
- ``step`` (required int): global step AFTER which the fault fires
  (the loop calls ``on_step`` once per completed step).
- ``rank`` (int or ``*``, default ``*``): which worker fires it.
- ``seconds`` (float, ``hang`` only, default 3600): hang duration —
  finite so an undetected hang still ends, but far beyond any
  reasonable ``HEARTBEAT_TIMEOUT_S``.

Each entry fires at most once per RUN, mirroring a real one-shot
hardware event: the fired-registry is module-global (an in-process
retry — the ``JaxTrainer`` local path — does not re-fire) AND, when a
checkpoint manager is bound, persisted as a marker file beside the
checkpoints — so on a real Ray cluster, where every retry attempt is a
FRESH actor process that re-reaches the fault step after resume, the
fault still fires exactly once. Tests call :func:`reset_fired` between
cases (fresh tmp checkpoint dirs take care of the marker file).
"""

from __future__ import annotations

import dataclasses
import glob
import logging
import os
import time
from typing import List, Optional

logger = logging.getLogger(__name__)

KINDS = ("kill", "hang", "sigterm", "ckpt_truncate")
_FIELDS = ("rank", "kind", "step", "seconds")


class InjectedKill(RuntimeError):
    """A deliberately killed worker (retryable, like the real thing)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    step: int
    rank: str = "*"          # "*" or the decimal rank
    seconds: float = 3600.0  # hang duration

    def matches(self, rank: int, step: int) -> bool:
        return self.step == step and (
            self.rank == "*" or int(self.rank) == rank)


def parse_fault_spec(spec: str) -> List[FaultSpec]:
    """Parse the FAULT_SPEC grammar; raises ValueError on anything it
    does not understand (a typo'd fault must fail the test loudly, not
    silently not-fire)."""
    out = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields = {}
        for part in entry.split(":"):
            if "=" not in part:
                raise ValueError(
                    f"FAULT_SPEC field {part!r} is not key=value "
                    f"(entry {entry!r})")
            k, v = part.split("=", 1)
            if k not in _FIELDS:
                raise ValueError(
                    f"FAULT_SPEC unknown field {k!r} (entry {entry!r}); "
                    f"known: {_FIELDS}")
            fields[k] = v
        if "kind" not in fields or "step" not in fields:
            raise ValueError(
                f"FAULT_SPEC entry {entry!r} needs kind= and step=")
        if fields["kind"] not in KINDS:
            raise ValueError(
                f"FAULT_SPEC unknown kind {fields['kind']!r}; "
                f"known: {KINDS}")
        rank = fields.get("rank", "*")
        if rank != "*":
            int(rank)  # fail fast on garbage
        out.append(FaultSpec(
            kind=fields["kind"], step=int(fields["step"]), rank=rank,
            seconds=float(fields.get("seconds", 3600.0))))
    return out


# process-global so an in-process retry attempt (which re-creates the
# injector from env) does not re-fire an already-fired fault; the
# marker file below extends the guarantee across worker processes
_FIRED = set()

MARKER_NAME = ".fault_spec_fired"


def reset_fired() -> None:
    _FIRED.clear()


class FaultInjector:
    """Step-boundary hook the train loop calls (``on_step``)."""

    def __init__(self, specs: List[FaultSpec], *, rank: int = 0,
                 ckpt_manager=None):
        self.specs = list(specs)
        self.rank = int(rank)
        self.ckpt_manager = ckpt_manager

    @staticmethod
    def from_env(rank: Optional[int] = None,
                 ckpt_manager=None) -> Optional["FaultInjector"]:
        """Injector from $FAULT_SPEC, or None when unset (the production
        default — zero overhead beyond this one env read)."""
        raw = os.environ.get("FAULT_SPEC", "").strip()
        if not raw:
            return None
        if rank is None:
            rank = int(os.environ.get("PROCESS_ID", "0"))
        return FaultInjector(parse_fault_spec(raw), rank=rank,
                             ckpt_manager=ckpt_manager)

    def bind_ckpt(self, ckpt_manager) -> None:
        if self.ckpt_manager is None:
            self.ckpt_manager = ckpt_manager

    def _marker_path(self) -> Optional[str]:
        if self.ckpt_manager is None:
            return None
        return os.path.join(str(self.ckpt_manager.directory), MARKER_NAME)

    def _marker_key(self, spec: FaultSpec) -> str:
        return f"rank{self.rank}:{spec.kind}@{spec.step}:match={spec.rank}"

    def _already_fired(self, spec: FaultSpec) -> bool:
        if (self.rank, spec) in _FIRED:
            return True
        path = self._marker_path()
        if path is None:
            return False
        try:
            with open(path) as f:
                return self._marker_key(spec) in f.read().splitlines()
        except OSError:  # no marker yet
            return False

    def _mark_fired(self, spec: FaultSpec) -> None:
        _FIRED.add((self.rank, spec))
        path = self._marker_path()
        if path is None:
            return
        try:
            # shared storage beside the checkpoints: a retried attempt
            # on a FRESH worker process (real Ray) must also see the
            # fault as spent
            with open(path, "a") as f:
                f.write(self._marker_key(spec) + "\n")
        except OSError as e:  # pragma: no cover - marker is best-effort
            logger.debug("could not persist fired-fault marker: %s", e)

    def on_step(self, step: int) -> None:
        for spec in self.specs:
            if spec.matches(self.rank, step) and \
                    not self._already_fired(spec):
                self._mark_fired(spec)
                self._fire(spec, step)

    def _fire(self, spec: FaultSpec, step: int) -> None:
        logger.warning("FAULT_SPEC firing kind=%s at step %d (rank %d)",
                       spec.kind, step, self.rank)
        if spec.kind == "kill":
            raise InjectedKill(
                f"injected kill at step {step} (rank {self.rank})")
        if spec.kind == "hang":
            time.sleep(spec.seconds)
        elif spec.kind == "sigterm":
            from gke_ray_train_tpu.train import preempt
            preempt.trigger()
        elif spec.kind == "ckpt_truncate":
            self._truncate_latest(step)

    def _truncate_latest(self, step: int) -> None:
        """Tear the newest checkpoint step the way an interrupted async
        save does: cut the largest data file in half. Restore of this
        step must subsequently fail (ckpt/manager.py falls back)."""
        mgr = self.ckpt_manager
        if mgr is None:
            raise RuntimeError(
                "FAULT_SPEC kind=ckpt_truncate needs a checkpoint "
                "manager bound to the injector (run with checkpointing "
                "enabled)")
        mgr.wait()  # the torn tail must be of a COMMITTED save
        latest = mgr.latest_step()
        if latest is None:
            raise RuntimeError(
                f"FAULT_SPEC ckpt_truncate at step {step}: no checkpoint "
                "saved yet (schedule the fault after a save step)")
        step_dir = os.path.join(str(mgr.directory), str(latest))
        files = [f for f in glob.glob(os.path.join(step_dir, "**", "*"),
                                      recursive=True) if os.path.isfile(f)]
        if not files:
            raise RuntimeError(f"ckpt_truncate: no files under {step_dir}")
        files.sort(key=os.path.getsize, reverse=True)
        target = files[0]
        size = os.path.getsize(target)
        with open(target, "r+b") as f:
            f.truncate(max(1, size // 2))
        logger.warning(
            "truncated %s (%d -> %d bytes): checkpoint step %d is now a "
            "corrupt tail", target, size, max(1, size // 2), latest)
