"""Deterministic fault injection — the testable half of fault tolerance.

None of the failure handling (retry-with-resume, graceful preemption,
corrupt-checkpoint fallback, heartbeat supervision) is trustworthy
unless a test can *cause* each failure at an exact step. ``FAULT_SPEC``
is that cause: an env/config grammar the train loop honors at every
step boundary, identical on the local path, the Ray worker path, and
the real multi-process harness (``tests/_multihost.py`` — the env
propagates to every worker process).

Grammar — ``;``-separated entries of ``:``-separated ``key=value``
fields::

    FAULT_SPEC="rank=1:kind=kill:step=5;rank=*:kind=sigterm:step=8"

Fields:

- ``kind`` (required): ``kill`` (raise — the worker process dies),
  ``hang`` (sleep ``seconds`` — a wedged collective), ``sigterm``
  (deliver a preemption, ``train/preempt.py``), ``ckpt_truncate``
  (corrupt the newest checkpoint step on disk — an interrupted async
  save's torn tail), ``pool_shrink`` (the spot pool changes to ``to``
  devices: the pool registry records the new size and a preemption
  carrying it is delivered — the elastic shrink/grow drill,
  ``rayint/trainer.py``), ``slice_evict`` (one whole slice is evicted:
  like ``pool_shrink`` but the surviving count is derived from the
  slice layout — ``parallel/mesh.py::slice_assignments`` — and the
  evicted slice is named), ``kill_during_commit`` (the worker dies
  while the async checkpoint committer is mid-commit: the in-flight
  commit is frozen in its COMMITTING-without-COMMITTED state via
  ``ckpt/manager.py::tear_mid_commit`` and the worker is killed — the
  write-ahead recovery drill; requires an ``ASYNC_CKPT=1`` manager).
- ``step`` (required int): global step AFTER which the fault fires
  (the loop calls ``on_step`` once per completed step).
- ``rank`` (int or ``*``, default ``*``): which worker fires it.
- ``seconds`` (float, ``hang`` only, default 3600): hang duration —
  finite so an undetected hang still ends, but far beyond any
  reasonable ``HEARTBEAT_TIMEOUT_S``.
- ``to`` (int, ``pool_shrink`` only, required): the surviving device
  count. A ``to`` LARGER than the current pool is a *grow* event (the
  nodepool returned) — same grammar, classified by comparison.
- ``slice`` (int, ``slice_evict`` only, default: the last slice): which
  slice the eviction removes.

Each entry fires at most once per RUN, mirroring a real one-shot
hardware event: the fired-registry is module-global (an in-process
retry — the ``JaxTrainer`` local path — does not re-fire) AND, when a
checkpoint manager is bound, persisted as a marker file beside the
checkpoints — so on a real Ray cluster, where every retry attempt is a
FRESH actor process that re-reaches the fault step after resume, the
fault still fires exactly once. Tests call :func:`reset_fired` between
cases (fresh tmp checkpoint dirs take care of the marker file).
"""

from __future__ import annotations

import dataclasses
import glob
import logging
import os
import time
from typing import List, Optional

logger = logging.getLogger(__name__)

KINDS = ("kill", "hang", "sigterm", "ckpt_truncate", "pool_shrink",
         "slice_evict", "kill_during_commit")
_FIELDS = ("rank", "kind", "step", "seconds", "to", "slice")


class InjectedKill(RuntimeError):
    """A deliberately killed worker (retryable, like the real thing)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    step: int
    rank: str = "*"          # "*" or the decimal rank
    seconds: float = 3600.0  # hang duration
    to: Optional[int] = None       # pool_shrink: surviving device count
    slice: Optional[int] = None    # slice_evict: which slice dies

    def matches(self, rank: int, step: int) -> bool:
        return self.step == step and (
            self.rank == "*" or int(self.rank) == rank)


def parse_fault_spec(spec: str) -> List[FaultSpec]:
    """Parse the FAULT_SPEC grammar; raises ValueError on anything it
    does not understand (a typo'd fault must fail the test loudly, not
    silently not-fire)."""
    out = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields = {}
        for part in entry.split(":"):
            if "=" not in part:
                raise ValueError(
                    f"FAULT_SPEC field {part!r} is not key=value "
                    f"(entry {entry!r})")
            k, v = part.split("=", 1)
            if k not in _FIELDS:
                raise ValueError(
                    f"FAULT_SPEC unknown field {k!r} (entry {entry!r}); "
                    f"known: {_FIELDS}")
            fields[k] = v
        if "kind" not in fields or "step" not in fields:
            raise ValueError(
                f"FAULT_SPEC entry {entry!r} needs kind= and step=")
        if fields["kind"] not in KINDS:
            raise ValueError(
                f"FAULT_SPEC unknown kind {fields['kind']!r}; "
                f"known: {KINDS}")
        rank = fields.get("rank", "*")
        if rank != "*":
            int(rank)  # fail fast on garbage
        if fields["kind"] == "pool_shrink" and "to" not in fields:
            raise ValueError(
                f"FAULT_SPEC kind=pool_shrink needs to=<surviving "
                f"device count> (entry {entry!r})")
        for f, kinds in (("to", ("pool_shrink",)),
                         ("slice", ("slice_evict",)),
                         ("seconds", ("hang",))):
            if f in fields and fields["kind"] not in kinds:
                raise ValueError(
                    f"FAULT_SPEC field {f}= only applies to kind in "
                    f"{kinds} (entry {entry!r})")
        out.append(FaultSpec(
            kind=fields["kind"], step=int(fields["step"]), rank=rank,
            seconds=float(fields.get("seconds", 3600.0)),
            to=int(fields["to"]) if "to" in fields else None,
            slice=int(fields["slice"]) if "slice" in fields else None))
    return out


# process-global so an in-process retry attempt (which re-creates the
# injector from env) does not re-fire an already-fired fault; the
# marker file below extends the guarantee across worker processes
_FIRED = set()

MARKER_NAME = ".fault_spec_fired"


def reset_fired() -> None:
    _FIRED.clear()


# ---------------------------------------------------------------------------
# emulated device pool — the infrastructure state behind pool faults
# ---------------------------------------------------------------------------

# current emulated pool size (None = the full physical pool). Unlike
# the fired-fault registry this is INFRASTRUCTURE state, not per-attempt
# state: a shrunken pool stays shrunken across retries until a grow
# event, exactly like a real spot nodepool. Persisted beside the
# checkpoints so a fresh Ray worker process sees the same pool.
_POOL: Optional[int] = None

POOL_MARKER_NAME = ".elastic_pool"


def set_pool(n_devices: int, ckpt_manager=None) -> None:
    """Record the emulated pool size (and persist it beside the
    checkpoints when a manager is bound)."""
    global _POOL
    _POOL = int(n_devices)
    if ckpt_manager is None:
        return
    try:
        with open(os.path.join(str(ckpt_manager.directory),
                               POOL_MARKER_NAME), "w") as f:
            f.write(str(_POOL))
    except OSError as e:  # pragma: no cover - marker is best-effort
        logger.debug("could not persist pool marker: %s", e)


def current_pool(ckpt_dir: Optional[str] = None) -> Optional[int]:
    """The emulated pool size: in-process registry first, then the
    persisted marker (fresh worker processes), else None (= full pool).
    This is what the trainer's post-mortem probes after a failure whose
    exception carried no pool notice."""
    if _POOL is not None:
        return _POOL
    if ckpt_dir:
        path = os.path.join(str(ckpt_dir), POOL_MARKER_NAME)
        try:
            with open(path) as f:
                return int(f.read().strip())
        except FileNotFoundError:
            pass  # no marker: the pool was never shrunk
        except (OSError, ValueError) as e:
            # present but unreadable is NOT "full pool": a torn or
            # permission-broken marker means the real pool size is
            # indeterminate, and silently returning None here would
            # make the trainer re-form the mesh on devices that may
            # not exist — fail loudly instead
            raise RuntimeError(
                f"elastic pool marker {path} exists but is unreadable "
                f"({type(e).__name__}: {e}); refusing to assume the "
                "full pool — repair or remove the marker") from e
    return None


def reset_pool() -> None:
    global _POOL
    _POOL = None


class FaultInjector:
    """Step-boundary hook the train loop calls (``on_step``)."""

    def __init__(self, specs: List[FaultSpec], *, rank: int = 0,
                 ckpt_manager=None):
        self.specs = list(specs)
        self.rank = int(rank)
        self.ckpt_manager = ckpt_manager

    @staticmethod
    def from_env(rank: Optional[int] = None,
                 ckpt_manager=None) -> Optional["FaultInjector"]:
        """Injector from $FAULT_SPEC, or None when unset (the production
        default — zero overhead beyond this one env read)."""
        raw = os.environ.get("FAULT_SPEC", "").strip()
        if not raw:
            return None
        if rank is None:
            rank = int(os.environ.get("PROCESS_ID", "0"))
        return FaultInjector(parse_fault_spec(raw), rank=rank,
                             ckpt_manager=ckpt_manager)

    def bind_ckpt(self, ckpt_manager) -> None:
        if self.ckpt_manager is None:
            self.ckpt_manager = ckpt_manager

    def _marker_path(self) -> Optional[str]:
        if self.ckpt_manager is None:
            return None
        return os.path.join(str(self.ckpt_manager.directory), MARKER_NAME)

    def _marker_key(self, spec: FaultSpec) -> str:
        key = f"rank{self.rank}:{spec.kind}@{spec.step}:match={spec.rank}"
        if spec.to is not None:
            key += f":to={spec.to}"
        if spec.slice is not None:
            key += f":slice={spec.slice}"
        return key

    def _already_fired(self, spec: FaultSpec) -> bool:
        if (self.rank, spec) in _FIRED:
            return True
        path = self._marker_path()
        if path is None:
            return False
        try:
            with open(path) as f:
                text = f.read()
        except FileNotFoundError:  # no marker yet
            return False
        except OSError:
            # present but unreadable: the at-most-once guarantee is
            # the one that must hold (a fault double-fired on resume
            # breaks every recovery drill), so err on "already fired"
            logger.warning("fired-fault marker %s is unreadable; "
                           "treating every fault as already fired",
                           path)
            return True
        key = self._marker_key(spec)
        lines = text.splitlines()
        if key in lines:
            return True
        # torn tail: the attempt that fired this fault was KILLED
        # mid-append (the usual sequel to firing a kill fault), leaving
        # a final line that is a strict prefix of the key. That fault
        # DID fire — re-firing it would loop the drill forever
        if text and not text.endswith("\n") and lines:
            tail = lines[-1]
            if tail and key.startswith(tail):
                return True
        return False

    def _mark_fired(self, spec: FaultSpec) -> None:
        _FIRED.add((self.rank, spec))
        path = self._marker_path()
        if path is None:
            return
        try:
            # shared storage beside the checkpoints: a retried attempt
            # on a FRESH worker process (real Ray) must also see the
            # fault as spent
            with open(path, "a") as f:
                f.write(self._marker_key(spec) + "\n")
        except OSError as e:  # pragma: no cover - marker is best-effort
            logger.debug("could not persist fired-fault marker: %s", e)

    def on_step(self, step: int) -> None:
        for spec in self.specs:
            if spec.matches(self.rank, step) and \
                    not self._already_fired(spec):
                self._mark_fired(spec)
                self._fire(spec, step)

    def _fire(self, spec: FaultSpec, step: int) -> None:
        logger.warning("FAULT_SPEC firing kind=%s at step %d (rank %d)",
                       spec.kind, step, self.rank)
        if spec.kind == "kill":
            self._evict_all_hot()
            raise InjectedKill(
                f"injected kill at step {step} (rank {self.rank})")
        if spec.kind == "kill_during_commit":
            self._kill_during_commit(step)
        if spec.kind == "hang":
            time.sleep(spec.seconds)
        elif spec.kind == "sigterm":
            from gke_ray_train_tpu.train import preempt
            preempt.trigger()
        elif spec.kind == "ckpt_truncate":
            self._truncate_latest(step)
        elif spec.kind == "pool_shrink":
            self._pool_change(spec.to, step, reason="pool_shrink")
        elif spec.kind == "slice_evict":
            survivors, evicted = self._slice_evict_target(spec)
            # the eviction kills that slice's host memory: its peer
            # hot-state slot dies with it (the survivor's slot — holding
            # the evicted slice's replica — is what the resume reads)
            peer = getattr(self.ckpt_manager, "peer", None)
            if peer is not None and self.ckpt_manager is not None:
                peer.evict_slice(str(self.ckpt_manager.directory),
                                 evicted)
            self._pool_change(survivors, step,
                              reason=f"slice_evict:slice={evicted}")

    def _pool_change(self, n_devices: int, step: int,
                     reason: str) -> None:
        """A pool-change notice, delivered the way the platform would:
        the surviving pool size lands in the registry (infrastructure
        state — it outlives the attempt) and a preemption carrying it
        is requested, so the loop grace-saves and the trainer's
        post-mortem re-forms the mesh on the survivors instead of
        burning a failure-budget slot."""
        set_pool(n_devices, self.ckpt_manager)
        from gke_ray_train_tpu.train import preempt
        preempt.request(source=f"{reason}@step{step}", pool=n_devices)

    def _slice_evict_target(self, spec: FaultSpec):
        """(surviving device count, evicted slice index) for a
        slice_evict fault — slice identity per the slice_index contract
        (``parallel/mesh.py::slice_assignments``; NUM_SLICES drives the
        emulated layout on fake/CPU devices)."""
        import jax

        from gke_ray_train_tpu.parallel.mesh import slice_assignments
        devices = jax.devices()
        # default 1 like every other slice_index consumer — an unset
        # NUM_SLICES is a single-domain pool, and evicting its only
        # slice errors loudly below instead of fabricating a layout
        num_slices = int(os.environ.get("NUM_SLICES", "1"))
        assign = slice_assignments(devices, num_slices)
        evicted = spec.slice if spec.slice is not None else max(assign)
        if evicted not in assign:
            raise RuntimeError(
                f"FAULT_SPEC slice_evict: slice {evicted} does not "
                f"exist (slices present: {sorted(set(assign))})")
        survivors = sum(1 for s in assign if s != evicted)
        if survivors == 0:
            raise RuntimeError(
                "FAULT_SPEC slice_evict would evict the ENTIRE pool — "
                "use kind=sigterm for a whole-job eviction")
        return survivors, evicted

    def _evict_all_hot(self) -> None:
        """A kill models the WHOLE emulated job dying: every slice's
        memory — and with it every peer hot-state slot — is gone, and
        only storage survives into the retry. (``slice_evict`` is the
        one fault that leaves a living holder.) Without this, the
        in-process retry would 'restore from peer' memory that no
        longer exists on a real cluster."""
        mgr = self.ckpt_manager
        if mgr is not None and getattr(mgr, "peer", None) is not None:
            from gke_ray_train_tpu.ckpt import peer as peer_hot
            peer_hot.reset(str(mgr.directory))

    def _kill_during_commit(self, step: int) -> None:
        """The async-checkpointing recovery drill: freeze the in-flight
        commit in its mid-commit on-disk state (COMMITTING without
        COMMITTED — ``ckpt/manager.py::tear_mid_commit``), then die.
        The resumed attempt must treat the torn step as never saved."""
        mgr = self.ckpt_manager
        if mgr is None:
            raise RuntimeError(
                "FAULT_SPEC kind=kill_during_commit needs a checkpoint "
                "manager bound to the injector (run with checkpointing "
                "enabled)")
        if not getattr(mgr, "async_commit", False):
            raise RuntimeError(
                "FAULT_SPEC kind=kill_during_commit requires an "
                "async-commit checkpoint manager (ASYNC_CKPT=1) — the "
                "sync save path has no background commit window to "
                "kill inside")
        torn = mgr.tear_mid_commit()
        self._evict_all_hot()
        raise InjectedKill(
            f"injected kill during commit of step {torn} "
            f"(fired at step {step}, rank {self.rank})")

    def _truncate_latest(self, step: int) -> None:
        """Tear the newest checkpoint step the way an interrupted async
        save does: cut the largest data file in half. Restore of this
        step must subsequently fail (ckpt/manager.py falls back)."""
        mgr = self.ckpt_manager
        if mgr is None:
            raise RuntimeError(
                "FAULT_SPEC kind=ckpt_truncate needs a checkpoint "
                "manager bound to the injector (run with checkpointing "
                "enabled)")
        mgr.wait()  # the torn tail must be of a COMMITTED save
        latest = mgr.latest_step()
        if latest is None:
            raise RuntimeError(
                f"FAULT_SPEC ckpt_truncate at step {step}: no checkpoint "
                "saved yet (schedule the fault after a save step)")
        step_dir = os.path.join(str(mgr.directory), str(latest))
        files = [f for f in glob.glob(os.path.join(step_dir, "**", "*"),
                                      recursive=True) if os.path.isfile(f)]
        if not files:
            raise RuntimeError(f"ckpt_truncate: no files under {step_dir}")
        files.sort(key=os.path.getsize, reverse=True)
        target = files[0]
        size = os.path.getsize(target)
        with open(target, "r+b") as f:
            f.truncate(max(1, size // 2))
        logger.warning(
            "truncated %s (%d -> %d bytes): checkpoint step %d is now a "
            "corrupt tail", target, size, max(1, size // 2), latest)
