"""The shared decoder-only transformer core (all model families).

Functional, pytree-first: ``init_params`` builds the weights,
``param_specs`` builds the matching PartitionSpec tree, ``forward`` is a
pure jittable function. Layers are *stacked* ([n_repeats, ...] leading dim)
and iterated with ``lax.scan`` so a 32-80 layer model traces/compiles one
block body instead of unrolling — the XLA-idiomatic replacement for the
reference's python ``nn.TransformerEncoder`` module stack
(ray-jobs/pytorch_llm_ray.py:86-90).

Sharding (SURVEY.md §2c, TPU build disposition):
- FSDP: every matrix's d_model-ish dim sharded over ``fsdp``.
- TP: head / ffn-hidden dims sharded over ``model``.
- Activations: batch over (data, fsdp), sequence over ``context``.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.ops.attention import (
    dot_product_attention, make_attention_mask)
from gke_ray_train_tpu.ops.norms import rms_norm
from gke_ray_train_tpu.ops.rope import (
    apply_rope, rope_frequencies, sinusoidal_positions)
from gke_ray_train_tpu.parallel.mesh import AXIS_CONTEXT, BATCH_AXES

Params = Dict[str, Any]

logger = logging.getLogger(__name__)
def _warn_flash_fallback(seq_len: int) -> None:
    """Once per sequence length (trace-time, not per step)."""
    from gke_ray_train_tpu.logging_utils import warn_once
    warn_once(logger, ("flash_fallback", seq_len),
              "attn_impl='flash' but seq_len=%d is not a 128 multiple — "
              "falling back to the O(S^2) dense-mask XLA path; pad the "
              "sequence to a 128 multiple to keep the kernel", seq_len)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Initialize the stacked param pytree.

    Truncated-normal fan-in style init; the two residual-writing matrices
    (wo, w_down) are scaled down by 1/sqrt(2*n_layers) to keep the
    residual-stream variance flat at depth.
    """
    pdt = jnp.dtype(cfg.param_dtype)
    hd = cfg.resolved_head_dim
    D, F, H, K, R = (cfg.d_model, cfg.d_ff, cfg.n_heads, cfg.n_kv_heads,
                     cfg.n_repeats)
    depth_scale = 1.0 / math.sqrt(2 * cfg.n_layers)

    keys = iter(jax.random.split(key, 16 * len(cfg.block_pattern) + 4))

    def normal(shape, std):
        return (jax.random.truncated_normal(next(keys), -3, 3, shape,
                                            jnp.float32) * std).astype(pdt)

    E = cfg.n_experts

    def block_params():
        std = 0.02
        p = {
            "attn_norm": jnp.zeros((R, D), pdt) if cfg.norm_scale_plus_one
            else jnp.ones((R, D), pdt),
            "wq": normal((R, D, H * hd), std),
            "wk": normal((R, D, K * hd), std),
            "wv": normal((R, D, K * hd), std),
            "wo": normal((R, H * hd, D), std * depth_scale),
            "mlp_norm": jnp.zeros((R, D), pdt) if cfg.norm_scale_plus_one
            else jnp.ones((R, D), pdt),
        }
        if cfg.attn_qkv_bias:
            # Qwen-2: bias on q/k/v only (o_proj stays bias-free);
            # zero-init — real values come from the HF checkpoint
            p["bq"] = jnp.zeros((R, H * hd), pdt)
            p["bk"] = jnp.zeros((R, K * hd), pdt)
            p["bv"] = jnp.zeros((R, K * hd), pdt)
        if E:
            # MoE MLP (ops/moe.py): router + expert bank, expert dim
            # sharded over `model` (expert parallelism, SURVEY.md EP row)
            p["router"] = normal((R, D, E), std)
            p["w_gate"] = normal((R, E, D, F), std)
            p["w_up"] = normal((R, E, D, F), std)
            p["w_down"] = normal((R, E, F, D), std * depth_scale)
        else:
            p["w_gate"] = normal((R, D, F), std)
            p["w_up"] = normal((R, D, F), std)
            p["w_down"] = normal((R, F, D), std * depth_scale)
        if cfg.post_block_norm:
            zero_or_one = (jnp.zeros if cfg.norm_scale_plus_one else jnp.ones)
            p["attn_post_norm"] = zero_or_one((R, D), pdt)
            p["mlp_post_norm"] = zero_or_one((R, D), pdt)
        return p

    params: Params = {
        "embed": normal((cfg.vocab_size, D), 0.02),
        "blocks": [block_params() for _ in cfg.block_pattern],
        "final_norm": (jnp.zeros((D,), pdt) if cfg.norm_scale_plus_one
                       else jnp.ones((D,), pdt)),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal((D, cfg.vocab_size), 0.02)
    return params


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree matching init_params exactly.

    The ZeRO/FSDP sharding the reference gets from bitsandbytes+DDP
    (SURVEY.md rows D4/D5) is this table; nothing else.
    """
    def block_specs():
        # Leading dim = stacked repeats: sharded over `pipe` (pipeline
        # stages own contiguous layer slices, models/pipeline.py); a
        # size-1 pipe axis makes this a no-op on non-PP meshes.
        s = {
            "attn_norm": P("pipe", None),
            "wq": P("pipe", "fsdp", "model"),
            "wk": P("pipe", "fsdp", "model"),
            "wv": P("pipe", "fsdp", "model"),
            "wo": P("pipe", "model", "fsdp"),
            "mlp_norm": P("pipe", None),
        }
        if cfg.attn_qkv_bias:
            # bias vectors follow their projection's OUTPUT dim sharding
            s["bq"] = P("pipe", "model")
            s["bk"] = P("pipe", "model")
            s["bv"] = P("pipe", "model")
        if cfg.n_experts:
            # expert dim over `model` = EP; GSPMD derives the token
            # all-to-alls from the dispatch einsums (ops/moe.py)
            s["router"] = P("pipe", "fsdp", None)
            s["w_gate"] = P("pipe", "model", "fsdp", None)
            s["w_up"] = P("pipe", "model", "fsdp", None)
            s["w_down"] = P("pipe", "model", None, "fsdp")
        else:
            s["w_gate"] = P("pipe", "fsdp", "model")
            s["w_up"] = P("pipe", "fsdp", "model")
            s["w_down"] = P("pipe", "model", "fsdp")
        if cfg.post_block_norm:
            s["attn_post_norm"] = P("pipe", None)
            s["mlp_post_norm"] = P("pipe", None)
        return s

    specs: Params = {
        "embed": P("model", "fsdp"),
        "blocks": [block_specs() for _ in cfg.block_pattern],
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "model")
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _constrain(x, mesh: Optional[Mesh], *spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _proj(x, w, lora_p, lora_scale, dtype, drop_rng=None, drop_rate=0.0,
          bias=None):
    """x @ w (+ bias), plus the low-rank LoRA bypass when adapters are
    present. ``bias``: optional [d_out] projection bias (Qwen-2 q/k/v).

    The LoRA path is two small matmuls (never a materialized delta-W) —
    the TPU-native replacement for peft's adapter modules (reference:
    ray-jobs/fine_tune_llama_ray.py:245-252, SURVEY.md row D6). ``w``
    may be a quantized QTensor (QLoRA base weights, SURVEY.md row D5) —
    dequantized here, in-jit, so XLA fuses it into the matmul prologue.

    ``drop_rng``/``drop_rate``: LoRA dropout (reference LORA_DROPOUT,
    fine_tune_config.json:32) — peft semantics: dropout on the *adapter
    branch input only*, the frozen-base path never drops.
    """
    # local import: ops.quant -> train.lora -> models.transformer is a
    # module-level chain, so this reverse edge must stay deferred
    from gke_ray_train_tpu.ops.quant import maybe_dequantize
    y = jnp.einsum("bsd,dh->bsh", x, maybe_dequantize(w, dtype))
    if lora_p is not None:
        xl = x
        if drop_rng is not None and drop_rate > 0.0:
            keep = 1.0 - drop_rate
            mask = jax.random.bernoulli(drop_rng, keep, x.shape)
            xl = jnp.where(mask, x / keep, jnp.zeros((), dtype)).astype(dtype)
        if lora_p["a"].ndim == 3:
            # per-row adapters, already gathered from a stacked
            # multi-tenant pool ([B, d_in, r] / [B, r, d_out]) — the
            # serving engine's batched multi-LoRA path
            from gke_ray_train_tpu.ops.lora_batched import bgmv
            y = y + bgmv(xl, lora_p["a"], lora_p["b"],
                         scale=lora_scale, dtype=dtype)
        else:
            xa = jnp.einsum("bsd,dr->bsr", xl, lora_p["a"].astype(dtype))
            y = y + jnp.einsum("bsr,rh->bsh", xa,
                               lora_p["b"].astype(dtype)) \
                * jnp.asarray(lora_scale, dtype)
    if bias is not None:
        y = y + bias.astype(dtype)
    return y


def _lora_entry(lora_p, name):
    return None if lora_p is None or name not in lora_p else lora_p[name]


def _drop_key(rng, tag: int):
    return None if rng is None else jax.random.fold_in(rng, tag)


def _rms_norm(x, scale, *, eps, scale_plus_one, fused_ops=False,
              mesh=None):
    """rms_norm, optionally through the fused Pallas kernel (plan knob
    ``FUSED_OPS``). The fused path is oracle-pinned in the kernelcheck
    tolerance ledger, not bitwise vs the XLA chain. ``mesh`` must ride
    along on GSPMD call sites: a pallas_call has no SPMD partitioning
    rule, so under a mesh the kernel is shard_map-wrapped (the flash
    dispatch discipline)."""
    if fused_ops:
        from gke_ray_train_tpu.ops.fused_norm_rope import fused_rmsnorm
        return fused_rmsnorm(x, scale, eps=eps,
                             scale_plus_one=scale_plus_one, mesh=mesh)
    return rms_norm(x, scale, eps=eps, scale_plus_one=scale_plus_one)


def _apply_rope_qk(q, k, positions, rope, fused_ops=False, mesh=None):
    """RoPE on the projected q AND k — one fused Pallas launch when the
    plan asks for it (shard_map-wrapped under a mesh), else the two
    separate ops/rope.py dispatches."""
    if fused_ops:
        from gke_ray_train_tpu.ops.fused_norm_rope import fused_rope_qk
        return fused_rope_qk(q, k, positions, rope, mesh=mesh)
    return apply_rope(q, positions, rope), apply_rope(k, positions, rope)


def _mlp(x, lp, cfg: ModelConfig, dtype, lora_p=None, lora_scale=1.0,
         drop_rng=None, drop_rate=0.0):
    def lr(name):
        return _lora_entry(lora_p, name)
    gate = _proj(x, lp["w_gate"], lr("w_gate"), lora_scale, dtype,
                 _drop_key(drop_rng, 4), drop_rate)
    up = _proj(x, lp["w_up"], lr("w_up"), lora_scale, dtype,
               _drop_key(drop_rng, 5), drop_rate)
    if cfg.activation == "silu":
        act = jax.nn.silu(gate)
    elif cfg.activation == "gelu_tanh":
        act = jax.nn.gelu(gate, approximate=True)
    else:
        raise ValueError(f"unknown activation {cfg.activation}")
    return _proj(act * up, lp["w_down"], lr("w_down"), lora_scale, dtype,
                 _drop_key(drop_rng, 6), drop_rate)


def _attn(x, lp, cfg: ModelConfig, impl, dtype, rope, positions, mask,
          window, segment_ids, mesh, lora_p=None, lora_scale=1.0,
          drop_rng=None, drop_rate=0.0, fused_ops=False):
    B, S, D = x.shape
    hd = cfg.resolved_head_dim
    H, K = cfg.n_heads, cfg.n_kv_heads

    def lr(name):
        return _lora_entry(lora_p, name)
    q = _proj(x, lp["wq"], lr("wq"), lora_scale, dtype,
              _drop_key(drop_rng, 0), drop_rate, bias=lp.get("bq"))
    k = _proj(x, lp["wk"], lr("wk"), lora_scale, dtype,
              _drop_key(drop_rng, 1), drop_rate, bias=lp.get("bk"))
    v = _proj(x, lp["wv"], lr("wv"), lora_scale, dtype,
              _drop_key(drop_rng, 2), drop_rate, bias=lp.get("bv"))
    q = q.reshape(B, S, H, hd)
    k = k.reshape(B, S, K, hd)
    v = v.reshape(B, S, K, hd)
    q = _constrain(q, mesh, BATCH_AXES, AXIS_CONTEXT, "model", None)
    k = _constrain(k, mesh, BATCH_AXES, AXIS_CONTEXT, "model", None)
    if rope is not None:
        q, k = _apply_rope_qk(q, k, positions, rope,
                              fused_ops=fused_ops, mesh=mesh)
    if impl == "xla":
        out = dot_product_attention(
            q, k, v, mask, scale=cfg.attn_scale,
            logit_softcap=cfg.attn_softcap)
    else:
        # flash (pallas) / ring (context-parallel) kernels take the mask
        # *inputs*, never a materialized [S, S] mask
        from gke_ray_train_tpu.ops.dispatch import attention_dispatch
        out = attention_dispatch(
            impl, q, k, v,
            q_positions=positions, kv_positions=positions,
            q_segment_ids=segment_ids, kv_segment_ids=segment_ids,
            causal=True, sliding_window=window, scale=cfg.attn_scale,
            logit_softcap=cfg.attn_softcap, mesh=mesh)
    out = out.reshape(B, S, H * hd)
    return _proj(out, lp["wo"], lr("wo"), lora_scale, dtype,
                 _drop_key(drop_rng, 3), drop_rate)


def run_block_stack(x, aux, layer_slice, cfg: ModelConfig, impl, dtype,
                    rope, positions, masks, segment_ids, mesh, *,
                    lora_slice=None, lora_scale: float = 1.0,
                    lora_dropout: float = 0.0, rep_rng=None,
                    token_weights=None, fused_ops: bool = False):
    """One repeat of the stacked block pattern — the body every layer
    loop shares. ``forward``'s scan and the manual-overlap pipeline
    (train/overlap.py) both call exactly this function, so the per-layer
    math cannot fork between the GSPMD and shard_map paths (the bitwise
    off/manual equivalence the overlap tests assert rides on that)."""
    eps, sp1 = cfg.norm_eps, cfg.norm_scale_plus_one
    moe = cfg.n_experts > 0
    for p, kind in enumerate(cfg.block_pattern):
        lp = layer_slice[p]
        lo = lora_slice[p] if lora_slice is not None else None
        drng = (jax.random.fold_in(rep_rng, p)
                if rep_rng is not None else None)
        h = _rms_norm(x, lp["attn_norm"], eps=eps, scale_plus_one=sp1,
                      fused_ops=fused_ops, mesh=mesh)
        h = _attn(h, lp, cfg, impl, dtype, rope, positions,
                  masks[kind],
                  cfg.sliding_window if kind == "sliding" else None,
                  segment_ids, mesh, lora_p=lo, lora_scale=lora_scale,
                  drop_rng=_drop_key(drng, 0), drop_rate=lora_dropout,
                  fused_ops=fused_ops)
        if cfg.post_block_norm:
            h = _rms_norm(h, lp["attn_post_norm"], eps=eps,
                          scale_plus_one=sp1, fused_ops=fused_ops,
                          mesh=mesh)
        x = x + h
        x = _constrain(x, mesh, BATCH_AXES, AXIS_CONTEXT, None)
        h = _rms_norm(x, lp["mlp_norm"], eps=eps, scale_plus_one=sp1,
                      fused_ops=fused_ops, mesh=mesh)
        if moe:
            # MoE MLP (ops/moe.py). LoRA adapts attention only on
            # MoE models — there is no single delta-W an adapter
            # pair could target across routed experts.
            from gke_ray_train_tpu.ops.moe import moe_mlp
            h, a = moe_mlp(h, lp["router"], lp["w_gate"], lp["w_up"],
                           lp["w_down"], cfg, dtype,
                           weights=token_weights)
            aux = aux + a
        else:
            h = _mlp(h, lp, cfg, dtype, lora_p=lo,
                     lora_scale=lora_scale,
                     drop_rng=_drop_key(drng, 1),
                     drop_rate=lora_dropout)
        if cfg.post_block_norm:
            h = _rms_norm(h, lp["mlp_post_norm"], eps=eps,
                          scale_plus_one=sp1, fused_ops=fused_ops,
                          mesh=mesh)
        x = x + h
        x = _constrain(x, mesh, BATCH_AXES, AXIS_CONTEXT, None)
    return x, aux


def resolve_seq_impl(cfg: ModelConfig, mesh, S: int) -> str:
    """The attention impl a sequence of length S actually runs — the
    pipe-mesh remap plus the S % 128 dense fallback ``forward`` applies
    (shared with train/overlap.py so both paths fall back identically)."""
    pipe_n = 1
    if mesh is not None and "pipe" in mesh.shape:
        pipe_n = int(mesh.shape["pipe"])
    impl = cfg.resolved_attn_impl
    if pipe_n > 1 and impl in ("ring", "a2a") \
            and mesh.shape[AXIS_CONTEXT] == 1:
        impl = "flash"
    if impl == "flash" and S % 128 != 0:
        _warn_flash_fallback(S)
        impl = "xla"
    return impl


def forward(params: Params, tokens: jnp.ndarray, cfg: ModelConfig, *,
            positions: Optional[jnp.ndarray] = None,
            segment_ids: Optional[jnp.ndarray] = None,
            mesh: Optional[Mesh] = None,
            lora: Optional[Params] = None,
            lora_scale: float = 1.0,
            lora_dropout: float = 0.0,
            lora_rng: Optional[jax.Array] = None,
            pipe_microbatches: Optional[int] = None,
            with_aux: bool = False,
            token_weights: Optional[jnp.ndarray] = None,
            fused_ops: bool = False,
            return_pre_unembed: bool = False):
    """tokens [B, S] int32 → logits [B, S, vocab] float32.

    ``lora``: optional adapter pytree from train/lora.py (same block
    structure as params, leaves {"a","b"}); base weights stay frozen —
    the caller decides what is trainable via the grad argnum/mask.

    ``lora_dropout``/``lora_rng``: adapter-input dropout (reference
    LORA_DROPOUT). Active only when BOTH are given — inference and merge
    paths pass neither, so they stay deterministic.

    ``pipe_microbatches``: pipeline microbatch count when the mesh has a
    ``pipe`` axis > 1 (models/pipeline.py); defaults to the stage count.

    ``with_aux``: return ``(logits, {"router_aux": scalar})`` — the mean
    per-layer Switch load-balance loss (MoE models; 0.0 for dense). The
    train step requests it when cfg.n_experts > 0.

    ``token_weights`` (optional [B, S]): passed to the MoE router aux so
    load balance is computed over REAL tokens, not padding (the train
    step passes the loss weights; ADVICE r4). Ignored by dense models.

    ``fused_ops``: route the rms_norm / rope epilogues through the
    fused Pallas kernels (plan knob ``FUSED_OPS``; tolerance-pinned,
    not bitwise vs the XLA dispatches).

    ``return_pre_unembed``: return the final-normed hidden state
    [B, S, D] instead of logits — the fused cross-entropy path
    (ops/fused_ce.py) consumes it so the [B, S, V] logits are never
    materialized in HBM.
    """
    B, S = tokens.shape
    dtype = jnp.dtype(cfg.dtype)

    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

    # NOTE on the SPMD "involuntary full rematerialization" warning this
    # gather triggers on (fsdp x model) meshes: the table is stored
    # P("model", "fsdp") so the output comes out D-sharded-over-fsdp and
    # must reshard to batch-over-fsdp (the constraint below); XLA's
    # fallback replicates ONE microbatch activation [B,S,D] per forward
    # (~0.1% of an 8B step). The alternatives are worse: replicating the
    # table costs ~1 GB of ICI per step at 8B, and a one-hot-matmul
    # embedding materializes [B,S,V]. Benign — do not "fix" blindly.
    x = params["embed"].astype(dtype)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), dtype)
    if cfg.positional == "sinusoidal":
        table = jnp.asarray(sinusoidal_positions(cfg.max_seq_len, cfg.d_model))
        x = x + table.astype(dtype)[positions]
        rope = None
    else:
        rope = jnp.asarray(rope_frequencies(
            cfg.resolved_head_dim, theta=cfg.rope_theta,
            llama3_scaling=cfg.rope_scaling))
    x = _constrain(x, mesh, BATCH_AXES, AXIS_CONTEXT, None)

    pipe_n = 1
    if mesh is not None and "pipe" in mesh.shape:
        pipe_n = int(mesh.shape["pipe"])

    # pipe remap (ring/a2a on context=1 pipelined meshes equal flash)
    # plus the loud S % 128 dense fallback — shared with the manual
    # overlap path so both fall back identically
    impl = resolve_seq_impl(cfg, mesh, S)

    if pipe_n > 1:
        # pipeline-parallel block stack (models/pipeline.py); falls
        # through to the shared final-norm/unembed tail below
        if lora is not None and lora_rng is not None and lora_dropout > 0.0:
            raise NotImplementedError(
                "LoRA dropout is not supported on a pipelined mesh; set "
                "LORA_DROPOUT=0 or pipe=1")
        from gke_ray_train_tpu.models.pipeline import pipeline_blocks
        x, pipe_aux = pipeline_blocks(
            x, params["blocks"], cfg, mesh, impl=impl, dtype=dtype,
            rope=rope, positions=positions, segment_ids=segment_ids,
            lora_blocks=lora["blocks"] if lora is not None else None,
            lora_scale=lora_scale, n_microbatches=pipe_microbatches,
            token_weights=token_weights)
        if return_pre_unembed:
            out = pre_unembed(x, params, cfg, mesh)
        else:
            out = _unembed(x, params, cfg, dtype, mesh)
        if with_aux:
            return out, {"router_aux": pipe_aux / cfg.n_layers}
        return out

    # dense masks are shared by every layer of the same kind — build once.
    # Kernel impls (flash/ring) build masks blockwise in-kernel instead.
    masks = {kind: None for kind in set(cfg.block_pattern)}
    if impl == "xla":
        for kind in masks:
            masks[kind] = make_attention_mask(
                positions, positions, segment_ids, segment_ids, causal=True,
                sliding_window=(cfg.sliding_window if kind == "sliding"
                                else None))

    # per-repeat dropout keys ride the scan alongside the block params so
    # every layer draws an independent mask
    drop_keys = None
    if lora is not None and lora_rng is not None and lora_dropout > 0.0:
        drop_keys = jax.random.split(lora_rng, cfg.n_repeats)

    moe = cfg.n_experts > 0

    def repeat_body(carry, xs_slice):
        x, aux = carry
        layer_slice = xs_slice[0]
        lora_slice = xs_slice[1] if lora is not None else None
        rep_rng = xs_slice[-1] if drop_keys is not None else None
        x, aux = run_block_stack(
            x, aux, layer_slice, cfg, impl, dtype, rope, positions,
            masks, segment_ids, mesh, lora_slice=lora_slice,
            lora_scale=lora_scale, lora_dropout=lora_dropout,
            rep_rng=rep_rng, token_weights=token_weights,
            fused_ops=fused_ops)
        return (x, aux), None

    body = repeat_body
    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            # save matmul outputs, recompute only elementwise — trades
            # HBM for the ~2N/token recompute the "full" policy pays
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        body = jax.checkpoint(repeat_body, prevent_cse=False,
                              policy=policy)
    xs = [params["blocks"]]
    if lora is not None:
        xs.append(lora["blocks"])
    if drop_keys is not None:
        xs.append(drop_keys)
    (x, aux_sum), _ = jax.lax.scan(
        body, (x, jnp.zeros((), jnp.float32)), tuple(xs))
    if return_pre_unembed:
        out = pre_unembed(x, params, cfg, mesh)
    else:
        out = _unembed(x, params, cfg, dtype, mesh)
    if with_aux:
        return out, {"router_aux": aux_sum / cfg.n_layers if moe
                     else aux_sum}
    return out


def pre_unembed(x, params: Params, cfg: ModelConfig, mesh):
    """The final-normed hidden state — everything of ``_unembed`` up to
    (but not including) the vocab matmul. The fused cross-entropy path
    (ops/fused_ce.py) takes it together with :func:`unembed_head` so
    the [B, S, vocab] logits never materialize in HBM."""
    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps,
                 scale_plus_one=cfg.norm_scale_plus_one)
    return _constrain(x, mesh, BATCH_AXES, AXIS_CONTEXT, None)


def unembed_head(params: Params, cfg: ModelConfig):
    """The [D, vocab] unembedding matrix (tied or dedicated)."""
    return params["embed"].T if cfg.tie_embeddings else params["lm_head"]


def _unembed(x, params: Params, cfg: ModelConfig, dtype, mesh):
    """Shared tail: final norm → (tied) unembedding → logit softcap."""
    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps,
                 scale_plus_one=cfg.norm_scale_plus_one)
    logits = jnp.einsum("bsd,dv->bsv", x, unembed_head(params, cfg
                                                       ).astype(dtype),
                        preferred_element_type=jnp.float32)
    if cfg.logit_softcap is not None:
        logits = jnp.tanh(logits / cfg.logit_softcap) * cfg.logit_softcap
    return _constrain(logits, mesh, BATCH_AXES, AXIS_CONTEXT, "model")
