"""The shared decoder-only transformer core (all model families).

Functional, pytree-first: ``init_params`` builds the weights,
``param_specs`` builds the matching PartitionSpec tree, ``forward`` is a
pure jittable function. Layers are *stacked* ([n_repeats, ...] leading dim)
and iterated with ``lax.scan`` so a 32-80 layer model traces/compiles one
block body instead of unrolling — the XLA-idiomatic replacement for the
reference's python ``nn.TransformerEncoder`` module stack
(ray-jobs/pytorch_llm_ray.py:86-90).

Sharding (SURVEY.md §2c, TPU build disposition):
- FSDP: every matrix's d_model-ish dim sharded over ``fsdp``.
- TP: head / ffn-hidden dims sharded over ``model``.
- Activations: batch over (data, fsdp), sequence over ``context``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.ops.attention import (
    dot_product_attention, make_attention_mask)
from gke_ray_train_tpu.ops.norms import rms_norm
from gke_ray_train_tpu.ops.rope import (
    apply_rope, rope_frequencies, sinusoidal_positions)
from gke_ray_train_tpu.parallel.mesh import AXIS_CONTEXT, BATCH_AXES

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Initialize the stacked param pytree.

    Truncated-normal fan-in style init; the two residual-writing matrices
    (wo, w_down) are scaled down by 1/sqrt(2*n_layers) to keep the
    residual-stream variance flat at depth.
    """
    pdt = jnp.dtype(cfg.param_dtype)
    hd = cfg.resolved_head_dim
    D, F, H, K, R = (cfg.d_model, cfg.d_ff, cfg.n_heads, cfg.n_kv_heads,
                     cfg.n_repeats)
    depth_scale = 1.0 / math.sqrt(2 * cfg.n_layers)

    keys = iter(jax.random.split(key, 16 * len(cfg.block_pattern) + 4))

    def normal(shape, std):
        return (jax.random.truncated_normal(next(keys), -3, 3, shape,
                                            jnp.float32) * std).astype(pdt)

    def block_params():
        std = 0.02
        p = {
            "attn_norm": jnp.zeros((R, D), pdt) if cfg.norm_scale_plus_one
            else jnp.ones((R, D), pdt),
            "wq": normal((R, D, H * hd), std),
            "wk": normal((R, D, K * hd), std),
            "wv": normal((R, D, K * hd), std),
            "wo": normal((R, H * hd, D), std * depth_scale),
            "mlp_norm": jnp.zeros((R, D), pdt) if cfg.norm_scale_plus_one
            else jnp.ones((R, D), pdt),
            "w_gate": normal((R, D, F), std),
            "w_up": normal((R, D, F), std),
            "w_down": normal((R, F, D), std * depth_scale),
        }
        if cfg.post_block_norm:
            zero_or_one = (jnp.zeros if cfg.norm_scale_plus_one else jnp.ones)
            p["attn_post_norm"] = zero_or_one((R, D), pdt)
            p["mlp_post_norm"] = zero_or_one((R, D), pdt)
        return p

    params: Params = {
        "embed": normal((cfg.vocab_size, D), 0.02),
        "blocks": [block_params() for _ in cfg.block_pattern],
        "final_norm": (jnp.zeros((D,), pdt) if cfg.norm_scale_plus_one
                       else jnp.ones((D,), pdt)),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal((D, cfg.vocab_size), 0.02)
    return params


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree matching init_params exactly.

    The ZeRO/FSDP sharding the reference gets from bitsandbytes+DDP
    (SURVEY.md rows D4/D5) is this table; nothing else.
    """
    def block_specs():
        s = {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "model"),
            "wk": P(None, "fsdp", "model"),
            "wv": P(None, "fsdp", "model"),
            "wo": P(None, "model", "fsdp"),
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "model"),
            "w_up": P(None, "fsdp", "model"),
            "w_down": P(None, "model", "fsdp"),
        }
        if cfg.post_block_norm:
            s["attn_post_norm"] = P(None, None)
            s["mlp_post_norm"] = P(None, None)
        return s

    specs: Params = {
        "embed": P("model", "fsdp"),
        "blocks": [block_specs() for _ in cfg.block_pattern],
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "model")
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _constrain(x, mesh: Optional[Mesh], *spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _mlp(x, lp, cfg: ModelConfig, dtype):
    gate = jnp.einsum("bsd,df->bsf", x, lp["w_gate"].astype(dtype))
    up = jnp.einsum("bsd,df->bsf", x, lp["w_up"].astype(dtype))
    if cfg.activation == "silu":
        act = jax.nn.silu(gate)
    elif cfg.activation == "gelu_tanh":
        act = jax.nn.gelu(gate, approximate=True)
    else:
        raise ValueError(f"unknown activation {cfg.activation}")
    return jnp.einsum("bsf,fd->bsd", act * up, lp["w_down"].astype(dtype))


def _attn(x, lp, cfg: ModelConfig, dtype, rope, positions, mask, mesh):
    B, S, D = x.shape
    hd = cfg.resolved_head_dim
    H, K = cfg.n_heads, cfg.n_kv_heads
    q = jnp.einsum("bsd,dh->bsh", x, lp["wq"].astype(dtype))
    k = jnp.einsum("bsd,dh->bsh", x, lp["wk"].astype(dtype))
    v = jnp.einsum("bsd,dh->bsh", x, lp["wv"].astype(dtype))
    q = q.reshape(B, S, H, hd)
    k = k.reshape(B, S, K, hd)
    v = v.reshape(B, S, K, hd)
    q = _constrain(q, mesh, BATCH_AXES, AXIS_CONTEXT, "model", None)
    k = _constrain(k, mesh, BATCH_AXES, AXIS_CONTEXT, "model", None)
    if rope is not None:
        q = apply_rope(q, positions, rope)
        k = apply_rope(k, positions, rope)
    out = dot_product_attention(
        q, k, v, mask, scale=cfg.attn_scale, logit_softcap=cfg.attn_softcap)
    out = out.reshape(B, S, H * hd)
    return jnp.einsum("bsh,hd->bsd", out, lp["wo"].astype(dtype))


def forward(params: Params, tokens: jnp.ndarray, cfg: ModelConfig, *,
            positions: Optional[jnp.ndarray] = None,
            segment_ids: Optional[jnp.ndarray] = None,
            mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """tokens [B, S] int32 → logits [B, S, vocab] float32."""
    B, S = tokens.shape
    dtype = jnp.dtype(cfg.dtype)
    eps, sp1 = cfg.norm_eps, cfg.norm_scale_plus_one

    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

    x = params["embed"].astype(dtype)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), dtype)
    if cfg.positional == "sinusoidal":
        table = jnp.asarray(sinusoidal_positions(cfg.max_seq_len, cfg.d_model))
        x = x + table.astype(dtype)[positions]
        rope = None
    else:
        rope = jnp.asarray(rope_frequencies(
            cfg.resolved_head_dim, theta=cfg.rope_theta,
            llama3_scaling=cfg.rope_scaling))
    x = _constrain(x, mesh, BATCH_AXES, AXIS_CONTEXT, None)

    # masks are shared by every layer of the same kind — build once
    masks = {}
    for kind in set(cfg.block_pattern):
        masks[kind] = make_attention_mask(
            positions, positions, segment_ids, segment_ids, causal=True,
            sliding_window=cfg.sliding_window if kind == "sliding" else None)

    def repeat_body(x, layer_slice):
        for p, kind in enumerate(cfg.block_pattern):
            lp = layer_slice[p]
            h = rms_norm(x, lp["attn_norm"], eps=eps, scale_plus_one=sp1)
            h = _attn(h, lp, cfg, dtype, rope, positions, masks[kind], mesh)
            if cfg.post_block_norm:
                h = rms_norm(h, lp["attn_post_norm"], eps=eps,
                             scale_plus_one=sp1)
            x = x + h
            x = _constrain(x, mesh, BATCH_AXES, AXIS_CONTEXT, None)
            h = rms_norm(x, lp["mlp_norm"], eps=eps, scale_plus_one=sp1)
            h = _mlp(h, lp, cfg, dtype)
            if cfg.post_block_norm:
                h = rms_norm(h, lp["mlp_post_norm"], eps=eps,
                             scale_plus_one=sp1)
            x = x + h
            x = _constrain(x, mesh, BATCH_AXES, AXIS_CONTEXT, None)
        return x, None

    body = repeat_body
    if cfg.remat:
        body = jax.checkpoint(repeat_body, prevent_cse=False)
    x, _ = jax.lax.scan(body, x, params["blocks"])

    x = rms_norm(x, params["final_norm"], eps=eps, scale_plus_one=sp1)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x, head.astype(dtype),
                        preferred_element_type=jnp.float32)
    if cfg.logit_softcap is not None:
        logits = jnp.tanh(logits / cfg.logit_softcap) * cfg.logit_softcap
    logits = _constrain(logits, mesh, BATCH_AXES, AXIS_CONTEXT, "model")
    return logits
