"""Model configuration — one config dataclass drives every model family.

The reference hardcodes one bespoke torch model (BasicLLM,
ray-jobs/pytorch_llm_ray.py:75-105) and delegates Llama to HF
``AutoModelForCausalLM`` (ray-jobs/fine_tune_llama_ray.py:240). Here a
single functional decoder core (models/transformer.py) covers Llama-3,
Mistral, Gemma-2 and the from-scratch BasicLM via this config, so every
family gets the same sharding specs, flash/ring attention, LoRA and
checkpointing for free.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# The seven projection matrices of every decoder block — the canonical
# target list for LoRA adapters (reference LORA_TARGET_MODULES,
# fine_tune_config.json:33) and weight quantization. Lives here (leaf
# module, no deps) so ops/quant.py and train/lora.py can both import it
# without a train↔ops cycle.
PROJ_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    head_dim: Optional[int] = None          # default d_model // n_heads
    max_seq_len: int = 2048
    norm_eps: float = 1e-5

    # positional encoding
    positional: str = "rope"                # "rope" | "sinusoidal"
    rope_theta: float = 10000.0
    # llama-3.1 NTK-by-parts params; dicts are normalized to sorted
    # (key, value) tuples in __post_init__ so the config stays hashable
    rope_scaling: Optional[object] = None

    # block structure; n_layers must divide by len(block_pattern).
    # "global" = full causal attention, "sliding" = windowed causal.
    block_pattern: Tuple[str, ...] = ("global",)
    sliding_window: Optional[int] = None

    activation: str = "silu"                # "silu" | "gelu_tanh"

    # Mixture-of-Experts (ops/moe.py). n_experts=0 → dense MLP. When >0,
    # every block's MLP becomes a top-k routed expert bank (Mixtral
    # pattern); experts shard over the `model` axis = expert parallelism
    # under GSPMD (SURVEY.md §2c row EP).
    n_experts: int = 0
    expert_top_k: int = 2
    # per-expert token capacity = capacity_factor * top_k * S / E
    # (GShard-style static capacity; overflow tokens drop to the
    # residual path)
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01           # Switch load-balance loss weight

    tie_embeddings: bool = False
    embed_scale: bool = False               # x *= sqrt(d_model) after embed
    attn_qkv_bias: bool = False             # Qwen-2: bias on q/k/v proj only
    norm_scale_plus_one: bool = False       # Gemma (1 + scale) RMSNorm
    post_block_norm: bool = False           # Gemma-2 post-attn/post-mlp norms
    attn_softcap: Optional[float] = None    # Gemma-2: 50.0
    logit_softcap: Optional[float] = None   # Gemma-2: 30.0
    attn_scale: Optional[float] = None      # override head_dim**-0.5

    # numerics / execution
    dtype: str = "bfloat16"                 # activation/compute dtype
    param_dtype: str = "float32"
    remat: bool = True                      # checkpoint each block
    # what the per-block checkpoint saves: "full" recomputes the whole
    # block in backward (lowest memory, +2N recompute FLOPs/token);
    # "dots" saves matmul outputs and recomputes only elementwise ops
    # (more memory, near-zero recompute) — worth ~1/3 higher arithmetic
    # throughput when activations fit HBM
    remat_policy: str = "full"              # "full" | "dots"
    attn_impl: str = "auto"     # "auto" | "xla" | "flash" | "ring" | "a2a"
    # "auto" resolves at trace time: flash (Pallas) on TPU, xla oracle off-TPU

    # pipeline schedule (models/pipeline.py): virtual stage groups per
    # device. 1 = plain shift buffer; v>1 = circular/interleaved (each
    # device owns v non-contiguous layer groups; see the pipeline module
    # docstring for the honest bubble table). Only read on pipe>1 meshes.
    pipe_virtual: int = 1

    def __post_init__(self):
        # keep the config hashable (jit static arg): dicts → sorted tuples
        if isinstance(self.rope_scaling, dict):
            object.__setattr__(self, "rope_scaling",
                               tuple(sorted(self.rope_scaling.items())))
        if isinstance(self.block_pattern, list):
            object.__setattr__(self, "block_pattern",
                               tuple(self.block_pattern))
        if self.n_layers % len(self.block_pattern) != 0:
            raise ValueError(
                f"n_layers={self.n_layers} not divisible by block pattern "
                f"length {len(self.block_pattern)}")
        if self.n_heads % self.n_kv_heads != 0:
            raise ValueError("n_heads must be a multiple of n_kv_heads")
        unknown = set(self.block_pattern) - {"global", "sliding"}
        if unknown:
            raise ValueError(f"unknown block kinds {unknown}; "
                             "valid: global, sliding")
        if "sliding" in self.block_pattern and self.sliding_window is None:
            raise ValueError("block_pattern contains 'sliding' but "
                             "sliding_window is None — that would silently "
                             "run full global attention")
        if self.attn_impl not in ("auto", "xla", "flash", "ring", "a2a"):
            raise ValueError(f"unknown attn_impl {self.attn_impl!r}")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(f"unknown remat_policy {self.remat_policy!r}")
        if self.pipe_virtual < 1:
            raise ValueError(f"pipe_virtual={self.pipe_virtual} must be >= 1")

    def to_dict(self) -> dict:
        """JSON-serializable form (offline converter sidecar files)."""
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "ModelConfig":
        d = dict(d)
        # JSON turns the normalized tuple-of-pairs rope_scaling into
        # lists; restore hashability before __post_init__ validation
        if isinstance(d.get("rope_scaling"), list):
            d["rope_scaling"] = tuple(
                tuple(x) for x in d["rope_scaling"])
        return ModelConfig(**d)

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.d_model // self.n_heads

    @property
    def resolved_attn_impl(self) -> str:
        if self.attn_impl != "auto":
            return self.attn_impl
        import jax
        return "flash" if jax.default_backend() == "tpu" else "xla"

    @property
    def n_repeats(self) -> int:
        return self.n_layers // len(self.block_pattern)

    def param_count(self) -> int:
        """Exact TOTAL param count (storage truth; for MoE this counts
        every expert). MFU math uses active_param_count()."""
        return self._count_params(self.n_experts)

    def active_param_count(self) -> int:
        """Params touched per token: for MoE, the router plus the top-k
        experts only — the FLOP-relevant count (train/metrics.py)."""
        return self._count_params(min(self.expert_top_k, self.n_experts)
                                  if self.n_experts else 0)

    def _count_params(self, experts_counted: int) -> int:
        hd = self.resolved_head_dim
        attn = (self.d_model * self.n_heads * hd          # wq
                + 2 * self.d_model * self.n_kv_heads * hd  # wk, wv
                + self.n_heads * hd * self.d_model)        # wo
        if self.attn_qkv_bias:
            attn += self.n_heads * hd + 2 * self.n_kv_heads * hd
        ffn = 3 * self.d_model * self.d_ff
        if self.n_experts:
            mlp = (self.d_model * self.n_experts          # router
                   + experts_counted * ffn)
        else:
            mlp = ffn
        norms = 2 * self.d_model + (2 * self.d_model if self.post_block_norm
                                    else 0)
        per_layer = attn + mlp + norms
        embed = self.vocab_size * self.d_model
        head = 0 if self.tie_embeddings else self.vocab_size * self.d_model
        return self.n_layers * per_layer + embed + head + self.d_model


# ---------------------------------------------------------------------------
# Family presets. Shapes follow the public architecture descriptions of each
# model family (not any particular implementation).
# ---------------------------------------------------------------------------

_LLAMA31_SCALING = dict(factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
                        original_max_position_embeddings=8192)


def llama2_7b(**kw) -> ModelConfig:
    """Llama-2-7B: MHA (no GQA), rope theta 1e4, 32k vocab — runs on
    the same decoder core with zero new mechanisms; HF tensor names are
    identical to Llama-3's, so interop needs nothing new either."""
    return ModelConfig(
        name="llama2-7b", vocab_size=32000, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=32, d_ff=11008, max_seq_len=4096,
        rope_theta=10000.0,
        **kw)


def llama2_13b(**kw) -> ModelConfig:
    return ModelConfig(
        name="llama2-13b", vocab_size=32000, d_model=5120, n_layers=40,
        n_heads=40, n_kv_heads=40, d_ff=13824, max_seq_len=4096,
        rope_theta=10000.0,
        **kw)


def llama2_70b(**kw) -> ModelConfig:
    # the one GQA member of the Llama-2 family (n_kv_heads = 8)
    return ModelConfig(
        name="llama2-70b", vocab_size=32000, d_model=8192, n_layers=80,
        n_heads=64, n_kv_heads=8, d_ff=28672, max_seq_len=4096,
        rope_theta=10000.0,
        **kw)


def llama3_8b(**kw) -> ModelConfig:
    kw.setdefault("rope_scaling", _LLAMA31_SCALING)
    return ModelConfig(
        name="llama3-8b", vocab_size=128256, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, max_seq_len=8192,
        rope_theta=500000.0,
        **kw)


def llama3_70b(**kw) -> ModelConfig:
    kw.setdefault("rope_scaling", _LLAMA31_SCALING)
    return ModelConfig(
        name="llama3-70b", vocab_size=128256, d_model=8192, n_layers=80,
        n_heads=64, n_kv_heads=8, d_ff=28672, max_seq_len=8192,
        rope_theta=500000.0,
        **kw)


def mistral_7b(**kw) -> ModelConfig:
    # vocab 32768 = the extended v0.3 tokenizer; pass vocab_size=32000 for
    # v0.1/v0.2 checkpoints
    kw.setdefault("vocab_size", 32768)
    return ModelConfig(
        name="mistral-7b", d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, max_seq_len=4096,
        rope_theta=10000.0, block_pattern=("sliding",), sliding_window=4096,
        **kw)


def mixtral_8x7b(**kw) -> ModelConfig:
    """Mixtral 8x7B: Mistral-7B dims with an 8-expert top-2 MoE MLP per
    layer (public architecture description; 47B total / ~13B active)."""
    return ModelConfig(
        name="mixtral-8x7b", vocab_size=32000, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, max_seq_len=4096,
        rope_theta=1e6, n_experts=8, expert_top_k=2,
        **kw)


def qwen2_7b(**kw) -> ModelConfig:
    """Qwen-2/2.5 7B: Llama-style GQA decoder whose one architectural
    delta is bias on the q/k/v projections (public architecture; the HF
    checkpoints carry q_proj.bias etc.)."""
    return ModelConfig(
        name="qwen2-7b", vocab_size=152064, d_model=3584, n_layers=28,
        n_heads=28, n_kv_heads=4, d_ff=18944, max_seq_len=32768,
        rope_theta=1e6, attn_qkv_bias=True, norm_eps=1e-6,
        **kw)


def gemma2_9b(**kw) -> ModelConfig:
    return ModelConfig(
        name="gemma2-9b", vocab_size=256128, d_model=3584, n_layers=42,
        n_heads=16, n_kv_heads=8, d_ff=14336, head_dim=256, max_seq_len=8192,
        rope_theta=10000.0, block_pattern=("sliding", "global"),
        sliding_window=4096, activation="gelu_tanh", tie_embeddings=True,
        embed_scale=True, norm_scale_plus_one=True, post_block_norm=True,
        attn_softcap=50.0, logit_softcap=30.0,
        attn_scale=256 ** -0.5,  # 9B query_pre_attn_scalar = head_dim = 256
        norm_eps=1e-6,
        **kw)


def basic_lm(vocab_size: int, *, d_model: int = 2048, n_layers: int = 24,
             n_heads: int = 16, d_ff: int = 8192, max_seq_len: int = 1024,
             **kw) -> ModelConfig:
    """The from-scratch pre-train model — capability parity with the
    reference's ~1.2B BasicLLM (2048d/24L/16H/8192ff,
    ray-jobs/pytorch_llm_ray.py:328-332), TPU-redesigned: pre-LN RMSNorm +
    RoPE decoder rather than post-LN sinusoidal nn.TransformerEncoder."""
    return ModelConfig(
        name="basic-lm", vocab_size=vocab_size, d_model=d_model,
        n_layers=n_layers, n_heads=n_heads, n_kv_heads=n_heads, d_ff=d_ff,
        max_seq_len=max_seq_len, **kw)


def tiny(vocab_size: int = 256, **kw) -> ModelConfig:
    """Test-scale config (fits the 8-fake-device CPU mesh)."""
    defaults = dict(
        name="tiny", vocab_size=vocab_size, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=128,
        dtype="float32", param_dtype="float32", remat=False)
    defaults.update(kw)
    return ModelConfig(**defaults)


PRESETS = {
    "llama2-7b": llama2_7b,
    "llama2-13b": llama2_13b,
    "llama2-70b": llama2_70b,
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "mistral-7b": mistral_7b,
    "mixtral-8x7b": mixtral_8x7b,
    "gemma2-9b": gemma2_9b,
    "qwen2-7b": qwen2_7b,
}


def preset_for_model_id(model_id: str, **kw) -> ModelConfig:
    """Map an HF-style MODEL_ID (fine_tune_config.json key) to a preset."""
    mid = model_id.lower()
    is_31 = any(t in mid for t in ("llama-3.1", "llama-3_1", "llama3.1"))
    if "llama-2" in mid or "llama2" in mid:
        if "70b" in mid:
            return llama2_70b(**kw)
        if "13b" in mid:
            return llama2_13b(**kw)
        return llama2_7b(**kw)
    if "llama-3" in mid or "llama3" in mid:
        fn = llama3_70b if "70b" in mid else llama3_8b
        # NTK rope scaling is a Llama-3.1 feature; plain Llama-3
        # checkpoints were trained without it
        kw.setdefault("rope_scaling", _LLAMA31_SCALING if is_31 else None)
        return fn(**kw)
    if "mixtral" in mid:
        return mixtral_8x7b(**kw)
    if "mistral" in mid:
        if any(t in mid for t in ("v0.1", "v0.2")):
            kw.setdefault("vocab_size", 32000)
        return mistral_7b(**kw)
    if "gemma-2" in mid or "gemma2" in mid:
        return gemma2_9b(**kw)
    if "qwen" in mid:
        return qwen2_7b(**kw)
    raise ValueError(f"no preset for MODEL_ID={model_id!r}; "
                     f"known families: {sorted(PRESETS)}")
