"""Greedy decoding.

Parity with the reference's inference path
(generate_sql_with_chat_template, ray-jobs/fine_tune_llama_ray.py:120-149:
greedy ``model.generate(max_new_tokens, do_sample=False)`` with multiple
EOS ids). TPU design: one jitted step over a *fixed-size* token buffer
(no dynamic shapes — recompilation-free), with a lax.while_loop host-free
decode loop. KV-cache decode is a planned optimization; this full-forward
variant is the correctness oracle for it.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.transformer import Params, forward


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "eos_ids",
                                   "lora_scale"))
def greedy_generate(params: Params, prompt: jnp.ndarray,
                    prompt_len: jnp.ndarray, cfg: ModelConfig, *,
                    max_new_tokens: int = 64,
                    eos_ids: Sequence[int] = (),
                    lora: Optional[Params] = None,
                    lora_scale: float = 1.0) -> jnp.ndarray:
    """prompt: [B, L] int32 padded buffer with room for generation
    (L >= max(prompt_len) + max_new_tokens); prompt_len: [B] int32.

    Returns the buffer with generated tokens written after each prompt.
    Finished rows (EOS emitted) stop growing.
    """
    B, L = prompt.shape
    eos = jnp.asarray(list(eos_ids) or [-1], jnp.int32)

    def cond(state):
        buf, lens, done, step = state
        return (step < max_new_tokens) & ~jnp.all(done)

    def body(state):
        buf, lens, done, step = state
        logits = forward(params, buf, cfg, lora=lora, lora_scale=lora_scale)
        # next token comes from the logit at each row's current last token
        idx = jnp.clip(lens - 1, 0, L - 1)
        next_tok = jnp.argmax(
            jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0, :],
            axis=-1).astype(jnp.int32)
        write_pos = jnp.clip(lens, 0, L - 1)
        buf = jnp.where(
            (~done)[:, None] & (jnp.arange(L)[None, :] == write_pos[:, None]),
            next_tok[:, None], buf)
        now_eos = jnp.any(next_tok[:, None] == eos[None, :], axis=-1)
        new_lens = jnp.where(done | (lens >= L), lens, lens + 1)
        return buf, new_lens, done | now_eos | (new_lens >= L), step + 1

    buf, lens, done, _ = jax.lax.while_loop(
        cond, body, (prompt, prompt_len,
                     jnp.zeros((B,), bool), jnp.asarray(0)))
    return buf
