"""GSPMD pipeline parallelism over the ``pipe`` mesh axis.

The reference stack reaches pipeline parallelism through DeepSpeed/
Megatron-style stage processes; SURVEY.md §2c records PP as optional on
TPU ("prefer TP+FSDP"). This module closes that row anyway, the TPU way:
no stage processes, no send/recv framework — the pipeline is ordinary
jit-traced array code whose *shardings* make XLA emit the stage-to-stage
transfer as a one-hop ``collective-permute`` on ICI.

Design (the "shift buffer" formulation, cf. the public scaling-book
pipelining recipe):

- The stacked block params ``[R, ...]`` are viewed as ``[R/P, P, ...]``
  with the stage dim sharded over ``pipe`` — each device owns the
  weights of its ``R/P`` contiguous repeats (param memory scales 1/P,
  same as the reference's stage partitioning).
- Activations live in a stage buffer ``[P, Bm, S, D]`` (microbatch size
  ``Bm = B/M``). Each tick: ``jnp.roll`` the buffer by one stage (XLA:
  collective-permute), feed microbatch ``t`` into stage 0, apply every
  stage's local repeats in parallel (stage-batched einsums — block-
  diagonal matmuls, one per device), and harvest stage ``P-1``'s output.
- ``M + P - 1`` ticks drain ``M`` microbatches; the bubble fraction is
  ``(P-1)/(M+P-1)`` — raise ``pipe_microbatches`` to amortize it.
- The whole loop is a ``lax.scan``; autodiff transposes the rolls into
  reverse permutes, so the backward pass is the mirrored pipeline with
  no hand-written schedule.

Circular / interleaved schedule (``cfg.pipe_virtual = v > 1``): each
device owns ``v`` NON-contiguous layer groups of ``R/(P·v)`` repeats
(device p owns groups ``{j·P+p}``); the stage buffer generalizes to
``[v, P, ...]`` and a microbatch loops the device ring ``v`` times.
``v = 1`` IS the plain shift schedule (one code path).

Honest bubble accounting for this homogeneous-scan formulation — every
tick costs the same R/P repeats per device whether a slot holds real
data or garbage, so "bubble" here means garbage-slot compute:

| schedule      | ticks        | garbage fraction    |
|---------------|--------------|---------------------|
| shift (v=1)   | M + P - 1    | (P-1)/(M+P-1)       |
| circular (v)  | M + vP - 1   | (vP-1)/(M+vP-1)     |

i.e. circular does NOT cut the scan-form bubble — the Megatron-style
``(P-1)/(Mv+P-1)`` figure requires a heterogeneous 1F1B schedule that a
single jitted scan (and its autodiff transpose) cannot express. What
circular buys here is finer-grained stages (first-token latency R/(vP)
per hop, relevant for inference pipelining) at the cost of one
gather-style param regroup per forward (non-contiguous ownership vs the
contiguous ``pipe``-sharded storage). The REAL bubble lever on TPU is
``M``: fold grad-accum microbatches into ``pipe_microbatches`` (set
``GRADIENT_ACCUMULATION_STEPS=1`` and ``PIPE_MICROBATCHES=G·M``) so one
pipeline pass amortizes its P-1 warmup over the whole accumulation
window — the loss is a per-token sum either way, so the math is
identical. Measured tick counts are pinned by tests/test_pipeline.py.

Composability: the batch dim stays sharded over ``(data, fsdp)``,
head/ffn dims over ``model``, and the sequence dim over ``context``
*inside* the pipeline (the stage dim is just one more array axis to
GSPMD), so PP composes with DP/FSDP/TP/CP — ring/a2a attention take the
stage-folded ``(pipe, data, fsdp)`` batch spec through the dispatch's
``batch_axes`` hook, and EP rides in via the MoE expert sharding.

Correctness notes:
- Warmup ticks process zero buffers and drain ticks replay the last
  microbatch; microbatch m surfaces from the last slot at tick
  m + depth - 1 (depth = v·P hops), so the harvest is simply the last M
  scan outputs (``ys[depth-1:]``) — garbage emissions fall outside the
  window and get zero cotangent in the backward pass. The one thing
  that DOES need masking is the MoE router aux, which would otherwise
  count the garbage passes (see the validity mask in the tick body).
- LoRA adapters ride along as stage-batched einsums (QLoRA bases
  dequantize per stage-slice); LoRA *dropout* is not supported on a
  pipelined mesh — the per-repeat rng fold-in would need a per-stage
  tick-varying key schedule for exactness.
- MoE MLPs route per stage via a vmapped moe_mlp; dispatch capacity is
  per sequence row, so pipelined logits are exact vs the plain path.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.ops.attention import (
    dot_product_attention, make_attention_mask)
from gke_ray_train_tpu.ops.norms import rms_norm
from gke_ray_train_tpu.ops.rope import apply_rope
from gke_ray_train_tpu.parallel.mesh import (
    AXIS_CONTEXT, AXIS_PIPE, BATCH_AXES)

# the folded (stage * microbatch) leading dim of attention inputs
STAGE_BATCH_AXES = (AXIS_PIPE,) + BATCH_AXES

def _warn_shallow_microbatches(M: int, V: int, Pn: int) -> None:
    """Trace-time (once per shape) warning: fewer microbatches than
    pipeline hops means the garbage fraction exceeds 50%."""
    import logging

    from gke_ray_train_tpu.logging_utils import warn_once
    depth = V * Pn
    warn_once(
        logging.getLogger(__name__), ("shallow_microbatches", M, V, Pn),
        "pipeline has %d microbatches for depth %d (pipe=%d x virtual=%d):"
        " garbage fraction is %d/%d — raise PIPE_MICROBATCHES to amortize",
        M, depth, Pn, V, depth - 1, M + depth - 1)


def _constrain(x, mesh: Optional[Mesh], *spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _proj_p(x, w, lora_p, lora_scale, dtype, bias=None):
    """Stage-batched projection: x [P, Bm, S, d_in] @ w [P, d_in, d_out]
    (+ optional per-stage bias [P, d_out] — Qwen-2 q/k/v).

    One matmul per stage (block-diagonal to XLA — each device sees only
    its own stage's operand, so locally this is a plain matmul on the
    MXU). ``w`` may be a quantized QTensor slice (QLoRA base)."""
    from gke_ray_train_tpu.ops.quant import maybe_dequantize
    y = jnp.einsum("pbsd,pdh->pbsh", x, maybe_dequantize(w, dtype))
    if lora_p is not None:
        xa = jnp.einsum("pbsd,pdr->pbsr", x, lora_p["a"].astype(dtype))
        y = y + jnp.einsum("pbsr,prh->pbsh", xa,
                           lora_p["b"].astype(dtype)) \
            * jnp.asarray(lora_scale, dtype)
    if bias is not None:
        y = y + bias[:, None, None, :].astype(dtype)
    return y


def _norm_p(x, scale, eps, sp1):
    """rms_norm with a per-stage scale [P, D] against x [P, Bm, S, D]."""
    return rms_norm(x, scale[:, None, None, :], eps=eps, scale_plus_one=sp1)


def _lora_entry(lora_p, name):
    return None if lora_p is None or name not in lora_p else lora_p[name]


def _attn_p(x, lp, cfg: ModelConfig, impl, dtype, rope, posf, segf, mask,
            window, mesh, lora_p, lora_scale, seq_ax=None):
    """posf/segf: stage-folded [Pn*Bm, S]; mask: prebuilt dense mask for
    this block kind (xla impl) or None (kernel impls build blockwise)."""
    Pn, Bm, S, D = x.shape
    hd = cfg.resolved_head_dim
    H, K = cfg.n_heads, cfg.n_kv_heads

    def lr(name):
        return _lora_entry(lora_p, name)
    q = _proj_p(x, lp["wq"], lr("wq"), lora_scale, dtype,
                bias=lp.get("bq"))
    k = _proj_p(x, lp["wk"], lr("wk"), lora_scale, dtype,
                bias=lp.get("bk"))
    v = _proj_p(x, lp["wv"], lr("wv"), lora_scale, dtype,
                bias=lp.get("bv"))
    # fold the stage dim into batch: attention is weightless, so every
    # stage runs the identical kernel on its own microbatch
    q = q.reshape(Pn * Bm, S, H, hd)
    k = k.reshape(Pn * Bm, S, K, hd)
    v = v.reshape(Pn * Bm, S, K, hd)
    q = _constrain(q, mesh, STAGE_BATCH_AXES, seq_ax, "model", None)
    k = _constrain(k, mesh, STAGE_BATCH_AXES, seq_ax, "model", None)
    if rope is not None:
        q = apply_rope(q, posf, rope)
        k = apply_rope(k, posf, rope)
    if impl == "xla":
        out = dot_product_attention(q, k, v, mask, scale=cfg.attn_scale,
                                    logit_softcap=cfg.attn_softcap)
    else:
        from gke_ray_train_tpu.ops.dispatch import attention_dispatch
        out = attention_dispatch(
            impl, q, k, v, q_positions=posf, kv_positions=posf,
            q_segment_ids=segf, kv_segment_ids=segf, causal=True,
            sliding_window=window, scale=cfg.attn_scale,
            logit_softcap=cfg.attn_softcap, mesh=mesh,
            batch_axes=STAGE_BATCH_AXES)
    out = out.reshape(Pn, Bm, S, H * hd)
    return _proj_p(out, lp["wo"], lr("wo"), lora_scale, dtype)


def _moe_p(x, lp, cfg: ModelConfig, dtype, w):
    """Stage-batched MoE MLP: vmap the plain moe_mlp over the stage dim
    (each stage owns different expert weights). Returns (y [P,Bm,S,D],
    per-stage aux [P]). Dispatch capacity is per sequence row, so the
    routing inside one microbatch is IDENTICAL to the unpipelined layer;
    only the aux statistic becomes a mean over (stage, microbatch)
    submeans instead of one joint batch mean. ``w`` [P,Bm,S] are the
    token weights riding the stage buffers — all-zero on WARMUP slots
    (zero-initialized buffer), but drain slots replay the last
    microbatch's real weights: the tick's ``(mb>=0)&(mb<M)`` mask is
    what actually excludes garbage passes from the aux."""
    from gke_ray_train_tpu.ops.moe import moe_mlp

    def one_stage(xs, router, w_gate, w_up, w_down, ws):
        return moe_mlp(xs, router, w_gate, w_up, w_down, cfg, dtype,
                       weights=ws)

    return jax.vmap(one_stage)(x, lp["router"], lp["w_gate"],
                               lp["w_up"], lp["w_down"], w)


def _mlp_p(x, lp, cfg: ModelConfig, dtype, lora_p, lora_scale):
    def lr(name):
        return _lora_entry(lora_p, name)
    gate = _proj_p(x, lp["w_gate"], lr("w_gate"), lora_scale, dtype)
    up = _proj_p(x, lp["w_up"], lr("w_up"), lora_scale, dtype)
    if cfg.activation == "silu":
        act = jax.nn.silu(gate)
    elif cfg.activation == "gelu_tanh":
        act = jax.nn.gelu(gate, approximate=True)
    else:
        raise ValueError(f"unknown activation {cfg.activation}")
    return _proj_p(act * up, lp["w_down"], lr("w_down"), lora_scale, dtype)


def _stage_repeats(x, pos, seg, w, blocks_r, lora_r, cfg: ModelConfig,
                   impl, dtype, rope, mesh, lora_scale, seq_ax=None):
    """Apply each stage's R/P local repeats to its buffer slot.

    Mirrors transformer.repeat_body, stage-batched; scanned over the
    per-stage repeat dim so depth compiles once. Dense masks (xla impl)
    are built ONCE per tick per block kind — pos/seg are constant across
    the repeat scan (same 'build once' rule as transformer.forward)."""
    eps, sp1 = cfg.norm_eps, cfg.norm_scale_plus_one
    Pn, Bm, S = pos.shape
    posf = pos.reshape(Pn * Bm, S)
    segf = seg.reshape(Pn * Bm, S)
    masks = {kind: None for kind in set(cfg.block_pattern)}
    if impl == "xla":
        for kind in masks:
            masks[kind] = make_attention_mask(
                posf, posf, segf, segf, causal=True,
                sliding_window=(cfg.sliding_window if kind == "sliding"
                                else None))

    moe = cfg.n_experts > 0

    def body(carry, xs_slice):
        x, aux = carry
        layer_slice = xs_slice[0]
        lora_slice = xs_slice[1] if lora_r is not None else None
        for p_i, kind in enumerate(cfg.block_pattern):
            lp = layer_slice[p_i]
            lo = lora_slice[p_i] if lora_slice is not None else None
            window = cfg.sliding_window if kind == "sliding" else None
            h = _norm_p(x, lp["attn_norm"], eps, sp1)
            h = _attn_p(h, lp, cfg, impl, dtype, rope, posf, segf,
                        masks[kind], window, mesh, lo, lora_scale,
                        seq_ax)
            if cfg.post_block_norm:
                h = _norm_p(h, lp["attn_post_norm"], eps, sp1)
            x = x + h
            x = _constrain(x, mesh, AXIS_PIPE, BATCH_AXES, seq_ax, None)
            h = _norm_p(x, lp["mlp_norm"], eps, sp1)
            if moe:
                h, a = _moe_p(h, lp, cfg, dtype, w)
                aux = aux + a
            else:
                h = _mlp_p(h, lp, cfg, dtype, lo, lora_scale)
            if cfg.post_block_norm:
                h = _norm_p(h, lp["mlp_post_norm"], eps, sp1)
            x = x + h
            x = _constrain(x, mesh, AXIS_PIPE, BATCH_AXES, seq_ax, None)
        return (x, aux), None

    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        body = jax.checkpoint(body, prevent_cse=False, policy=policy)
    xs = [blocks_r]
    if lora_r is not None:
        xs.append(lora_r)
    (x, aux), _ = jax.lax.scan(
        body, (x, jnp.zeros((Pn,), jnp.float32)), tuple(xs))
    return x, aux


def _virtual_repeats(buf, pbuf, sbuf, wbuf, blocks_r, lora_r,
                     cfg: ModelConfig, impl, dtype, rope, mesh,
                     lora_scale, seq_ax):
    """Apply every (virtual-group, device-stage) slot's local repeats.

    buf [V, Pn, Bm, S, D]; blocks_r/lora_r leaves [Rg, V, Pn, ...].
    V=1 (the default shift schedule) calls _stage_repeats directly —
    byte-identical program to the pre-virtual implementation; V>1 vmaps
    it over the virtual-group dim (device p's V groups are processed
    within one tick, keeping per-tick cost at R/P repeats per device).
    Returns (buf [V, Pn, ...], aux [V, Pn])."""
    V = buf.shape[0]
    if V == 1:
        blocks1 = jax.tree.map(lambda l: l[:, 0], blocks_r)
        lora1 = (jax.tree.map(lambda l: l[:, 0], lora_r)
                 if lora_r is not None else None)
        x, aux = _stage_repeats(buf[0], pbuf[0], sbuf[0], wbuf[0],
                                blocks1, lora1, cfg, impl, dtype, rope,
                                mesh, lora_scale, seq_ax)
        return x[None], aux[None]

    def one_group(x, p, s, w, b, lo):
        return _stage_repeats(x, p, s, w, b, lo, cfg, impl, dtype, rope,
                              mesh, lora_scale, seq_ax)

    if lora_r is None:
        return jax.vmap(
            lambda x, p, s, w, b: one_group(x, p, s, w, b, None),
            in_axes=(0, 0, 0, 0, 1))(buf, pbuf, sbuf, wbuf, blocks_r)
    return jax.vmap(one_group, in_axes=(0, 0, 0, 0, 1, 1))(
        buf, pbuf, sbuf, wbuf, blocks_r, lora_r)


def pipeline_blocks(x, params_blocks, cfg: ModelConfig, mesh: Mesh, *,
                    impl: str, dtype, rope, positions, segment_ids,
                    lora_blocks=None, lora_scale: float = 1.0,
                    n_microbatches: Optional[int] = None,
                    token_weights=None):
    """Run the stacked decoder blocks pipelined over the ``pipe`` axis.

    x: embedded activations [B, S, D] (batch sharded over (data, fsdp),
    replicated over pipe). Returns ``(y, aux)``: the block-stack output
    [B, S, D] with the same layout (final norm/unembed run replicated,
    outside) and the summed-over-layers MoE router aux (0.0 for dense).
    """
    Pn = int(mesh.shape[AXIS_PIPE])
    V = int(cfg.pipe_virtual)  # >= 1 by ModelConfig validation
    R = cfg.n_repeats
    if R % (Pn * V) != 0:
        raise ValueError(
            f"n_repeats={R} must be divisible by pipe axis x virtual "
            f"stages ({Pn} x {V})")
    if impl not in ("xla", "flash", "ring", "a2a"):
        raise ValueError(f"unknown attn impl {impl!r}")
    # context-parallel attention composes: ring/a2a take the stage-folded
    # batch spec (ops/dispatch.py batch_axes) and the seq dims of every
    # buffer shard over `context`
    seq_ax = AXIS_CONTEXT if mesh.shape[AXIS_CONTEXT] > 1 else None
    Rg = R // (Pn * V)
    B, S, D = x.shape
    # default M: one microbatch per HOP (depth = V*Pn) so the circular
    # schedule is not born with a majority-garbage tick budget; an
    # explicit n_microbatches below the depth still runs but is warned
    # about once (garbage fraction (depth-1)/(M+depth-1) per the table)
    M = int(n_microbatches) if n_microbatches else V * Pn
    if M < Pn:
        raise ValueError(
            f"pipeline microbatches ({M}) must be >= pipe stages ({Pn})")
    if M < V * Pn:
        _warn_shallow_microbatches(M, V, Pn)
    if B % M != 0:
        raise ValueError(
            f"batch {B} not divisible by {M} pipeline microbatches")
    batch_par = math.prod(mesh.shape[a] for a in BATCH_AXES)
    Bm = B // M
    if Bm % batch_par != 0:
        raise ValueError(
            f"pipeline microbatch size {Bm} (= batch {B} / {M}) must stay "
            f"divisible by the batch-parallel extent {batch_par}; lower "
            f"pipe_microbatches or raise the batch")

    # [R, ...] -> [Rg, V, Pn, ...]: group g = j*Pn + p (hop order ==
    # layer order) owns repeats [g*Rg, (g+1)*Rg). For V=1 the split
    # boundary coincides with the pipe shard boundary so no data moves;
    # for V>1 ownership is non-contiguous and GSPMD regroups the params
    # once per forward (outside the tick scan).
    def to_stages(leaf):
        return jnp.moveaxis(
            leaf.reshape((V, Pn, Rg) + leaf.shape[1:]), 2, 0)

    blocks_r = jax.tree.map(to_stages, params_blocks)
    lora_r = (jax.tree.map(to_stages, lora_blocks)
              if lora_blocks is not None else None)

    if positions is None:
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32), (B, S))
    if segment_ids is None:
        segment_ids = jnp.ones((B, S), jnp.int32)
    if token_weights is None:
        # all-ones = unweighted router aux (weighted mean == plain mean)
        token_weights = jnp.ones((B, S), jnp.float32)

    # microbatch streams ride the tick scan as xs (static per-iteration
    # slices — a traced dynamic_index over the microbatch dim forces the
    # SPMD partitioner into full rematerialization on reshard); drain
    # ticks replay the last microbatch into slot (0,0) and their outputs
    # are dropped by the static ys window below. Pipeline depth in hops
    # is V*Pn (a microbatch loops the device ring V times).
    depth = V * Pn
    T = M + depth - 1

    def pad_drain(a):
        return jnp.concatenate(
            [a, jnp.broadcast_to(a[-1:], (depth - 1,) + a.shape[1:])])

    xm = _constrain(pad_drain(x.reshape(M, Bm, S, D)), mesh,
                    None, BATCH_AXES, seq_ax, None)
    pm = pad_drain(positions.reshape(M, Bm, S))
    sm = pad_drain(segment_ids.reshape(M, Bm, S))
    wm = pad_drain(token_weights.astype(jnp.float32).reshape(M, Bm, S))

    buf = _constrain(jnp.zeros((V, Pn, Bm, S, D), x.dtype), mesh,
                     None, AXIS_PIPE, BATCH_AXES, seq_ax, None)
    pbuf = jnp.zeros((V, Pn, Bm, S), pm.dtype)
    sbuf = jnp.ones((V, Pn, Bm, S), sm.dtype)
    # weight buffer starts all-zero, nulling WARMUP-slot aux; drain
    # ticks replay real weights (pad_drain), so the tick mask below is
    # load-bearing for them — do not remove it as redundant
    wbuf = jnp.zeros((V, Pn, Bm, S), jnp.float32)

    def shift(b, inj):
        """Advance the (V, Pn) ring one hop: slot (j,p) <- (j,p-1); the
        wrap (j-1, Pn-1) -> (j, 0) re-enters the device ring (device-
        local move: both slots live on device 0's column after the
        roll); slot (0,0) takes the injected microbatch."""
        r = jnp.roll(b, 1, axis=1)         # one-hop collective-permute
        c0 = jnp.roll(r[:, 0], 1, axis=0).at[0].set(inj)
        return r.at[:, 0].set(c0)

    def tick(carry, xs_t):
        buf, pbuf, sbuf, wbuf, aux = carry
        x_in, p_in, s_in, w_in, t = xs_t
        buf = shift(buf, x_in)
        pbuf = shift(pbuf, p_in)
        sbuf = shift(sbuf, s_in)
        wbuf = shift(wbuf, w_in)
        buf = _constrain(buf, mesh, None, AXIS_PIPE, BATCH_AXES, seq_ax,
                         None)
        buf, aux_vec = _virtual_repeats(buf, pbuf, sbuf, wbuf, blocks_r,
                                        lora_r, cfg, impl, dtype, rope,
                                        mesh, lora_scale, seq_ax)
        # MoE router aux: slot (j,p) holds microbatch t - (j*Pn + p) —
        # warmup/drain passes over garbage slots must not contribute.
        # This mask is the sole guard for DRAIN slots (their wbuf holds
        # the replayed last microbatch's real weights)
        mb = t - (jnp.arange(V)[:, None] * Pn + jnp.arange(Pn)[None, :])
        aux = aux + jnp.sum(aux_vec * ((mb >= 0) & (mb < M)))
        # emit the last slot; microbatch m surfaces from (V-1, Pn-1) at
        # tick m + depth-1, so ys[depth-1:] is exactly [0..M) in order
        return (buf, pbuf, sbuf, wbuf, aux), buf[V - 1, Pn - 1]

    (_, _, _, _, aux), ys = jax.lax.scan(
        tick, (buf, pbuf, sbuf, wbuf, jnp.zeros((), jnp.float32)),
        (xm, pm, sm, wm, jnp.arange(T)))
    out = ys[depth - 1:]
    # aux summed over (every layer) x (every microbatch): /M leaves the
    # same sum-over-layers scale the plain path returns (forward then
    # divides by n_layers)
    return out.reshape(B, S, D), aux / M
