"""KV-cache decode (VERDICT r1 missing #3).

The reference's inference rides HF ``model.generate`` with its built-in
KV cache (/root/reference/ray-jobs/fine_tune_llama_ray.py:138-146). The
round-1 decode loop (models/decode.py) recomputes the full O(L²) forward
per generated token — correct (it is the oracle this module is tested
against) but unusable at 8B/300-token scale.

TPU design:
- The cache is a pytree shaped like the scanned block stack
  ([n_repeats, B, max_len, n_kv_heads, head_dim] per pattern position),
  so the same ``lax.scan`` that runs training blocks runs decode blocks.
- One function, ``forward_step``, serves prefill (T = prompt length)
  and decode (T = 1): new tokens sit at per-row positions
  ``lens + arange(T)``, their K/V are scattered into the cache, and
  attention masks by absolute position (kv_pos <= q_pos) — ragged
  prompts need no compaction, garbage slots from right-padding are
  overwritten before they ever become visible.
- Static shapes everywhere: the decode loop is a ``lax.while_loop``
  over a fixed buffer, one compile per (B, L, max_new) bucket.
- Prefill runs through the flash kernel when the prompt and cache
  widths tile by 128 (the dense path materializes [B, H, T, max_len]
  logits — the O(S²) memory wall at long prompts); T = 1 decode steps
  and ``attn_impl="xla"`` keep the dense mask.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.transformer import (
    Params, _lora_entry, _mlp, _proj)
from gke_ray_train_tpu.ops.attention import (
    dot_product_attention, make_attention_mask)
from gke_ray_train_tpu.ops.norms import rms_norm
from gke_ray_train_tpu.ops.rope import (
    apply_rope, rope_frequencies, sinusoidal_positions)

Cache = Dict[str, Any]


def init_cache(cfg: ModelConfig, batch: int, max_len: int,
               dtype: Optional[str] = None) -> Cache:
    """Zeroed cache pytree: blocks[i] = {"k","v"} of
    [n_repeats, batch, max_len, n_kv_heads, head_dim]."""
    dt = jnp.dtype(dtype or cfg.dtype)
    hd = cfg.resolved_head_dim
    shape = (cfg.n_repeats, batch, max_len, cfg.n_kv_heads, hd)
    return {"blocks": [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
                       for _ in cfg.block_pattern]}


def insert_cache_slot(pool: Cache, slot: jnp.ndarray, row: Cache) -> Cache:
    """Write a batch=1 cache ``row`` into batch index ``slot`` of a
    pooled cache — the continuous-batching admit path (serve/engine.py):
    a freshly prefilled request takes over a finished sequence's slot
    without touching any other slot's K/V bytes (pure
    ``dynamic_update_slice`` along the batch axis, so the surviving
    sequences' attention inputs are bit-identical before and after).

    ``slot`` may be a traced scalar — one compiled insert serves every
    slot index."""
    def upd(p, r):
        return jax.lax.dynamic_update_slice_in_dim(
            p, r.astype(p.dtype), slot, axis=1)
    return jax.tree.map(upd, pool, row)


def _scatter_rows(cache_kv: jnp.ndarray, new_kv: jnp.ndarray,
                  lens: jnp.ndarray) -> jnp.ndarray:
    """Write new_kv [B, T, K, hd] into cache_kv [B, max_len, K, hd] at
    per-row offsets lens[b] — a vmapped dynamic_update_slice: O(T·K·hd)
    copy per row, no materialized [T, max_len] one-hot.

    dynamic_update_slice clamps out-of-range starts, so a done row whose
    lens reached max_len re-writes the last slot instead of dropping the
    write — harmless, nothing is read for done rows."""
    def upd(c, n, start):
        return jax.lax.dynamic_update_slice(c, n, (start, 0, 0))
    return jax.vmap(upd)(cache_kv, new_kv.astype(cache_kv.dtype), lens)


def _warn_dense_prefill(T: int, max_len: int) -> None:
    import logging

    from gke_ray_train_tpu.logging_utils import warn_once
    warn_once(logging.getLogger(__name__), ("dense_prefill", T, max_len),
              "prefill width %d / cache %d do not tile by 128 — falling "
              "back to dense-mask attention (O(T*max_len) logits in "
              "memory); pad the prompt buffer to 128-multiples to use "
              "the flash kernel", T, max_len)


def forward_step(params: Params, tokens: jnp.ndarray, cfg: ModelConfig,
                 cache: Cache, lens: jnp.ndarray, *,
                 lora: Optional[Params] = None,
                 lora_scale: float = 1.0) -> Tuple[jnp.ndarray, Cache]:
    """tokens [B, T] at per-row absolute positions lens + arange(T) →
    (logits [B, T, vocab] fp32, updated cache).

    Same math as transformer.forward restricted to the new tokens, with
    K/V read from + written to the cache. Supports every family the
    trainer supports (GQA, RoPE/sinusoidal, sliding-window patterns,
    softcaps, QTensor bases, LoRA adapters).
    """
    if lora is not None and "aslot" in lora:
        # multi-tenant serving: ``lora`` is {"aslot": [B] int32,
        # "blocks": stacked pool with adapter axis 1} — gather each
        # row's adapter ONCE here (not per layer) so the block scan
        # sees ordinary per-row [B, d_in, r] entries and ``_proj``
        # takes the batched-einsum path (ops/lora_batched.py)
        from gke_ray_train_tpu.ops.lora_batched import gather_pool
        lora = {"blocks": gather_pool(lora["blocks"], lora["aslot"])}

    B, T = tokens.shape
    dtype = jnp.dtype(cfg.dtype)
    eps, sp1 = cfg.norm_eps, cfg.norm_scale_plus_one
    hd = cfg.resolved_head_dim
    H, K = cfg.n_heads, cfg.n_kv_heads
    max_len = cache["blocks"][0]["k"].shape[2]

    positions = lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]

    x = params["embed"].astype(dtype)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.d_model ** 0.5, dtype)
    if cfg.positional == "sinusoidal":
        table = jnp.asarray(sinusoidal_positions(cfg.max_seq_len,
                                                 cfg.d_model))
        x = x + table.astype(dtype)[jnp.clip(positions, 0,
                                             cfg.max_seq_len - 1)]
        rope = None
    else:
        rope = jnp.asarray(rope_frequencies(
            hd, theta=cfg.rope_theta, llama3_scaling=cfg.rope_scaling))

    kv_positions = jnp.broadcast_to(
        jnp.arange(max_len, dtype=jnp.int32)[None, :], (B, max_len))
    # prefill goes through the flash kernel when shapes tile (the dense
    # path materializes [B, H, T, max_len] logits — the O(S²) memory
    # wall at long prompts); single-token decode steps (T=1) and odd
    # widths keep the cheap dense mask, and attn_impl="xla" forces it.
    # ring/a2a are training-time context-parallel strategies — decode is
    # mesh-local, so they resolve to plain flash here.
    use_flash = (cfg.resolved_attn_impl != "xla" and T > 1
                 and T % 128 == 0 and max_len % 128 == 0)
    if not use_flash and cfg.resolved_attn_impl != "xla" and T > 1:
        # loud fallback, same policy as transformer._warn_flash_fallback:
        # a non-tiling long prefill silently eating O(T·max_len) logits
        # memory is easy to miss (pad the prompt buffer to 128s instead)
        _warn_dense_prefill(T, max_len)
    masks = {}
    if not use_flash:
        for kind in set(cfg.block_pattern):
            masks[kind] = make_attention_mask(
                positions, kv_positions, causal=True,
                sliding_window=(cfg.sliding_window if kind == "sliding"
                                else None))

    def repeat_body(x, xs_slice):
        layer_slice = xs_slice[0]
        cache_slice = xs_slice[1]
        lora_slice = xs_slice[2] if lora is not None else None
        new_cache = []
        for p, kind in enumerate(cfg.block_pattern):
            lp = layer_slice[p]
            ck = cache_slice[p]
            lo = lora_slice[p] if lora_slice is not None else None

            def lr(name):
                return _lora_entry(lo, name)

            h = rms_norm(x, lp["attn_norm"], eps=eps, scale_plus_one=sp1)
            q = _proj(h, lp["wq"], lr("wq"), lora_scale, dtype,
                      bias=lp.get("bq"))
            k = _proj(h, lp["wk"], lr("wk"), lora_scale, dtype,
                      bias=lp.get("bk"))
            v = _proj(h, lp["wv"], lr("wv"), lora_scale, dtype,
                      bias=lp.get("bv"))
            q = q.reshape(B, T, H, hd)
            k = k.reshape(B, T, K, hd)
            v = v.reshape(B, T, K, hd)
            if rope is not None:
                q = apply_rope(q, positions, rope)
                k = apply_rope(k, positions, rope)
            k_cache = _scatter_rows(ck["k"], k.astype(ck["k"].dtype), lens)
            v_cache = _scatter_rows(ck["v"], v.astype(ck["v"].dtype), lens)
            window = cfg.sliding_window if kind == "sliding" else None
            if use_flash:
                # single kernel entry point for the whole repo
                # (ops/dispatch.py); mesh=None — decode is mesh-local
                from gke_ray_train_tpu.ops.dispatch import (
                    attention_dispatch)
                out = attention_dispatch(
                    "flash", q, k_cache.astype(dtype),
                    v_cache.astype(dtype),
                    q_positions=positions, kv_positions=kv_positions,
                    causal=True, sliding_window=window,
                    scale=cfg.attn_scale, logit_softcap=cfg.attn_softcap)
            else:
                out = dot_product_attention(
                    q, k_cache.astype(dtype), v_cache.astype(dtype),
                    masks[kind], scale=cfg.attn_scale,
                    logit_softcap=cfg.attn_softcap)
            h = _proj(out.reshape(B, T, H * hd), lp["wo"], lr("wo"),
                      lora_scale, dtype)
            if cfg.post_block_norm:
                h = rms_norm(h, lp["attn_post_norm"], eps=eps,
                             scale_plus_one=sp1)
            x = x + h
            h = rms_norm(x, lp["mlp_norm"], eps=eps, scale_plus_one=sp1)
            if cfg.n_experts > 0:
                # routed expert MLP; the load-balance aux is a training
                # loss term and is discarded at inference
                from gke_ray_train_tpu.ops.moe import moe_mlp
                h, _ = moe_mlp(h, lp["router"], lp["w_gate"], lp["w_up"],
                               lp["w_down"], cfg, dtype)
            else:
                h = _mlp(h, lp, cfg, dtype, lora_p=lo,
                         lora_scale=lora_scale)
            if cfg.post_block_norm:
                h = rms_norm(h, lp["mlp_post_norm"], eps=eps,
                             scale_plus_one=sp1)
            x = x + h
            new_cache.append({"k": k_cache, "v": v_cache})
        return x, new_cache

    xs = [params["blocks"], cache["blocks"]]
    if lora is not None:
        xs.append(lora["blocks"])
    x, new_blocks = jax.lax.scan(repeat_body, x, tuple(xs))

    x = rms_norm(x, params["final_norm"], eps=eps, scale_plus_one=sp1)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("btd,dv->btv", x, head.astype(dtype),
                        preferred_element_type=jnp.float32)
    if cfg.logit_softcap is not None:
        logits = jnp.tanh(logits / cfg.logit_softcap) * cfg.logit_softcap
    return logits, {"blocks": new_blocks}


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "eos_ids",
                                   "lora_scale"))
def greedy_generate_cached(params: Params, prompt: jnp.ndarray,
                           prompt_len: jnp.ndarray, cfg: ModelConfig, *,
                           max_new_tokens: int = 64,
                           eos_ids: Sequence[int] = (),
                           lora: Optional[Params] = None,
                           lora_scale: float = 1.0) -> jnp.ndarray:
    """Drop-in replacement for decode.greedy_generate (same signature,
    same outputs) running prefill + cached single-token steps.

    prompt: [B, L] right-padded buffer with L >= prompt_len + max_new;
    the prompt region (L - max_new_tokens) is prefilled in one pass.

    The prefill width is rounded UP to a 128 multiple (capped at L) so
    the flash-prefill gate engages for any max_new_tokens. Safe by the
    same invariant right-padding already relies on: garbage K/V written
    past prompt_len sit at positions strictly above every query's until
    the decode loop overwrites them (one slot per step, always writing
    slot ``lens`` before attending), so they are never unmasked.
    """
    B, L = prompt.shape
    Lp = max(L - max_new_tokens, 1)
    if L % 128 == 0 and Lp > 1:
        # only when the flash gate can actually engage (max_len = L must
        # tile too) — otherwise rounding just widens the dense prefill
        Lp = min(L, ((Lp + 127) // 128) * 128)
    eos = jnp.asarray(list(eos_ids) or [-1], jnp.int32)

    cache = init_cache(cfg, B, L)
    logits, cache = forward_step(
        params, prompt[:, :Lp], cfg, cache,
        jnp.zeros((B,), jnp.int32), lora=lora, lora_scale=lora_scale)
    idx = jnp.clip(prompt_len - 1, 0, Lp - 1)
    cur_tok = jnp.argmax(
        jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0, :],
        axis=-1).astype(jnp.int32)

    def cond(state):
        buf, lens, done, cache, cur_tok, step = state
        return (step < max_new_tokens) & ~jnp.all(done)

    def body(state):
        buf, lens, done, cache, cur_tok, step = state
        write_pos = jnp.clip(lens, 0, L - 1)
        buf = jnp.where(
            (~done)[:, None] & (jnp.arange(L)[None, :] ==
                                write_pos[:, None]),
            cur_tok[:, None], buf)
        logits, cache = forward_step(
            params, cur_tok[:, None], cfg, cache, lens,
            lora=lora, lora_scale=lora_scale)
        next_tok = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        now_eos = jnp.any(cur_tok[:, None] == eos[None, :], axis=-1)
        new_lens = jnp.where(done | (lens >= L), lens, lens + 1)
        new_done = done | now_eos | (new_lens >= L)
        return buf, new_lens, new_done, cache, next_tok, step + 1

    buf, _, _, _, _, _ = jax.lax.while_loop(
        cond, body, (prompt, prompt_len, jnp.zeros((B,), bool), cache,
                     cur_tok, jnp.asarray(0)))
    return buf
