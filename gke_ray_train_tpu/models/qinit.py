"""Quantize-during-init for QLoRA base weights.

The reference acquires its QLoRA base through ``BitsAndBytesConfig`` so
full-precision weights never sit in accelerator memory
(/root/reference/ray-jobs/fine_tune_llama_ray.py:216-227,240). The
stream-load path here does the same (ckpt/hf_io.py: one layer-slice on
device at a time); this module covers the third acquisition path —
RANDOM init at full model dims (offline smoke / bench runs with no
checkpoint) — which otherwise materializes the full fp32 tree before
quantizing and OOMs an 8B model on one 16 GB v5e chip.

Design: each projection leaf [R, D, F] is built inside one jit by
``lax.map`` over its R repeat-slices — XLA serializes the map body, so
peak memory is a single bf16 slice plus the int8 codes / fp32 scales
being accumulated (~4.5 GB total for 8B NF4 instead of 32 GB fp32).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.transformer import Params, param_specs
from gke_ray_train_tpu.ops.quant import (
    DEFAULT_GROUP, QTensor, QUANT_TARGETS, quant_specs, quantize_tensor)


def _quantized_leaf(shape, std, kind, group, key,
                    out_shardings=None) -> QTensor:
    R = shape[0]

    def one(k):
        w = (jax.random.truncated_normal(k, -3, 3, shape[1:], jnp.float32)
             * std).astype(jnp.bfloat16)
        qt = quantize_tensor(w[None], kind, group)
        return qt.codes[0], qt.scales[0]

    kw = {} if out_shardings is None else {"out_shardings": out_shardings}
    codes, scales = jax.jit(
        lambda ks: jax.lax.map(one, ks), **kw)(jax.random.split(key, R))
    return QTensor(codes, scales, kind, group)


def _dense_leaf(make, sharding=None):
    kw = {} if sharding is None else {"out_shardings": sharding}
    return jax.jit(make, **kw)()


def init_quantized_params(cfg: ModelConfig, key: jax.Array, *,
                          kind: str = "nf4", group: int = DEFAULT_GROUP,
                          mesh: Optional[Mesh] = None,
                          targets=QUANT_TARGETS) -> Params:
    """Sharding-invariant entry: same draws meshed or not (see
    parallel.sharding.sharding_invariant_rng and make_train_state)."""
    from gke_ray_train_tpu.parallel.sharding import sharding_invariant_rng
    with sharding_invariant_rng():
        return _init_quantized_params(cfg, key, kind=kind, group=group,
                                      mesh=mesh, targets=targets)


def _init_quantized_params(cfg: ModelConfig, key: jax.Array, *,
                           kind: str = "nf4", group: int = DEFAULT_GROUP,
                           mesh: Optional[Mesh] = None,
                           targets=QUANT_TARGETS) -> Params:
    """init_params with the targeted projections quantized as they are
    created. Same tree structure, same init distribution (truncated
    normal, 1/sqrt(2*n_layers) residual-writer scaling), same sharding
    rules (quant_specs adapts each spec to the codes/scales shapes).
    Norms/embed/lm_head stay full precision, like the reference's bnb
    pass which only rewrites the proj modules.

    MoE configs take the simple path (full init, then quantize the
    expert bank): the expert leaves are 4-D and per-slice streaming
    buys less there since each expert is 1/E the FFN size."""
    if cfg.n_experts > 0:
        from gke_ray_train_tpu.models.transformer import init_params
        from gke_ray_train_tpu.ops.quant import quantize_params
        from gke_ray_train_tpu.parallel.sharding import tree_shardings
        if mesh is not None:
            p_shard = tree_shardings(mesh, param_specs(cfg))
            params = jax.jit(lambda k: init_params(cfg, k),
                             out_shardings=p_shard)(key)
        else:
            params = init_params(cfg, key)
        return quantize_params(params, kind=kind, group=group,
                               targets=targets)
    pdt = jnp.dtype(cfg.param_dtype)
    hd = cfg.resolved_head_dim
    D, F, H, K, R = (cfg.d_model, cfg.d_ff, cfg.n_heads, cfg.n_kv_heads,
                     cfg.n_repeats)
    depth_scale = 1.0 / math.sqrt(2 * cfg.n_layers)
    std = 0.02
    proj_shapes = {
        "wq": ((R, D, H * hd), std),
        "wk": ((R, D, K * hd), std),
        "wv": ((R, D, K * hd), std),
        "wo": ((R, H * hd, D), std * depth_scale),
        "w_gate": ((R, D, F), std),
        "w_up": ((R, D, F), std),
        "w_down": ((R, F, D), std * depth_scale),
    }
    specs = param_specs(cfg)

    def q_shardings(spec, shape):
        """NamedShardings for (codes, scales) of a target leaf."""
        if mesh is None:
            return None
        probe = jax.eval_shape(
            partial(quantize_tensor, kind=kind, group=group),
            jax.ShapeDtypeStruct((1,) + shape[1:], jnp.bfloat16))
        probe = QTensor(
            jax.ShapeDtypeStruct((shape[0],) + probe.codes.shape[1:],
                                 probe.codes.dtype),
            jax.ShapeDtypeStruct((shape[0],) + probe.scales.shape[1:],
                                 probe.scales.dtype),
            kind, group)
        qs = quant_specs(spec, probe, mesh)
        return (NamedSharding(mesh, qs.codes), NamedSharding(mesh, qs.scales))

    def sharding_for(spec):
        return None if mesh is None else NamedSharding(mesh, spec)

    def normal_maker(shape, s, k):
        return lambda: (jax.random.truncated_normal(
            k, -3, 3, shape, jnp.float32) * s).astype(pdt)

    def norm_maker(shape):
        return lambda: (jnp.zeros(shape, pdt) if cfg.norm_scale_plus_one
                        else jnp.ones(shape, pdt))

    keys = iter(jax.random.split(key, 16 * len(cfg.block_pattern) + 4))

    def block(p):
        bspec = specs["blocks"][p]
        out = {}
        for name in ("attn_norm", "mlp_norm"):
            out[name] = _dense_leaf(norm_maker((R, D)),
                                    sharding_for(bspec[name]))
        if cfg.post_block_norm:
            for name in ("attn_post_norm", "mlp_post_norm"):
                out[name] = _dense_leaf(norm_maker((R, D)),
                                        sharding_for(bspec[name]))
        if cfg.attn_qkv_bias:
            # Qwen-2 q/k/v biases: zero-init, full precision (never a
            # quant target), same leaves init_params creates
            for name, dim in (("bq", H * hd), ("bk", K * hd),
                              ("bv", K * hd)):
                out[name] = _dense_leaf(
                    lambda dim=dim: jnp.zeros((R, dim), pdt),
                    sharding_for(bspec[name]))
        for name, (shape, s) in proj_shapes.items():
            k = next(keys)
            if name in targets:
                out[name] = _quantized_leaf(
                    shape, s, kind, group, k,
                    out_shardings=q_shardings(bspec[name], shape))
            else:
                out[name] = _dense_leaf(
                    normal_maker(shape, s, k),
                    sharding_for(bspec[name]))
        return out

    params: Params = {
        "embed": _dense_leaf(
            normal_maker((cfg.vocab_size, D), 0.02, next(keys)),
            sharding_for(specs["embed"])),
        "blocks": [block(p) for p in range(len(cfg.block_pattern))],
        "final_norm": _dense_leaf(norm_maker((D,)),
                                  sharding_for(specs["final_norm"])),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_leaf(
            normal_maker((D, cfg.vocab_size), 0.02, next(keys)),
            sharding_for(specs["lm_head"]))
    return params
