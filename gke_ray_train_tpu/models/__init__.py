from gke_ray_train_tpu.models.config import (  # noqa: F401
    ModelConfig, llama2_7b, llama2_13b, llama2_70b, llama3_8b, llama3_70b, mistral_7b, mixtral_8x7b,
    gemma2_9b, qwen2_7b, basic_lm, tiny, PRESETS, preset_for_model_id)
from gke_ray_train_tpu.models.transformer import (  # noqa: F401
    init_params, param_specs, forward)
from gke_ray_train_tpu.models.decode import greedy_generate  # noqa: F401
from gke_ray_train_tpu.models.kvcache import (  # noqa: F401
    forward_step, greedy_generate_cached, init_cache)


def __getattr__(name):
    # lazy (PEP 562): qinit imports ops.quant, which imports
    # models.config — an eager import here would make
    # `import gke_ray_train_tpu.ops.quant` re-enter ops.quant through
    # this package __init__ while it is still initializing
    if name == "init_quantized_params":
        from gke_ray_train_tpu.models.qinit import init_quantized_params
        return init_quantized_params
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
