"""obs/ — unified run telemetry (ISSUE 11) + causal tracing (ISSUE 14).

- ``events``  — structured per-rank JSONL event stream (pinned schema)
- ``metrics`` — counters/gauges/histograms + Prometheus/JSON exporters
- ``capture`` — anomaly-triggered one-shot ``jax.profiler`` captures
- ``trace``   — causal spans (pinned schema): the ledger-timed attempt
  boundaries + serve request lifecycles, one trace per run
- ``critical``— critical-path attribution over the merged span DAG,
  reconciled against the goodput ledger
- ``runtime`` — the per-process session everything emits through
- ``report``  — one merged, reconciled report per run
  (CLI: ``python -m gke_ray_train_tpu.obs report <run_dir>``)
- ``diff``    — the cross-run regression gate
  (CLI: ``python -m gke_ray_train_tpu.obs diff <A> <B>``; checked-in
  ledgers under ``tests/regressions/``)

Stdlib-only at import: the driver, the supervisor, the report and the
diff run without jax.
"""

from gke_ray_train_tpu.obs.events import (  # noqa: F401
    EVENT_KINDS, STAMP_FIELDS, EventLog, iter_events, validate_event)
from gke_ray_train_tpu.obs.metrics import (  # noqa: F401
    METRIC_NAMES, MetricsRegistry)
from gke_ray_train_tpu.obs.runtime import (  # noqa: F401
    active, emit, registry, resolve_obs_dir, span_add, start_attempt,
    end_attempt, tracing)
from gke_ray_train_tpu.obs.trace import (  # noqa: F401
    SPAN_NAMES, SPAN_STAMP, SpanLog, iter_spans, validate_span)
