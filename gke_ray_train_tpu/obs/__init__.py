"""obs/ — unified run telemetry (ISSUE 11).

- ``events``  — structured per-rank JSONL event stream (pinned schema)
- ``metrics`` — counters/gauges/histograms + Prometheus/JSON exporters
- ``capture`` — anomaly-triggered one-shot ``jax.profiler`` captures
- ``runtime`` — the per-process session everything emits through
- ``report``  — one merged, reconciled report per run
  (CLI: ``python -m gke_ray_train_tpu.obs report <run_dir>``)

Stdlib-only at import: the driver, the supervisor, and the report run
without jax.
"""

from gke_ray_train_tpu.obs.events import (  # noqa: F401
    EVENT_KINDS, STAMP_FIELDS, EventLog, iter_events, validate_event)
from gke_ray_train_tpu.obs.metrics import (  # noqa: F401
    METRIC_NAMES, MetricsRegistry)
from gke_ray_train_tpu.obs.runtime import (  # noqa: F401
    active, emit, registry, resolve_obs_dir, start_attempt, end_attempt)
