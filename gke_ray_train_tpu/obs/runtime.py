"""The per-process obs session — one configure point, no plumbing.

``rayint/trainer.py::_run_worker`` starts an attempt-scoped session
(:func:`start_attempt`) and the driver a run-scoped one
(:func:`start_driver`); everything else — the train loop, the preempt
exit, the elastic replan, the serve engine, the entries — just calls
:func:`emit` / :func:`registry` / :func:`active`, which no-op when
nothing is configured (bare ``run_training`` in tests and benches pays
one ``is None`` check).

Resolution (:func:`resolve_obs_dir`): an explicit ``OBS_DIR`` (plan
field ``obs_dir``) wins; otherwise the run's output dir is used
(``OUTPUT_DIR_BASE`` for the fine-tune entry, ``storage_path`` +
``run_name`` for the pre-train entry) with an ``obs/`` suffix; with
neither resolvable — or ``OBS=0`` — the session stays off. Identity
rides the env: the trainer mints ``OBS_RUN_ID`` once per ``fit()`` and
stamps ``OBS_ATTEMPT`` per attempt, so every rank of every attempt
writes into one correlated stream.

Stdlib-only at import (driver side has no jax); capture and the
jax.monitoring listener import lazily inside the session.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Any, Dict, Optional, Union

from gke_ray_train_tpu.obs import events as events_mod
from gke_ray_train_tpu.obs import metrics as metrics_mod
from gke_ray_train_tpu.obs.events import EventLog, events_path
from gke_ray_train_tpu.obs.metrics import (
    MetricsRegistry, export_serve_stats, pull_jax_counters)
from gke_ray_train_tpu.obs import trace as trace_mod
from gke_ray_train_tpu.obs.trace import SpanLog, new_span_id, spans_path

logger = logging.getLogger(__name__)

RUN_ID_ENV = "OBS_RUN_ID"
ATTEMPT_ENV = "OBS_ATTEMPT"
# the causal parent of this process's attempt span (obs/trace.py): the
# driver mints one span id per attempt and forwards it to every worker
# through the same env path as the run/attempt identity, so worker
# attempt spans parent under the driver's and the merged cross-rank
# span DAG is connected
PARENT_SPAN_ENV = "OBS_PARENT_SPAN"

_active: Optional["ObsRun"] = None


def new_run_id() -> str:
    return uuid.uuid4().hex[:10]


def _knob(name: str, config: Optional[dict], default: str) -> str:
    """config key > env > default (every knob's precedence)."""
    if config is not None and name in config:
        return str(config[name])
    return os.environ.get(name, default)


def _truthy(raw) -> bool:
    """The one falsey-spelling set for every default-on obs knob
    (OBS / OBS_CAPTURE / TRACE) — four call sites, one dialect."""
    return str(raw).strip().lower() not in ("0", "false", "no", "off")


def resolve_obs_dir(plan=None, config: Optional[dict] = None
                    ) -> Optional[str]:
    """The obs dir for this run, or None (= obs off). Precedence:
    plan.obs_dir / OBS_DIR > OUTPUT_DIR_BASE/obs > storage_path[/run_
    name]/obs. ``plan.obs=False`` / OBS=0 disables regardless."""
    config = config or {}
    enabled = True
    explicit = None
    if plan is not None:
        enabled = bool(getattr(plan, "obs", True))
        explicit = getattr(plan, "obs_dir", None)
    else:
        enabled = _truthy(config.get("OBS", os.environ.get("OBS", "1")))
        explicit = config.get("OBS_DIR", os.environ.get("OBS_DIR"))
    if not enabled:
        return None
    if explicit:
        return str(explicit)
    base = config.get("OUTPUT_DIR_BASE")
    if base:
        return os.path.join(str(base), "obs")
    storage = config.get("storage_path")
    if storage:
        return os.path.join(str(storage),
                            str(config.get("run_name", "")), "obs")
    return None


def current_backend() -> Optional[str]:
    """The backend tag observed-row producers stamp (ISSUE 16): the
    honest answer to "what hardware produced this measurement".
    ``cpu-fallback`` when the run itself declared it is a fallback
    (bench.py's BENCH_CPU_FALLBACK contract), else the live jax
    backend name — lazy-imported so the stdlib-only driver side can
    call this and get None rather than an import error. The point of
    the stamp: a cpu-fallback number must be REFUSABLE at autotune
    ingest, so it can never calibrate a TPU ChipSpec."""
    if os.environ.get("BENCH_CPU_FALLBACK") == "1":
        return "cpu-fallback"
    try:
        import jax
        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 - no jax / dead backend = no tag
        return None


class ObsRun:
    """One configured obs session: an event log, the process metrics
    registry, and (worker side) the anomaly capture manager."""

    def __init__(self, obs_dir: str, *, run_id: str, attempt: int,
                 rank: Union[int, str], slice_index: Optional[int],
                 plan_fingerprint: Optional[str],
                 capture=None, trace: bool = True):
        self.obs_dir = obs_dir
        self.rank = rank
        self.events = EventLog(events_path(obs_dir, rank),
                               run_id=run_id, attempt=attempt, rank=rank,
                               slice_index=slice_index,
                               plan_fingerprint=plan_fingerprint)
        self.registry = MetricsRegistry(labels={
            "run_id": run_id, "attempt": str(attempt), "rank": str(rank),
            **({"slice": str(slice_index)}
               if slice_index is not None else {})})
        self.capture = capture
        # causal span stream (obs/trace.py): one attempt span per
        # session, parented under the driver's (OBS_PARENT_SPAN) when
        # one exists; leaf spans default-parent under the attempt span.
        # The span is OPENED here and written at finish() — a killed
        # attempt simply never lands it, which is itself the signal.
        self.spans: Optional[SpanLog] = None
        self.attempt_span_id: Optional[str] = None
        self._attempt_parent = os.environ.get(PARENT_SPAN_ENV) or None
        self._attempt_t0 = time.time()
        if trace:
            self.spans = SpanLog(spans_path(obs_dir, rank),
                                 run_id=run_id, attempt=attempt,
                                 rank=rank, slice_index=slice_index)
            self.attempt_span_id = new_span_id()
        self._closed = False

    # -- loop hooks (hot-path budget: host floats only) ----------------

    def note_step(self, step: int, iter_s: float, wait_s: float) -> None:
        self.events.set_step(step)
        if self.capture is not None:
            self.capture.note_step(step, iter_s, wait_s)
        else:
            # captures off = detection off, but the per-step timing
            # metrics must not go blind with them
            self.registry.counter("steps_total").inc()
            self.registry.histogram("step_time_s").observe(iter_s)
            if wait_s > 0:
                self.registry.histogram("data_wait_s").observe(wait_s)

    def log_metrics(self, step: int, metrics: Dict[str, Any],
                    epoch: Optional[int] = None) -> None:
        """Log-cadence sink: gauges from the already-fetched host
        metrics dict, one ``step`` event, and a file export — all at
        ``log_every`` rate, never per step."""
        self.registry.set_many(metrics)
        pull_jax_counters(self.registry)
        payload = {k: metrics[k] for k in (
            "loss", "learning_rate", "grad_norm",
            "tokens_per_sec_per_chip", "mfu", "data_stall_frac")
            if k in metrics}
        self.emit("step", step=step, epoch=epoch, **payload)
        self.export()

    def note_cost_report(self, report) -> None:
        """Network gauges from a StepCostReport something else already
        computed (the AOT build in perf/cache.py, the serve engine's
        executable_info) — never a second compile-time analysis. One
        call per attempt; exported with the next registry flush."""
        self.registry.set_many({"ici_bytes": getattr(report, "ici_bytes", 0),
                                "dcn_bytes": getattr(report, "dcn_bytes", 0)})

    def note_serve(self, stats: Dict[str, Any],
                   replica: Optional[int] = None) -> None:
        export_serve_stats(self.registry, stats)
        self.emit("serve_drained", replica=replica, stats={
            k: stats.get(k) for k in (
                "iterations", "refills", "completed", "batch_occupancy",
                "p50_token_latency_s", "p99_token_latency_s")})
        self.export()

    def finish(self, status: str, ledger: Optional[dict] = None) -> None:
        """Attempt exit (every path): ledger terms into the registry,
        a ``worker_exit`` event, final export, close."""
        if self._closed:
            return
        if self.capture is not None:
            self.capture.close()
        if ledger:
            from gke_ray_train_tpu.train.metrics import ledger_metrics
            self.registry.set_many(ledger_metrics(ledger))
        pull_jax_counters(self.registry)
        self.emit("worker_exit", status=status, goodput=ledger)
        self.export()
        if self.spans is not None:
            now = time.time()
            try:
                self.spans.emit("attempt", now - self._attempt_t0,
                                t1=now, span_id=self.attempt_span_id,
                                parent_id=self._attempt_parent,
                                status=status)
            except Exception as e:  # noqa: BLE001 - IO best-effort
                logger.warning("obs attempt span dropped: %s", e)
            self.spans.close()
        self.events.close()
        self._closed = True

    # -- primitives ----------------------------------------------------

    def emit(self, kind: str, step: Optional[int] = None,
             **payload: Any) -> None:
        try:
            self.events.emit(kind, step=step, **payload)
        except events_mod.EventError:
            raise            # schema violations are bugs, not telemetry
        except Exception as e:  # noqa: BLE001 - IO must not kill a run
            logger.warning("obs event %s dropped: %s", kind, e)

    def span_add(self, name: str, dur_s: float, *,
                 t1: Optional[float] = None,
                 step: Optional[int] = None,
                 parent_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 **attrs: Any) -> Optional[str]:
        """Record one finished leaf span (obs/trace.py), parented under
        this attempt's span unless told otherwise. ``dur_s`` is the
        caller's own measurement — instrumented sites pass the exact
        float the goodput ledger booked, which is what lets
        ``obs/critical.py`` reconcile the two streams exactly. Returns
        the span id (for child spans), or None when tracing is off."""
        if self.spans is None:
            return None
        try:
            rec = self.spans.emit(
                name, dur_s, t1=t1, step=step, span_id=span_id,
                parent_id=(parent_id if parent_id is not None
                           else self.attempt_span_id),
                **attrs)
            return rec["span_id"]
        except trace_mod.SpanError:
            raise            # schema violations are bugs, not telemetry
        except Exception as e:  # noqa: BLE001 - IO must not kill a run
            logger.warning("obs span %s dropped: %s", name, e)
            return None

    def export(self) -> None:
        try:
            self.registry.export(self.obs_dir, self.rank)
        except Exception as e:  # noqa: BLE001
            logger.warning("obs metrics export failed: %s", e)


# ---------------------------------------------------------------------------
# module-level session
# ---------------------------------------------------------------------------

def active() -> Optional[ObsRun]:
    return _active


def emit(kind: str, step: Optional[int] = None, **payload: Any) -> None:
    """Emit through the active session; a no-op when none is
    configured — the one line every instrumented module calls."""
    if _active is not None:
        _active.emit(kind, step=step, **payload)


def registry() -> Optional[MetricsRegistry]:
    return _active.registry if _active is not None else None


def note_cost_report(report) -> None:
    """Module-level twin of :meth:`ObsRun.note_cost_report` — no-op
    unconfigured, like :func:`emit`."""
    if _active is not None:
        _active.note_cost_report(report)


def span_add(name: str, dur_s: float, **kw: Any) -> Optional[str]:
    """Module-level twin of :meth:`ObsRun.span_add` — the one line
    every instrumented module calls; no-op (None) when no session is
    configured or tracing is off."""
    if _active is not None:
        return _active.span_add(name, dur_s, **kw)
    return None


def tracing() -> bool:
    """True when the active session records spans — lets hot-ish call
    sites skip building attr dicts for nothing."""
    return _active is not None and _active.spans is not None


def start_attempt(plan=None, config: Optional[dict] = None, *,
                  rank: Optional[int] = None,
                  slice_index: Optional[int] = None,
                  obs_dir: Optional[str] = None) -> Optional[ObsRun]:
    """Worker-side session for one attempt (called by ``_run_worker``
    and usable directly by tests/benches). Returns None when obs is
    off or no dir resolves. Also prefixes the stdlib text logs with
    the same correlation fields (``logging_utils``)."""
    global _active
    end_attempt("replaced")      # a retry must not inherit the old log
    obs_dir = obs_dir or resolve_obs_dir(plan, config)
    run_id = os.environ.get(RUN_ID_ENV) or new_run_id()
    attempt = int(os.environ.get(ATTEMPT_ENV, "1") or 1)
    rank = int(os.environ.get("PROCESS_ID", "0")) if rank is None \
        else int(rank)
    if obs_dir is None:
        return None
    # the log prefix exists to JOIN text logs with the event stream —
    # installed only when a stream exists (and cleared by end_attempt)
    from gke_ray_train_tpu.logging_utils import configure_run_logging
    configure_run_logging(run_id, attempt, rank)
    if slice_index is None:
        slice_index = _rank_slice(rank, config)
    fp = None
    if plan is not None:
        try:
            fp = plan.fingerprint()
        except Exception:  # noqa: BLE001 - provenance is best-effort
            pass
    capture = None
    if plan is not None:        # validated fields
        cap_on = bool(getattr(plan, "obs_capture", True))
        budget = int(getattr(plan, "obs_capture_budget", 4))
        trace_on = bool(getattr(plan, "trace", True))
    else:
        # config key wins over env, and a malformed value DEGRADES
        # with a warning — telemetry knobs must never kill an attempt
        # (the ELASTIC_N_DEVICES convention)
        cap_on = _truthy(_knob("OBS_CAPTURE", config, "1"))
        raw = _knob("OBS_CAPTURE_BUDGET", config, "4")
        try:
            budget = int(raw)
        except (TypeError, ValueError):
            logger.warning("OBS_CAPTURE_BUDGET=%r is not an int; "
                           "using 4", raw)
            budget = 4
        trace_on = _truthy(_knob("TRACE", config, "1"))
    run = ObsRun(obs_dir, run_id=run_id, attempt=attempt, rank=rank,
                 slice_index=slice_index, plan_fingerprint=fp,
                 trace=trace_on)
    if cap_on:
        from gke_ray_train_tpu.obs.capture import CaptureManager
        capture = CaptureManager(obs_dir, emit_fn=run.emit,
                                 registry=run.registry, budget=budget)
        run.capture = capture
    _active = run
    logger.info("obs: events -> %s (run %s attempt %d rank %s%s)",
                run.events.path, run_id, attempt, rank,
                f" slice {slice_index}" if slice_index is not None
                else "")
    return run


def end_attempt(status: str = "ok") -> None:
    """Seal the active worker session (idempotent) and drop the log
    prefix — outside an attempt there is no run context to stamp."""
    global _active
    from gke_ray_train_tpu.logging_utils import clear_run_logging
    clear_run_logging()
    if _active is not None:
        run, _active = _active, None
        try:
            from gke_ray_train_tpu.rayint.context import get_context
            ledger = get_context().goodput
        except Exception:  # noqa: BLE001
            ledger = None
        run.finish(status, ledger)


def _rank_slice(rank: int, config: Optional[dict]) -> Optional[int]:
    """Rank -> slice index through the one contract function
    (parallel/mesh.py). None when no slice identity exists (single
    slice, or a non-tiling layout)."""
    try:
        num_slices = int((config or {}).get(
            "NUM_SLICES", os.environ.get("NUM_SLICES", "1")))
        n = int(os.environ.get("NUM_PROCESSES", "1"))
        if num_slices <= 1 or n <= 1:
            return None
        from gke_ray_train_tpu.parallel.mesh import slice_assignments
        assign = slice_assignments(list(range(n)), num_slices)
        return assign[rank] if len(set(assign)) > 1 else None
    except Exception:  # noqa: BLE001 - identity is best-effort
        return None


# ---------------------------------------------------------------------------
# driver side (rayint/trainer.py fit loop)
# ---------------------------------------------------------------------------

class DriverObs:
    """Run-scoped driver session: the ``attempt_end`` / ``run_end``
    reconciliation stream plus the supervisor heartbeat export — and,
    with tracing on, the span skeleton the worker spans hang off: one
    ``run`` root span and one ``attempt`` span per attempt, whose id
    is forwarded to the workers as ``OBS_PARENT_SPAN``."""

    def __init__(self, obs_dir: str, run_id: str, trace: bool = True):
        self.obs_dir = obs_dir
        self.run_id = run_id
        self.events = EventLog(events_path(obs_dir, "driver"),
                               run_id=run_id, attempt=0, rank="driver")
        self.spans: Optional[SpanLog] = None
        self.run_span_id: Optional[str] = None
        self.attempt_span_id: Optional[str] = None
        self._run_t0 = time.time()
        self._attempt_t0: Optional[float] = None
        self._run_status: Optional[str] = None
        if trace:
            self.spans = SpanLog(spans_path(obs_dir, "driver"),
                                 run_id=run_id, attempt=0, rank="driver")
            self.run_span_id = new_span_id()

    def begin_attempt(self, attempt: int) -> Optional[str]:
        """Mint (and remember) the span id for the attempt ABOUT TO
        run — the trainer stamps it into every worker's env before the
        workers launch; the span itself lands at ``note_attempt``."""
        if self.spans is None:
            return None
        self.attempt_span_id = new_span_id()
        self._attempt_t0 = time.time()
        self.spans.attempt = int(attempt)
        return self.attempt_span_id

    def note_attempt(self, attempt: int, entry: Dict[str, Any],
                     plan_fingerprint: Optional[str] = None) -> None:
        self.events.attempt = int(attempt)
        self.events.plan_fingerprint = (
            entry.get("plan_fingerprint") or plan_fingerprint)
        self.events.emit(
            "attempt_end", step=entry.get("step"),
            status=entry.get("status"), goodput=entry.get("goodput"),
            event=entry.get("event"), pool=entry.get("pool"),
            error=entry.get("error"),
            resumed_step=entry.get("resumed_step"),
            ckpt_save_s=entry.get("ckpt_save_s"))
        if self.spans is not None and self.attempt_span_id is not None:
            now = time.time()
            t0 = self._attempt_t0 if self._attempt_t0 is not None else now
            self.spans.emit("attempt", now - t0, t1=now,
                            span_id=self.attempt_span_id,
                            parent_id=self.run_span_id,
                            status=entry.get("status"))
            self.attempt_span_id = None
            self._attempt_t0 = None

    def note_run_end(self, result) -> None:
        self._run_status = result.status
        self.events.emit("run_end", status=result.status,
                         attempts=result.attempts,
                         preemptions=result.preemptions,
                         goodput=result.goodput)

    def note_stall(self, stalled, timeout_s: float,
                   attempt: Optional[int] = None) -> None:
        if attempt is not None:
            # stamp the attempt that stalled — note_attempt for it has
            # not run yet, so the log still carries the previous one
            self.events.attempt = int(attempt)
        self.events.emit("stall", stalled=[list(s) for s in stalled],
                         timeout_s=timeout_s)
        self.events.emit("anomaly", **{"class": "stalled_rank"},
                         detail={"stalled": [list(s) for s in stalled]},
                         trigger_step=max((s[1] for s in stalled),
                                          default=-1))

    def export_supervisor(self, view: Dict[str, Any]) -> None:
        """HeartbeatBoard.metrics_view() -> <obs_dir>/supervisor.json
        (atomic) — the per-rank last-beat-age/slice/step export both
        the scraper and ``obs report`` consume."""
        import json
        try:
            os.makedirs(self.obs_dir, exist_ok=True)
            path = os.path.join(self.obs_dir, "supervisor.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"ts": time.time(), "run_id": self.run_id,
                           **view}, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001
            logger.warning("supervisor export failed: %s", e)

    def close(self) -> None:
        if self.spans is not None:
            now = time.time()
            try:
                self.spans.attempt = 0
                self.spans.emit("run", now - self._run_t0, t1=now,
                                span_id=self.run_span_id,
                                status=self._run_status)
            except Exception as e:  # noqa: BLE001 - IO best-effort
                logger.warning("obs run span dropped: %s", e)
            self.spans.close()
        self.events.close()


_minted_ids: set = set()


def start_driver(config: Optional[dict] = None,
                 obs_dir: Optional[str] = None) -> Optional[DriverObs]:
    """Driver session for one ``fit()``; mints and exports the shared
    run id so every worker stamps the same one. An id minted by a
    PREVIOUS fit in this process is stale — each fit is its own run —
    but an externally supplied OBS_RUN_ID (a job-level env) is kept."""
    run_id = os.environ.get(RUN_ID_ENV)
    if not run_id or run_id in _minted_ids:
        run_id = new_run_id()
        _minted_ids.add(run_id)
        os.environ[RUN_ID_ENV] = run_id
    obs_dir = obs_dir or resolve_obs_dir(None, config)
    if obs_dir is None:
        return None
    return DriverObs(obs_dir, run_id,
                     trace=_truthy(_knob("TRACE", config, "1")))
