"""``obs diff`` — the cross-run regression gate over telemetry reports
(ISSUE 14 tentpole, part 3).

PR 4/9/12 made compile-time cost numbers pinnable: checked-in JSONs +
a two-sided comparator, re-recorded only on intentional change, CI
enforcing the rest. This module gives RUNTIME telemetry the same
ratchet: a report (``obs/report.py``) flattens to a small dict of
robust scalars — goodput fraction, the ledger terms as fractions of
wall, attempt/preemption/reshard counts, serve p50/p99, and the
critical-path composition — and two such dicts are compared by the
SAME comparator core the budget files use (``perf/compare.py``:
two-sided relative tolerances, per-field overrides recorded in the
checked-in JSON, the offending-term delta printed on a trip).

The checked-in side lives in ``tests/regressions/*.json`` — one file
per recorded drill (the ``BENCH_MODE=elastic`` 8→4→8 run is the
flagship). Re-record after an INTENTIONAL change with
``REGRESSION_UPDATE=1`` (or ``obs diff <run> <ledger> --update``) and
review the JSON diff like code — that diff IS the goodput review.

Why fractions, not seconds: wall-clock varies machine to machine; the
COMPOSITION of an attempt (what share of wall went to restore vs step)
is the stable, reviewable signal — exactly the quantity the goodput
ledger was built to expose. Fields where both sides sit under
:data:`NOISE_FLOOR` are skipped (a 0.4%→0.9% compile share is timing
noise, not a regression; relative tolerances explode near zero).

Stdlib-only, like everything report-side.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from gke_ray_train_tpu.obs.report import LEDGER_TERMS
from gke_ray_train_tpu.perf.compare import compare_dicts

# two-sided relative tolerances per flattened field. Counts are exact
# (a drill that suddenly takes 4 attempts instead of 3 IS the
# regression); composition fractions get wide bands (CPU-mesh timing
# jitter); latencies are loosest (absolute seconds on shared runners).
# A regression ledger can tighten/loosen any of these via its own
# "tolerances" key — recorded beside the numbers, reviewed like code.
DIFF_TOLERANCES: Dict[str, float] = {
    "goodput_frac": 0.35,
    **{f"frac_{t}": 0.60 for t in LEDGER_TERMS},
    "n_attempts": 0.0,
    "preemptions": 0.0,
    "reshards": 0.0,
    # "anomalies" is flattened for the record but NOT gated by default:
    # spike/stall detection is machine-speed dependent — a ledger that
    # wants to pin it adds its own tolerance entry
    "serve_p50_token_latency_s": 2.0,
    "serve_p99_token_latency_s": 2.0,
    **{f"cp_frac_{t}": 0.60 for t in LEDGER_TERMS},
    # autotune calibration drift (autotune/registry.py ingest): the
    # drift-event count is exact — a calibrated cost model tripping
    # the band where the recorded run had zero drift IS the
    # regression; the worst relative error gets a wide band (it only
    # exists when drift fired, and its magnitude is machine-sensitive)
    "autotune_drift_events": 0.0,
    "autotune_drift_stale": 0.0,
    "autotune_drift_max_rel_err": 1.0,
}
# composition fields where both sides below this share are noise
NOISE_FLOOR = 0.02


def flatten_report(report: Dict[str, Any]) -> Dict[str, float]:
    """The comparable scalar surface of one report — every field here
    must be meaningful to compare across machines/runs of the same
    drill (compositions and counts, not absolute seconds)."""
    flat: Dict[str, float] = {}
    g = report.get("goodput") or {}
    wall = float(g.get("wall_s") or 0.0)
    if wall > 0:
        flat["goodput_frac"] = float(
            g.get("goodput_frac", g.get("step_s", 0.0) / wall))
        for t in LEDGER_TERMS:
            flat[f"frac_{t}"] = float(g.get(t, 0.0)) / wall
    flat["n_attempts"] = float(report.get("n_attempts", 0))
    if report.get("preemptions") is not None:
        flat["preemptions"] = float(report["preemptions"])
    flat["reshards"] = float(sum(
        len(a.get("reshard", [])) for a in report.get("attempts", [])))
    flat["anomalies"] = float(len(report.get("anomalies", [])))
    # serving latency: the max across rank exports (a replica's p99 is
    # the fleet's p99)
    for key in ("serve_p50_token_latency_s", "serve_p99_token_latency_s"):
        vals = [doc.get(key) for doc in
                (report.get("metrics") or {}).values()
                if isinstance(doc.get(key), (int, float))]
        if vals:
            flat[key] = float(max(vals))
    # critical-path composition (obs/critical.py): the SPAN-attributed
    # share of total wall per term, summed across attempts — where the
    # attempt spent its gating rank's time, not just that it spent it
    cp_sum: Dict[str, float] = {}
    cp_wall = 0.0
    for a in report.get("attempts", []):
        cp = a.get("critical_path")
        if not cp or not cp.get("wall_s"):
            continue
        cp_wall += float(cp["wall_s"])
        for t, v in (cp.get("span_terms") or {}).items():
            cp_sum[t] = cp_sum.get(t, 0.0) + float(v)
    if cp_wall > 0:
        for t in LEDGER_TERMS:
            if t in cp_sum:
                flat[f"cp_frac_{t}"] = cp_sum[t] / cp_wall
    # autotune feedback scalars (report "autotune" section): the drift
    # counts pin the calibration loop — a model that starts
    # mispredicting real runs shows up as a count where the recorded
    # drill had none
    at = report.get("autotune") or {}
    if at:
        flat["autotune_drift_events"] = float(at.get("drift_events", 0))
        flat["autotune_drift_stale"] = float(at.get("drift_stale", 0))
        if at.get("drift_max_rel_err") is not None:
            flat["autotune_drift_max_rel_err"] = \
                float(at["drift_max_rel_err"])
    return {k: round(v, 6) for k, v in flat.items()}


def _drop_noise(a: Dict[str, float], b: Dict[str, float]
                ) -> Tuple[Dict[str, float], Dict[str, float]]:
    """Remove composition fields where BOTH sides sit under the noise
    floor — relative tolerances are meaningless at ~0, and a 0.004 vs
    0.011 compile share is scheduler jitter, not a regression."""
    def keep(k: str) -> bool:
        if not (k.startswith("frac_") or k.startswith("cp_frac_")):
            return True
        return abs(a.get(k, 0.0)) >= NOISE_FLOOR \
            or abs(b.get(k, 0.0)) >= NOISE_FLOOR
    kept = [k for k in set(a) | set(b) if keep(k)]
    return ({k: v for k, v in a.items() if k in kept},
            {k: v for k, v in b.items() if k in kept})


def diff_flat(flat_a: Dict[str, Any], flat_b: Dict[str, Any],
              tolerances: Optional[Dict[str, float]] = None
              ) -> List[str]:
    """Violation strings comparing A (the fresh run) against B (the
    recorded side) — the ``perf/budget.py`` comparator shape, reused
    not forked. Empty = within tolerances."""
    a, b = _drop_noise(
        {k: v for k, v in flat_a.items()
         if isinstance(v, (int, float)) and not k.startswith("_")},
        {k: v for k, v in flat_b.items()
         if isinstance(v, (int, float)) and not k.startswith("_")})
    # the recorded side may carry its own per-field overrides, exactly
    # like a budget JSON's "tolerances" key
    budget = dict(b)
    if isinstance(flat_b.get("tolerances"), dict):
        budget["tolerances"] = flat_b["tolerances"]
    viols = compare_dicts(a, budget, tolerances,
                          default_tolerances=DIFF_TOLERANCES)
    # the comparator skips fields absent from either side — safe for
    # budget files (their field set is structural), WRONG for
    # telemetry, where fields are emergent from the run: a recorded
    # cp_frac_* vanishing from the fresh report usually means tracing
    # silently broke (TRACE off, a span-stream bug) — exactly the
    # regression class this gate exists to catch. Noise-floored
    # fields were already dropped from BOTH dicts above, so anything
    # still recorded-but-missing is a real signal.
    gated = dict(DIFF_TOLERANCES)
    gated.update(budget.get("tolerances", {}))
    gated.update(tolerances or {})
    for k in sorted(set(b) - set(a)):
        if k in gated and not k.startswith("_") and k != "tolerances":
            viols.append(
                f"{k}: recorded {b[k]:.4g} but MISSING from the fresh "
                "report — the telemetry that produced it broke or was "
                "turned off")
    return viols


def load_side(path: str) -> Tuple[Dict[str, Any], str]:
    """Resolve one CLI operand into a flat dict: a regression-ledger
    JSON (already flat), a ``report.json``, an obs dir, or a run dir
    (report built on the fly). Returns (flat, label)."""
    from gke_ray_train_tpu.obs.report import build_report
    if os.path.isdir(path):
        return flatten_report(build_report(path)), f"report({path})"
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if "attempts" in doc:          # a written report.json
        return flatten_report(doc), f"report({path})"
    return doc, path               # an already-flat regression ledger


def write_regression(flat: Dict[str, Any], path: str, *,
                     source: str = "",
                     tolerances: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """Record one flattened report as a checked-in regression ledger
    (the ``write_budget`` shape: provenance + re-record note + the
    numbers, reviewed like code)."""
    doc: Dict[str, Any] = {
        "_source": source,
        "_note": ("re-record after an INTENTIONAL change: "
                  "REGRESSION_UPDATE=1 (or `obs diff <run> <ledger> "
                  "--update`) and review this diff like code"),
        **({"tolerances": dict(tolerances)} if tolerances else {}),
        # "tolerances" excluded from the spread: when the A side is
        # itself a flat ledger its own overrides ride in ``flat`` and
        # would silently clobber the reviewed B-side ones the caller
        # explicitly passed to preserve
        **{k: flat[k] for k in sorted(flat)
           if not k.startswith("_") and k != "tolerances"},
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return doc
