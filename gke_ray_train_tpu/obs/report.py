"""One report per run (ISSUE 11 tentpole, part 4).

``build_report(run_dir)`` merges everything a run left behind — the
per-rank event streams, the metric exports, the supervisor heartbeat
export, the capture artifacts, and any bench records — into one JSON
document with a per-attempt timeline: compile / restore / fast-forward
/ stall / step / lost decomposition (the goodput ledger), reshards,
anomalies, and their capture artifacts.

The reconciliation invariant is re-VERIFIED here, not trusted: every
attempt's ledger terms must sum to its wall-clock (the identity
``finish_ledger`` constructs; ``rayint/trainer.py`` computes ``lost_s``
as the attempt-wall residual). A report whose ledgers do not reconcile
is a telemetry bug — the CLI exits 3 so CI catches it.

Stdlib-only (the report runs on machines with no jax — a laptop
pointed at a GCS-FUSE mount).
"""

from __future__ import annotations

import glob
import json
import logging
import os
from typing import Any, Dict, List, Optional

from gke_ray_train_tpu.obs import critical as critical_mod
from gke_ray_train_tpu.obs.events import iter_events
from gke_ray_train_tpu.obs.trace import iter_spans

logger = logging.getLogger(__name__)

# terms must match train/metrics.py LEDGER_TERMS; duplicated as a
# STRING list on purpose — the report must run without jax, and the
# schema contract test pins the two against each other
LEDGER_TERMS = ["compile_s", "restore_s", "fast_forward_s",
                "data_stall_s", "eval_ckpt_stall_s", "ckpt_async_s",
                "peer_restore_s", "step_s", "lost_s"]
RECONCILE_TOL = 1e-6


class ReportError(RuntimeError):
    """The run dir is unreadable or holds no telemetry."""


def find_obs_dir(run_dir: str) -> str:
    """Accept the obs dir itself OR its parent run dir."""
    for cand in (run_dir, os.path.join(run_dir, "obs")):
        if glob.glob(os.path.join(cand, "events-*.jsonl")):
            return cand
    raise ReportError(
        f"no obs telemetry under {run_dir!r} (no events-*.jsonl in it "
        "or its obs/ subdir) — was the run started with OBS enabled?")


def _reconcile(goodput: Optional[dict]) -> Optional[Dict[str, Any]]:
    if not goodput or "wall_s" not in goodput:
        return None
    total = sum(float(goodput.get(t, 0.0)) for t in LEDGER_TERMS)
    wall = float(goodput["wall_s"])
    return {"terms_sum_s": total, "wall_s": wall,
            "residual_s": total - wall,
            "ok": abs(total - wall) <= RECONCILE_TOL * max(1.0, wall)}


def _captures_on_disk(obs_dir: str) -> List[Dict[str, Any]]:
    out = []
    for marker in sorted(glob.glob(
            os.path.join(obs_dir, "captures", "*", "capture.json"))):
        try:
            with open(marker, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        doc["artifact"] = os.path.dirname(marker)
        out.append(doc)
    return out


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _bench_records(obs_dir: str) -> List[dict]:
    out = []
    path = os.path.join(obs_dir, "bench_records.jsonl")
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass
    return out


def build_report(run_dir: str) -> Dict[str, Any]:
    obs_dir = find_obs_dir(run_dir)
    events = list(iter_events(obs_dir))
    if not events:
        raise ReportError(f"no events under {obs_dir!r}")
    run_ids = sorted({e.get("run_id") for e in events if e.get("run_id")})

    # -- attempts: driver attempt_end is authoritative (it carries the
    # FINISHED ledger, lost_s reconciled to the attempt wall); a bare
    # run_training session has only worker_exit streams --------------
    att_events: Dict[int, List[dict]] = {}
    for e in events:
        att_events.setdefault(int(e.get("attempt") or 0), []).append(e)
    ends = [e for e in events if e["kind"] == "attempt_end"]
    if not ends:
        # driverless session: ONE entry per attempt, not per rank — a
        # multi-process run writes a worker_exit per rank, all stamped
        # with the same attempt; summing them would multiply the
        # goodput totals by the world size
        picked: Dict[int, dict] = {}
        for e in events:
            if e["kind"] == "worker_exit":
                picked.setdefault(int(e.get("attempt") or 0), e)
        ends = list(picked.values())
    attempts: List[Dict[str, Any]] = []
    for i, end in enumerate(sorted(ends, key=lambda e: e["ts"]), 1):
        n = int(end.get("attempt") or i)
        end_run_id = end.get("run_id")
        evs = att_events.get(n, [])
        t0 = min((e["ts"] for e in evs), default=end["ts"])
        goodput = end.get("goodput")
        att: Dict[str, Any] = {
            "attempt": n,
            "run_id": end_run_id,
            "status": end.get("status"),
            "plan_fingerprint": end.get("plan_fingerprint"),
            "resumed_step": end.get("resumed_step"),
            "goodput": goodput,
            "reconciliation": _reconcile(goodput),
            "timeline": [
                {"t": round(e["ts"] - t0, 3), "rank": e.get("rank"),
                 "step": e.get("step"), "kind": e["kind"],
                 **{k: v for k, v in e.items()
                    if k not in ("ts", "run_id", "attempt", "rank",
                                 "slice", "step", "plan_fingerprint",
                                 "kind")}}
                for e in evs if e["kind"] not in ("step",)],
            "steps_logged": sum(1 for e in evs if e["kind"] == "step"),
            # the backend the attempt ACTUALLY ran on (first_step
            # stamps it): `autotune ingest` filters on this so a
            # cpu-fallback measurement can never calibrate a TPU
            # ChipSpec — the report carries it through
            "backend": next((e.get("backend") for e in evs
                             if e["kind"] == "first_step"
                             and e.get("backend")), None),
        }
        if end.get("event"):
            att["event"] = end["event"]          # shrink | grow
            att["pool"] = end.get("pool")
        # one entry per actual mesh transition: the plan re-formation
        # (rayint/elastic.py) and the resharded restore
        # (ckpt/manager.py) both witness the same from->to pair —
        # merge them, keeping the richest fields
        reshards: Dict[tuple, dict] = {}
        for e in evs:
            if e["kind"] != "reshard":
                continue
            key = (e.get("from_devices"), e.get("to_devices"))
            merged = reshards.setdefault(key, {})
            for k in ("from_devices", "to_devices", "to_fingerprint",
                      "mesh", "per_device_batch"):
                if e.get(k) is not None:
                    merged[k] = e[k]
        if reshards:
            att["reshard"] = list(reshards.values())
        attempts.append(att)

    # a local-path heartbeat stall is witnessed TWICE — the watchdog's
    # worker-stream anomaly (which may carry the capture) and the
    # driver's note_stall anomaly; merge per (attempt, class,
    # trigger_step) like the reshard twins, preferring the worker's
    # (rank-stamped) record
    seen_anoms: Dict[tuple, dict] = {}
    for e in events:
        if e["kind"] != "anomaly":
            continue
        key = (int(e.get("attempt") or 0), e.get("class"),
               e.get("trigger_step"))
        prev = seen_anoms.get(key)
        if prev is None or prev.get("rank") == "driver":
            seen_anoms[key] = e
    anomalies = list(seen_anoms.values())
    capture_events = [e for e in events if e["kind"] == "capture"]
    captures = _captures_on_disk(obs_dir)

    # anomaly -> capture cross-reference: fire-once means each
    # (attempt, class) pair with an anomaly has AT MOST one capture;
    # count how many anomalies got their artifact
    cap_keys = {(int(e.get("attempt") or 0), e.get("class"))
                for e in capture_events}
    for a in anomalies:
        a_key = (int(a.get("attempt") or 0), a.get("class"))
        a["captured"] = a_key in cap_keys

    # -- metrics: latest export per rank ------------------------------
    metrics = {}
    for path in sorted(glob.glob(os.path.join(obs_dir,
                                              "metrics-r*.json"))):
        rank = os.path.basename(path)[len("metrics-r"):-len(".json")]
        doc = _load_json(path)
        if doc is not None:
            metrics[rank] = doc

    # -- causal spans (obs/trace.py) -> per-attempt critical path ------
    # grouped by (run_id, attempt), NOT attempt alone: span/event files
    # open in append mode, so a reused obs dir (the run-stable default
    # <output>/obs) holds several runs' streams — merging run A's and
    # run B's attempt-1 spans would double-count terms against one
    # ledger and flip the rc=3 gate on perfectly healthy telemetry
    spans = list(iter_spans(obs_dir))
    spans_by_attempt: Dict[tuple, List[dict]] = {}
    for s in spans:
        spans_by_attempt.setdefault(
            (s.get("run_id"), int(s.get("attempt") or 0)), []).append(s)
    # per-rank worker ledgers: the span/ledger reconciliation runs
    # against the CRITICAL rank's own books (worker_exit carries one
    # per rank), not rank 0's
    rank_ledgers: Dict[tuple, Dict[Any, dict]] = {}
    for e in events:
        if e["kind"] == "worker_exit" and isinstance(e.get("goodput"),
                                                     dict):
            rank_ledgers.setdefault(
                (e.get("run_id"), int(e.get("attempt") or 0)),
                {})[e.get("rank")] = e["goodput"]
    critical_ok = True
    for att in attempts:
        key = (att.get("run_id"), att["attempt"])
        sp = spans_by_attempt.get(key)
        if not sp:
            continue
        cp = critical_mod.critical_path(
            sp, att.get("goodput"), rank_ledgers.get(key))
        if cp is not None:
            att["critical_path"] = cp
            critical_ok = critical_ok and cp["reconciliation"]["ok"]
    trace_section = None
    if spans:
        # same reused-dir discipline as the critical path above: the
        # headline trace section describes ONE run — the newest by
        # span end time — never a cross-run mixture (the serve
        # "slowest request" of run A must not label run B's report)
        newest_run = max(spans, key=lambda s: s.get("t1", 0.0)) \
            .get("run_id")
        tr_spans = [s for s in spans if s.get("run_id") == newest_run]
        trace_section = {
            "trace_id": tr_spans[0].get("trace_id"),
            "span_count": len(tr_spans),
            "runs_in_dir": len({s.get("run_id") for s in spans}),
            "serve": critical_mod.serve_summary(tr_spans),
        }

    reconciled = all(a["reconciliation"]["ok"] for a in attempts
                     if a["reconciliation"] is not None)
    totals: Dict[str, float] = {}
    for a in attempts:
        for k, v in (a.get("goodput") or {}).items():
            if isinstance(v, (int, float)):
                totals[k] = totals.get(k, 0.0) + float(v)
    if totals.get("wall_s"):
        totals["goodput_frac"] = totals.get("step_s", 0.0) / \
            totals["wall_s"]

    # network traffic of the compiled step (grt_ici_bytes /
    # grt_dcn_bytes, noted at AOT build from the StepCostReport): one
    # per-run summary — every rank compiles the same SPMD program, so
    # the max across ranks IS the program's number
    network = {}
    for key in ("ici_bytes", "dcn_bytes"):
        vals = [doc.get(key) for doc in metrics.values()
                if isinstance(doc.get(key), (int, float))]
        if vals:
            network[key] = max(vals)

    # -- autotune feedback loop (autotune/registry.py ingest): an
    # autotune_drift event in the stream means a calibrated cost model
    # mispredicted a real run — counted and the worst relative error
    # surfaced as report scalars, so an `obs diff` baseline pins a
    # silently-degrading model (a drift event appearing where the
    # recorded run had none trips the gate)
    drift_events = [e for e in events if e["kind"] == "autotune_drift"]
    n_candidates = sum(1 for e in events
                       if e["kind"] == "autotune_candidate")
    n_results = sum(1 for e in events if e["kind"] == "autotune_result")
    autotune_section = None
    if drift_events or n_candidates or n_results:
        autotune_section = {
            "candidates": n_candidates,
            "results": n_results,
            "drift_events": len(drift_events),
            "drift_stale": sum(1 for e in drift_events
                               if e.get("stale")),
        }
        if drift_events:
            worst = max(drift_events,
                        key=lambda e: float(e.get("rel_err") or 0.0))
            autotune_section["drift_max_rel_err"] = worst.get("rel_err")
            autotune_section["drift_band"] = worst.get("band")
            autotune_section["drift_keys"] = sorted(
                {e.get("key") for e in drift_events if e.get("key")})

    backends = sorted({a["backend"] for a in attempts if a.get("backend")})

    run_end = next((e for e in reversed(events)
                    if e["kind"] == "run_end"), None)
    report = {
        "run_id": run_ids[0] if len(run_ids) == 1 else run_ids,
        "obs_dir": obs_dir,
        "status": run_end.get("status") if run_end else None,
        "attempts": attempts,
        "n_attempts": len(attempts),
        "preemptions": run_end.get("preemptions") if run_end else None,
        "goodput": totals or None,
        "network": network or None,
        "backend": (backends[0] if len(backends) == 1
                    else (backends or None)),
        "autotune": autotune_section,
        "reconciled": reconciled,
        # span/ledger cross-stream verification (obs/critical.py):
        # True when no attempt has spans, or every attempt's span-
        # derived terms match its rank's ledger — the CLI exits 3 on
        # False, the same teeth as the ledger identity above
        "critical_path_ok": critical_ok,
        "trace": trace_section,
        "anomalies": [{k: a.get(k) for k in
                       ("attempt", "rank", "class", "trigger_step",
                        "detail", "captured")} for a in anomalies],
        "captures": captures,
        "metrics": metrics,
        "supervisor": _load_json(os.path.join(obs_dir,
                                              "supervisor.json")),
        "bench_records": _bench_records(obs_dir),
        "event_count": len(events),
    }
    return report


def render_text(report: Dict[str, Any]) -> str:
    """Human-readable per-attempt timeline."""
    L: List[str] = []
    L.append(f"obs report — run {report['run_id']} "
             f"({report['n_attempts']} attempt(s), "
             f"{report['event_count']} events, "
             f"{'reconciled' if report['reconciled'] else 'NOT RECONCILED'})")
    g = report.get("goodput") or {}
    if g.get("wall_s"):
        L.append("  goodput: {:.1%} of {:.1f}s wall".format(
            g.get("goodput_frac", 0.0), g["wall_s"]))
    net = report.get("network") or {}
    if net:
        L.append("  network: ici {:,}B dcn {:,}B per step".format(
            int(net.get("ici_bytes", 0)), int(net.get("dcn_bytes", 0))))
    if report.get("backend"):
        L.append(f"  backend: {report['backend']}")
    at = report.get("autotune")
    if at:
        line = (f"  autotune: {at['candidates']} candidate(s), "
                f"{at['results']} result(s), {at['drift_events']} "
                f"drift event(s)")
        if at["drift_events"]:
            line += (f" — {at['drift_stale']} STALE, worst rel err "
                     f"{at.get('drift_max_rel_err')} vs band "
                     f"{at.get('drift_band')} ({at.get('drift_keys')})")
        L.append(line)
    for a in report["attempts"]:
        head = f"attempt {a['attempt']}: {a['status']}"
        if a.get("event"):
            head += f" [{a['event']} -> pool {a.get('pool')}]"
        if a.get("resumed_step") is not None:
            head += f" (resumed @ step {a['resumed_step']})"
        L.append(head)
        gp = a.get("goodput") or {}
        if gp:
            wall = gp.get("wall_s", 0.0) or 1.0
            bar = "  ledger: " + " ".join(
                f"{t[:-2]}={gp.get(t, 0.0):.2f}s"
                f"({gp.get(t, 0.0) / wall:.0%})"
                for t in LEDGER_TERMS if gp.get(t, 0.0) > 0.0005)
            L.append(bar + f"  wall={wall:.2f}s")
            rec = a.get("reconciliation")
            if rec is not None and not rec["ok"]:
                L.append(f"  !! ledger does NOT reconcile: terms sum "
                         f"{rec['terms_sum_s']:.4f}s vs wall "
                         f"{rec['wall_s']:.4f}s")
        cp = a.get("critical_path")
        if cp:
            terms = cp.get("terms") or {}
            cw = cp.get("wall_s") or terms.get("wall_s") or 1.0
            flame = " | ".join(
                f"{t[:-2]} {terms.get(t, 0.0):.2f}s"
                f"({terms.get(t, 0.0) / cw:.0%})"
                for t in LEDGER_TERMS
                if terms.get(t, 0.0) > max(0.005 * cw, 0.0005))
            crec = cp.get("reconciliation") or {}
            L.append(f"  critical path r{cp['rank']}: {flame}"
                     + ("" if crec.get("ok")
                        else "  !! SPANS DO NOT MATCH LEDGER "
                             f"(deltas {crec.get('deltas')})"))
        for e in a["timeline"]:
            extras = {k: v for k, v in e.items()
                      if k not in ("t", "rank", "step", "kind")
                      and v is not None}
            detail = (" " + json.dumps(extras, sort_keys=True,
                                       default=str)[:160]
                      if extras else "")
            L.append(f"  +{e['t']:>8.3f}s r{e['rank']} "
                     f"step {e['step'] if e['step'] is not None else '-':>5}"
                     f"  {e['kind']}{detail}")
    if report["anomalies"]:
        L.append("anomalies:")
        for a in report["anomalies"]:
            L.append(f"  attempt {a['attempt']} {a['class']} @ step "
                     f"{a['trigger_step']} captured={a['captured']}")
    if report["captures"]:
        L.append("captures:")
        for c in report["captures"]:
            L.append(f"  {c['class']} @ step {c['trigger_step']}: "
                     f"{c['artifact']}")
    tr = report.get("trace")
    if tr:
        L.append(f"trace {tr['trace_id']}: {tr['span_count']} spans")
        sv = tr.get("serve")
        if sv:
            ex = sv.get("slowest") or {}
            L.append(
                f"  serve: {sv['requests']} request(s), slowest "
                f"{ex.get('rid')} = {ex.get('total_s', 0.0):.3f}s "
                f"(enqueue {ex.get('enqueue_s', 0.0):.3f}s, prefill "
                f"{ex.get('prefill_s', 0.0):.3f}s, decode "
                f"{ex.get('decode_s', 0.0):.3f}s / "
                f"{ex.get('iterations')} iter)")
    sup = report.get("supervisor")
    if sup and sup.get("stalled"):
        L.append(f"supervisor: stalled ranks {sup['stalled']}")
    for b in report.get("bench_records", []):
        L.append(f"bench: {b.get('metric', '?')[:80]} = "
                 f"{b.get('value')} {b.get('unit')}")
    return "\n".join(L)


def write_report(run_dir: str,
                 out_path: Optional[str] = None) -> Dict[str, Any]:
    """build + persist ``report.json`` beside the events; returns the
    report dict (the CLI layers the rc contract on top)."""
    report = build_report(run_dir)
    path = out_path or os.path.join(report["obs_dir"], "report.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)
    os.replace(tmp, path)
    report["report_path"] = path
    return report
