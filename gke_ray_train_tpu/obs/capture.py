"""Anomaly-triggered profiler capture (ISSUE 11 tentpole, part 3).

``train/profiling.py`` can trace a FIXED window of steps — almost
always a warm, boring one. This module arms a one-shot ``jax.profiler``
capture the moment an anomaly is DETECTED, so the trace that exists is
the trace of the bad window:

- **step_time_spike** — the host's per-iteration wall time jumps past
  ``spike_factor`` x its trailing median. Under async dispatch the host
  loop runs ahead and is back-pressured by the device, so host
  iteration time tracks device step time without any added sync.
- **data_stall** — the input-pipeline wait for one batch exceeds
  ``stall_factor`` x the median iteration time (what prefetch should
  drive to ~0; ``data/prefetch.py``).
- **recompile** — the jax.monitoring backend-compile counter ticks
  after the warmup window (shape/dtype/sharding churn mid-run; the
  same signal ``analysis/jaxprcheck.py`` lints for, caught live).
- **stalled_rank** — driver-side: the heartbeat watchdog names a rank
  with no step progress; on the local path a best-effort capture runs
  BEFORE the attempt is killed (the device may still be executing the
  wedged collective — exactly the trace worth keeping).

Budget discipline: each anomaly class fires AT MOST ONE capture per
attempt, a global per-attempt capture budget bounds the disk/overhead,
and only one trace is active at a time (a pending class queues behind
the active capture; ``jax.profiler`` is process-global). Detection
itself is a handful of float comparisons per step on numbers the loop
already measured — nothing here syncs the device.
"""

from __future__ import annotations

import collections
import logging
import os
import statistics
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

ANOMALY_CLASSES = ("step_time_spike", "data_stall", "recompile",
                   "stalled_rank")

# backend-compile monitoring event (the constant jaxprcheck pins)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_compile_count = 0
_listener_installed = False


def _install_compile_listener() -> None:
    """Count backend compiles process-wide via jax.monitoring (installed
    once, kept — the listener is a counter increment)."""
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax._src import monitoring

        def _on_duration(event, duration, **kw):
            global _compile_count
            if event == _BACKEND_COMPILE_EVENT:
                _compile_count += 1

        monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_installed = True
    except Exception as e:  # noqa: BLE001 - private API; detector off
        logger.warning("backend-compile listener unavailable (%s); "
                       "recompile anomaly detection disabled", e)


def backend_compile_count() -> int:
    return _compile_count


class CaptureManager:
    """Per-attempt anomaly detector + one-shot capture scheduler.

    The loop calls :meth:`note_step` once per completed step with the
    host iteration wall time (eval/ckpt pauses and the data wait
    already excluded by the caller) and the data wait; detections emit
    ``anomaly``/``capture`` events through ``emit_fn`` and bump the
    registry counters. Captures reuse ``TraceProfiler`` (imported
    lazily — this module stays importable without jax) aimed at
    ``<obs_dir>/captures/<class>-step<k>``, each with a ``capture.json``
    marker so ``obs report`` can inventory artifacts without parsing
    XLA trace files.
    """

    def __init__(self, obs_dir: str, *,
                 emit_fn: Optional[Callable] = None,
                 registry=None,
                 budget: int = 4,
                 num_steps: int = 2,
                 warmup_steps: int = 5,
                 spike_factor: float = 3.0,
                 stall_factor: float = 2.0,
                 min_stall_s: float = 0.02,
                 trace_conflict: Optional[Callable[[], bool]] = None):
        self.obs_dir = obs_dir
        self.emit = emit_fn or (lambda *a, **k: None)
        self.registry = registry
        self.budget = int(budget)
        self.num_steps = int(num_steps)
        self.warmup_steps = int(warmup_steps)
        self.spike_factor = float(spike_factor)
        self.stall_factor = float(stall_factor)
        self.min_stall_s = float(min_stall_s)
        # external in-flight trace (the config-gated TraceProfiler
        # window): jax.profiler is process-global, never start a second
        self._conflict = trace_conflict or (lambda: False)
        self.fired: Dict[str, int] = {}        # class -> trigger step
        self.captured: List[Dict[str, Any]] = []
        self._iter_times = collections.deque(maxlen=32)
        self._steps_seen = 0
        self._compile_base: Optional[int] = None
        self._active: Optional[dict] = None     # {profiler, class, step}
        self._pending: List[tuple] = []         # (class, step, detail)
        # wall seconds this manager itself spent starting/stopping
        # traces since the last note_step — subtracted from the next
        # sample, or the capture's own cost reads as a step-time spike
        self._self_s = 0.0
        _install_compile_listener()

    # -- detection -----------------------------------------------------

    def _median(self) -> float:
        return statistics.median(self._iter_times) \
            if self._iter_times else 0.0

    def note_step(self, step: int, iter_s: float, wait_s: float) -> None:
        """Once per completed step. ``iter_s`` = host wall since the
        previous step, minus data wait and eval/ckpt pauses (the
        caller's ledger already tracks those); ``wait_s`` = the input
        pipeline wait for this batch."""
        self._steps_seen += 1
        iter_s = max(iter_s - self._self_s, 0.0)
        self._self_s = 0.0
        if self.registry is not None:
            self.registry.counter("steps_total").inc()
            self.registry.histogram("step_time_s").observe(iter_s)
            if wait_s > 0:
                self.registry.histogram("data_wait_s").observe(wait_s)
        # recompile: any backend compile after the baseline snapshot
        # (taken once warmup completes — the first-step compile, a
        # first eval compile and resume rebuilds are legitimate)
        count = backend_compile_count()
        if self.registry is not None:
            self.registry.counter("backend_compiles_total").value = count
        if self._steps_seen == self.warmup_steps:
            self._compile_base = count
        elif self._compile_base is not None and count > self._compile_base:
            self._compile_base = count
            self._anomaly("recompile", step,
                          {"backend_compiles": count})
        med = self._median()
        warm = self._steps_seen > self.warmup_steps and med > 0
        if warm and iter_s > max(self.spike_factor * med, med + 0.01):
            self._anomaly("step_time_spike", step,
                          {"iter_s": round(iter_s, 4),
                           "median_s": round(med, 4)})
        if warm and wait_s > max(self.stall_factor * med,
                                 self.min_stall_s):
            self._anomaly("data_stall", step,
                          {"wait_s": round(wait_s, 4),
                           "median_step_s": round(med, 4)})
        # the sample window feeds the median AFTER detection so the
        # spike itself does not drag the baseline up before it is seen
        self._iter_times.append(max(iter_s, 0.0))
        self._drive(step)

    def note_stalled_rank(self, detail: Dict[str, Any],
                          seconds: float = 0.5) -> None:
        """Driver/watchdog path: capture NOW (bounded), synchronously —
        by the time a stall is named the loop is not stepping, so the
        step-driven scheduler below never runs."""
        step = int(detail.get("step", -1))
        if not self._anomaly("stalled_rank", step, detail):
            return
        if self._active is not None:
            # one trace at a time: seal our own in-flight capture first
            # (the loop is wedged, it was never going to finish; the
            # partial trace is still evidence) so start_trace below
            # does not collide with it
            try:
                self._active["profiler"].close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            self._finish_active()
        if self._budget_left() and not self._conflict():
            self._capture_now("stalled_rank", step, seconds)

    # -- capture scheduling --------------------------------------------

    def _budget_left(self) -> bool:
        return len(self.captured) < self.budget

    def _anomaly(self, cls: str, step: int, detail: Dict[str, Any]
                 ) -> bool:
        """Record one anomaly; returns True when this is the class's
        FIRST firing this attempt (the one that may arm a capture)."""
        if cls in self.fired:
            return False
        self.fired[cls] = int(step)
        if self.registry is not None:
            self.registry.counter("anomalies_total").inc()
        logger.warning("obs anomaly %s at step %d: %s", cls, step, detail)
        self.emit("anomaly", step=step, **{"class": cls},
                  detail=detail, trigger_step=int(step))
        if cls != "stalled_rank" and self._budget_left():
            self._pending.append((cls, int(step), detail))
        return True

    def _capture_dir(self, cls: str, step: int) -> str:
        return os.path.join(self.obs_dir, "captures", f"{cls}-step{step}")

    def _drive(self, step: int) -> None:
        """Advance the active capture / start the next pending one.
        Called from note_step — captures trace the steps FOLLOWING the
        trigger (the bad regime is usually still in effect; the trigger
        step itself is already gone)."""
        t0 = time.perf_counter()
        try:
            self._drive_inner(step)
        finally:
            # trace start/stop cost is the manager's own, not the
            # step's — keep it out of the next anomaly sample
            self._self_s += time.perf_counter() - t0

    def _drive_inner(self, step: int) -> None:
        if self._active is not None:
            prof = self._active["profiler"]
            prof.step(step)
            if prof._done:
                self._finish_active()
        if self._active is None and self._pending:
            if self._conflict():
                return          # retry at the next step boundary
            cls, t_step, _detail = self._pending.pop(0)
            if not self._budget_left():
                self._pending.clear()
                return
            from gke_ray_train_tpu.train.profiling import TraceProfiler
            logdir = self._capture_dir(cls, t_step)
            os.makedirs(logdir, exist_ok=True)
            self._active = {
                "profiler": TraceProfiler(logdir, start_step=1,
                                          num_steps=self.num_steps),
                "class": cls, "trigger_step": t_step,
                "t0": time.perf_counter()}

    def _finish_active(self) -> None:
        a, self._active = self._active, None
        if a is None:
            return
        artifact = a["profiler"].logdir
        # a profiler whose start_trace failed marks itself done without
        # ever arming a stop step — that capture produced NO trace and
        # must be reported failed, not as a good artifact
        started = a["profiler"]._stop_at is not None
        ok = self._write_marker(a["class"], a["trigger_step"],
                                artifact) and started
        self.captured.append({"class": a["class"],
                              "trigger_step": a["trigger_step"],
                              "artifact": artifact})
        if self.registry is not None:
            self.registry.counter("captures_total").inc()
        self.emit("capture", step=a["trigger_step"],
                  **{"class": a["class"]}, artifact=artifact,
                  num_steps=self.num_steps,
                  trigger_step=a["trigger_step"], failed=not ok)

    def _capture_now(self, cls: str, step: int, seconds: float) -> None:
        """Synchronous bounded trace (stalled_rank only): whatever the
        device is doing RIGHT NOW is the evidence."""
        import jax
        logdir = self._capture_dir(cls, step)
        os.makedirs(logdir, exist_ok=True)
        ok = True
        try:
            jax.profiler.start_trace(logdir)
            time.sleep(max(seconds, 0.05))
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 - wedged backend likely
            ok = False
            logger.warning("stalled-rank capture failed: %s", e)
        self._write_marker(cls, step, logdir)
        self.captured.append({"class": cls, "trigger_step": step,
                              "artifact": logdir})
        if self.registry is not None:
            self.registry.counter("captures_total").inc()
        self.emit("capture", step=step, **{"class": cls},
                  artifact=logdir, num_steps=0, trigger_step=step,
                  failed=not ok)

    def _write_marker(self, cls: str, step: int, logdir: str) -> bool:
        """capture.json beside the trace — the artifact inventory
        ``obs report`` reads (XLA trace layouts vary by backend)."""
        import json
        try:
            with open(os.path.join(logdir, "capture.json"), "w",
                      encoding="utf-8") as f:
                json.dump({"class": cls, "trigger_step": int(step),
                           "num_steps": self.num_steps,
                           "ts": time.time()}, f)
            return True
        except OSError as e:  # pragma: no cover
            logger.warning("capture marker write failed: %s", e)
            return False

    def close(self) -> None:
        """Attempt end: stop an in-flight capture (the partial trace is
        still evidence) and drop anything pending."""
        if self._active is not None:
            try:
                self._active["profiler"].close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
            self._finish_active()
        self._pending.clear()
