"""Critical-path attribution over the merged span DAG (ISSUE 14
tentpole, part 2).

The report's goodput ledger says an attempt spent 41% of wall in
``restore_s``; this module says *which rank's* restore gated the
attempt and what the gating chain looked like. Per attempt:

- worker spans (``obs/trace.py``) are grouped by rank; the **critical
  rank** is the one whose attempt span ran longest — on an SPMD job
  every rank exits the attempt together, so the rank with the longest
  own-work chain is the one the others waited on;
- the **path** is that rank's causally-ordered leaf spans (a rank's
  loop is sequential, so temporal order on one rank IS causal order;
  cross-rank edges come from the driver-attempt parent links);
- the **terms** are the attempt's finished goodput ledger — the
  identity that already sums to attempt wall EXACTLY (``finish_ledger``
  constructs it; ``report.py`` re-verifies it);
- the **reconciliation** is this module's own teeth: the span-derived
  duration of every directly-traced term (restore / compile /
  fast-forward / eval+ckpt stalls / data stalls) must match the same
  rank's goodput ledger to within :data:`RECONCILE_TOL` — the
  instrumented sites emit the EXACT floats the ledger booked, so a
  drift between the two streams is an instrumentation bug, not noise —
  and the spans must never claim more time than the attempt wall.
  ``obs report`` exits 3 on a failure, the same discipline as the
  ledger identity itself.

Stdlib-only (runs wherever the report runs — no jax).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# span name -> the goodput ledger term it measures (train/metrics.py
# LEDGER_TERMS; duplicated as strings on purpose — report-side code
# must run without jax, and test_trace pins the mapping against the
# ledger). step_window spans split between step_s (their duration
# minus the stall attr) and data_stall_s; serve/reshard/attempt spans
# map to no term (reshard time is inside restore; serve runs post-loop).
SPAN_TERM = {
    "restore": "restore_s",
    "peer_restore": "peer_restore_s",
    "compile": "compile_s",
    "fast_forward": "fast_forward_s",
    "eval": "eval_ckpt_stall_s",
    "ckpt_save": "eval_ckpt_stall_s",
    "ckpt_snapshot": "ckpt_async_s",
    "preempt_save": "eval_ckpt_stall_s",
}
# the terms whose span measurement must agree with the ledger exactly
# (they are emitted from the identical floats); step_s is NOT here —
# step windows legitimately undercover the loop's residual (the ledger
# books step_s as wall minus everything else).
RECONCILED_TERMS = ("restore_s", "compile_s", "fast_forward_s",
                    "eval_ckpt_stall_s", "data_stall_s",
                    "ckpt_async_s", "peer_restore_s")
RECONCILE_TOL = 1e-6
MAX_PATH = 64


def _is_worker(span: Dict[str, Any]) -> bool:
    return str(span.get("rank")) != "driver"


def span_terms(leaves: List[Dict[str, Any]]) -> Dict[str, float]:
    """Ledger-term sums as the SPANS measured them, for one rank's
    leaf spans of one attempt."""
    out: Dict[str, float] = {}
    for s in leaves:
        name = s.get("name")
        dur = float(s.get("dur_s", 0.0))
        if name == "step_window":
            stall = float(s.get("data_stall_s", 0.0) or 0.0)
            out["step_s"] = out.get("step_s", 0.0) + max(dur - stall, 0.0)
            out["data_stall_s"] = out.get("data_stall_s", 0.0) + stall
        elif name in SPAN_TERM:
            term = SPAN_TERM[name]
            out[term] = out.get(term, 0.0) + dur
    return out


def critical_path(spans: List[Dict[str, Any]],
                  goodput: Optional[Dict[str, Any]],
                  worker_ledgers: Optional[Dict[Any, dict]] = None,
                  max_path: int = MAX_PATH) -> Optional[Dict[str, Any]]:
    """The critical-path section for ONE attempt.

    ``spans``: every span of the attempt (all ranks incl. driver).
    ``goodput``: the driver's finished ledger (terms + ``wall_s``).
    ``worker_ledgers``: rank -> that rank's own ``worker_exit`` ledger
    (the per-rank stream carries one each); the span/ledger
    reconciliation runs against the CRITICAL rank's own ledger, not
    rank 0's — on a multi-rank job the gating rank's spans must match
    the gating rank's books.
    """
    by_rank: Dict[Any, List[Dict[str, Any]]] = {}
    for s in spans:
        if _is_worker(s):
            by_rank.setdefault(s.get("rank"), []).append(s)
    if not by_rank:
        return None

    def rank_weight(rank) -> float:
        att = [s for s in by_rank[rank] if s.get("name") == "attempt"]
        if att:
            return float(att[-1].get("dur_s", 0.0))
        return sum(float(s.get("dur_s", 0.0)) for s in by_rank[rank])

    crit = max(sorted(by_rank, key=str), key=rank_weight)
    mine = sorted(by_rank[crit], key=lambda s: (s.get("t0", 0.0),
                                                str(s.get("span_id"))))
    att_spans = [s for s in mine if s.get("name") == "attempt"]
    t_base = (att_spans[-1].get("t0") if att_spans
              else (mine[0].get("t0") if mine else 0.0)) or 0.0
    # the path: causally-ordered leaf spans (serve children excluded —
    # their parent request span already covers them)
    child_parents = {s.get("span_id") for s in mine
                     if s.get("name") == "serve_request"}
    leaves = [s for s in mine
              if s.get("name") != "attempt"
              and s.get("parent_id") not in child_parents]
    if not any(s.get("name") in SPAN_TERM or s.get("name") ==
               "step_window" for s in leaves):
        # no ledger-mapped spans at all: the session never ran the
        # instrumented loop (a serve-only drain, a bench emitting bare
        # events, an attempt killed before restore) — there is no path
        # to attribute and nothing to reconcile
        return None
    path = [{
        "name": s.get("name"),
        "t": round(float(s.get("t0", 0.0)) - float(t_base), 3),
        "dur_s": float(s.get("dur_s", 0.0)),
        "step": s.get("step"),
        **({"steps": s.get("steps")}
           if s.get("name") == "step_window" else {}),
    } for s in leaves]
    dropped = max(len(path) - max_path, 0)
    path = path[:max_path]

    sterms = span_terms(leaves)
    wall = float((goodput or {}).get("wall_s", 0.0) or 0.0)
    tol = RECONCILE_TOL * max(1.0, wall)
    ledger = (worker_ledgers or {}).get(crit) \
        or (worker_ledgers or {}).get(str(crit)) or goodput or {}
    deltas: Dict[str, float] = {}
    ok = True
    for term in RECONCILED_TERMS:
        if term not in ledger:
            continue
        d = sterms.get(term, 0.0) - float(ledger.get(term, 0.0))
        deltas[term] = d
        if abs(d) > tol:
            ok = False
    covered = sum(sterms.values())
    over = covered - wall if wall else 0.0
    if wall and over > tol:
        # spans claiming more time than the attempt wall is the same
        # class of telemetry bug as a non-summing ledger
        ok = False
    out: Dict[str, Any] = {
        "rank": crit,
        "wall_s": wall or None,
        # the attempt's reconciled identity: these sum to wall exactly
        # (report.py re-verifies); the spans ATTRIBUTE them
        "terms": {k: float(v) for k, v in (goodput or {}).items()
                  if isinstance(v, (int, float))} or None,
        "span_terms": {k: round(v, 6) for k, v in sorted(sterms.items())},
        "path": path,
        "reconciliation": {
            "ok": ok,
            "deltas": {k: round(v, 9) for k, v in deltas.items()},
            "span_covered_s": round(covered, 6),
            "overcoverage_s": round(max(over, 0.0), 6),
        },
    }
    if dropped:
        out["path_truncated"] = dropped
    return out


def serve_summary(spans: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """End-to-end decomposition of the traced serve requests: per-phase
    mean durations plus one fully-decomposed example request (the
    "where did my p99 go" witness the report surfaces)."""
    reqs = [s for s in spans if s.get("name") == "serve_request"]
    if not reqs:
        return None
    children: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        if s.get("name") in ("serve_enqueue", "serve_prefill",
                             "serve_decode"):
            children.setdefault(s.get("parent_id"), []).append(s)

    def mean(vals: List[float]) -> float:
        return round(sum(vals) / len(vals), 6) if vals else 0.0

    phases: Dict[str, List[float]] = {}
    iters: List[int] = []
    for r in reqs:
        for c in children.get(r.get("span_id"), []):
            phases.setdefault(c["name"], []).append(
                float(c.get("dur_s", 0.0)))
            if c["name"] == "serve_decode" and c.get("iterations") \
                    is not None:
                iters.append(int(c["iterations"]))
    first = max(reqs, key=lambda r: float(r.get("dur_s", 0.0)))
    example: Dict[str, Any] = {
        "rid": first.get("rid"), "bucket": first.get("bucket"),
        "total_s": round(float(first.get("dur_s", 0.0)), 6),
        "finish_reason": first.get("finish_reason"),
        "generated": first.get("generated"),
    }
    for c in children.get(first.get("span_id"), []):
        example[c["name"].replace("serve_", "") + "_s"] = round(
            float(c.get("dur_s", 0.0)), 6)
        if c["name"] == "serve_decode":
            example["iterations"] = c.get("iterations")
    return {
        "requests": len(reqs),
        "mean_total_s": mean([float(r.get("dur_s", 0.0)) for r in reqs]),
        "mean_enqueue_s": mean(phases.get("serve_enqueue", [])),
        "mean_prefill_s": mean(phases.get("serve_prefill", [])),
        "mean_decode_s": mean(phases.get("serve_decode", [])),
        "mean_iterations": (round(sum(iters) / len(iters), 2)
                            if iters else None),
        "slowest": example,
    }
