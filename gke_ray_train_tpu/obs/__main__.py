"""CLI: ``python -m gke_ray_train_tpu.obs <verb>``.

Verbs:

- ``report <run_dir>`` — merge the run's events/spans/metrics/ledger/
  bench records into ``<obs_dir>/report.json``, print ONE JSON summary
  line on stdout (the record_baselines.sh / driver contract; ``--text``
  additionally renders the per-attempt timeline + critical-path flame
  summary on stderr).
- ``diff <A> <B>`` — the cross-run regression gate (obs/diff.py):
  compare two reports' goodput terms, goodput_frac, serve p50/p99 and
  critical-path composition under two-sided tolerances; each operand
  is a run dir, a ``report.json``, or a checked-in regression ledger
  (``tests/regressions/*.json``). ``--update`` (or
  ``REGRESSION_UPDATE=1``) re-records B from A instead of comparing.
- ``schema`` — validate the shipped event + metric + trace schema
  files against the code's pinned vocabularies (the CI lint step).

Exit codes (pinned by tests/test_obs.py + tests/test_trace.py):
  0 ok · 1 run dir unreadable / no telemetry / schema drift ·
  2 usage (argparse) · 3 ledger/span reconciliation failure ·
  4 ``diff`` tripped a regression tolerance.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m gke_ray_train_tpu.obs")
    sub = p.add_subparsers(dest="verb", required=True)
    rp = sub.add_parser("report", help="one report per run dir")
    rp.add_argument("run_dir")
    rp.add_argument("--out", default=None,
                    help="report.json path (default: <obs_dir>/report.json)")
    rp.add_argument("--text", action="store_true",
                    help="also render the human timeline (stderr)")
    dp = sub.add_parser("diff", help="cross-run regression gate")
    dp.add_argument("a", help="fresh side: run dir / report.json / "
                              "regression ledger")
    dp.add_argument("b", help="recorded side (same forms; usually "
                              "tests/regressions/<name>.json)")
    dp.add_argument("--update", action="store_true",
                    help="re-record B from A instead of comparing "
                         "(also: REGRESSION_UPDATE=1)")
    sub.add_parser("schema", help="validate shipped schema files")
    args = p.parse_args(argv)

    if args.verb == "schema":
        from gke_ray_train_tpu.obs import events, metrics, trace
        findings = (events.check_schema() + metrics.check_schema()
                    + trace.check_schema())
        for f in findings:
            print(f"SCHEMA: {f}", file=sys.stderr)
        print(json.dumps({"verb": "schema",
                          "findings": len(findings),
                          "ok": not findings}))
        return 1 if findings else 0

    if args.verb == "diff":
        return _diff(args)

    from gke_ray_train_tpu.obs.report import (
        ReportError, render_text, write_report)
    try:
        report = write_report(args.run_dir, args.out)
    except ReportError as e:
        print(f"obs report: {e}", file=sys.stderr)
        return 1
    if args.text:
        print(render_text(report), file=sys.stderr)
    summary = {
        "metric": f"obs report {report['run_id']}",
        "value": report["n_attempts"], "unit": "attempts",
        "reconciled": report["reconciled"],
        "critical_path_ok": report.get("critical_path_ok", True),
        "spans": (report.get("trace") or {}).get("span_count", 0),
        "anomalies": len(report["anomalies"]),
        "captures": len(report["captures"]),
        "reshards": sum(len(a.get("reshard", []))
                        for a in report["attempts"]),
        "events": report["event_count"],
        "goodput_frac": round((report.get("goodput") or {}).get(
            "goodput_frac", 0.0), 4),
        "report": report["report_path"],
    }
    print(json.dumps(summary))
    if not report["reconciled"]:
        print("obs report: ledger terms do NOT reconcile to attempt "
              "wall-clock — telemetry bug", file=sys.stderr)
        return 3
    if not report.get("critical_path_ok", True):
        print("obs report: span-derived critical-path terms do NOT "
              "match the goodput ledger — telemetry bug (see each "
              "attempt's critical_path.reconciliation)", file=sys.stderr)
        return 3
    return 0


def _diff(args) -> int:
    from gke_ray_train_tpu.obs.diff import (
        diff_flat, load_side, write_regression)
    from gke_ray_train_tpu.obs.report import ReportError
    try:
        flat_a, label_a = load_side(args.a)
    except (ReportError, OSError, ValueError) as e:
        print(f"obs diff: cannot read A ({args.a}): {e}",
              file=sys.stderr)
        return 1
    update = args.update or os.environ.get(
        "REGRESSION_UPDATE", "").strip().lower() in ("1", "true", "yes")
    if update:
        try:
            old_tol = None
            if os.path.exists(args.b):
                with open(args.b, encoding="utf-8") as f:
                    old = json.load(f)
                old_tol = old.get("tolerances") \
                    if isinstance(old.get("tolerances"), dict) else None
            doc = write_regression(flat_a, args.b, source=label_a,
                                   tolerances=old_tol)
        except (OSError, ValueError) as e:
            print(f"obs diff: cannot record {args.b}: {e}",
                  file=sys.stderr)
            return 1
        print(json.dumps({"metric": f"obs diff record {args.b}",
                          "value": len([k for k in doc
                                        if not k.startswith("_")]),
                          "unit": "fields", "recorded": args.b}))
        return 0
    try:
        flat_b, label_b = load_side(args.b)
    except (ReportError, OSError, ValueError) as e:
        print(f"obs diff: cannot read B ({args.b}): {e}",
              file=sys.stderr)
        return 1
    viols = diff_flat(flat_a, flat_b)
    for v in viols:
        print(f"DIFF {v}", file=sys.stderr)
    print(json.dumps({
        "metric": f"obs diff {label_a} vs {label_b}",
        "value": len(viols), "unit": "violations",
        "ok": not viols,
        "goodput_frac": [flat_a.get("goodput_frac"),
                         flat_b.get("goodput_frac")],
    }))
    if viols:
        print("obs diff: regression tolerances tripped — if the "
              "change is INTENTIONAL, re-record: REGRESSION_UPDATE=1 "
              "python -m gke_ray_train_tpu.obs diff A B",
              file=sys.stderr)
        return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
