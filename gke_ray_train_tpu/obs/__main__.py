"""CLI: ``python -m gke_ray_train_tpu.obs <verb>``.

Verbs:

- ``report <run_dir>`` — merge the run's events/metrics/ledger/bench
  records into ``<obs_dir>/report.json``, print ONE JSON summary line
  on stdout (the record_baselines.sh / driver contract; ``--text``
  additionally renders the per-attempt timeline on stderr).
- ``schema`` — validate the shipped event + metric schema files
  against the code's pinned vocabularies (the CI lint step).

Exit codes (pinned by tests/test_obs.py):
  0 ok · 1 run dir unreadable / no telemetry / schema drift ·
  2 usage (argparse) · 3 ledger reconciliation failure.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m gke_ray_train_tpu.obs")
    sub = p.add_subparsers(dest="verb", required=True)
    rp = sub.add_parser("report", help="one report per run dir")
    rp.add_argument("run_dir")
    rp.add_argument("--out", default=None,
                    help="report.json path (default: <obs_dir>/report.json)")
    rp.add_argument("--text", action="store_true",
                    help="also render the human timeline (stderr)")
    sub.add_parser("schema", help="validate shipped schema files")
    args = p.parse_args(argv)

    if args.verb == "schema":
        from gke_ray_train_tpu.obs import events, metrics
        findings = events.check_schema() + metrics.check_schema()
        for f in findings:
            print(f"SCHEMA: {f}", file=sys.stderr)
        print(json.dumps({"verb": "schema",
                          "findings": len(findings),
                          "ok": not findings}))
        return 1 if findings else 0

    from gke_ray_train_tpu.obs.report import (
        ReportError, render_text, write_report)
    try:
        report = write_report(args.run_dir, args.out)
    except ReportError as e:
        print(f"obs report: {e}", file=sys.stderr)
        return 1
    if args.text:
        print(render_text(report), file=sys.stderr)
    summary = {
        "metric": f"obs report {report['run_id']}",
        "value": report["n_attempts"], "unit": "attempts",
        "reconciled": report["reconciled"],
        "anomalies": len(report["anomalies"]),
        "captures": len(report["captures"]),
        "reshards": sum(len(a.get("reshard", []))
                        for a in report["attempts"]),
        "events": report["event_count"],
        "goodput_frac": round((report.get("goodput") or {}).get(
            "goodput_frac", 0.0), 4),
        "report": report["report_path"],
    }
    print(json.dumps(summary))
    if not report["reconciled"]:
        print("obs report: ledger terms do NOT reconcile to attempt "
              "wall-clock — telemetry bug", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
