"""Structured run-event stream — the machine-readable record of what
happened when (ISSUE 11 tentpole, part 1).

Every interesting boundary of a run — attempt start/end, checkpoint
resume, the first (compiling) step, periodic step metrics, eval,
checkpoint saves, preemption exits, elastic reshards, anomalies and
their profiler captures, serving drains — lands as ONE JSON line in a
per-rank file ``<obs_dir>/events-r<rank>.jsonl`` (the driver writes
``events-driver.jsonl``). Each record is stamped with the same
correlation fields (:data:`STAMP_FIELDS`): ``run_id`` / ``attempt`` /
``rank`` / ``slice`` / ``step`` / ``plan_fingerprint``, so one grep
joins the event stream, the prefixed text logs
(``logging_utils.configure_run_logging``) and the metric exports.

The event vocabulary is CLOSED: :data:`EVENT_KINDS` is pinned by the
shipped schema file (``obs/schemas/events.schema.json``) and by
``tests/test_obs.py`` — a renamed kind fails the contract test instead
of silently orphaning old run dirs. Emission sits OFF the hot path by
construction: events fire at boundaries (log cadence at the fastest),
never per step, and never fetch device values themselves — payloads are
host values the caller already had.

Stdlib-only by design (the supervisor/trainer driver side must import
this without jax).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

logger = logging.getLogger(__name__)

# correlation fields stamped on EVERY record, in this order
STAMP_FIELDS = ("ts", "run_id", "attempt", "rank", "slice", "step",
                "plan_fingerprint", "kind")

# the closed event vocabulary: kind -> allowed payload fields. Pinned by
# obs/schemas/events.schema.json and the test_obs contract test.
EVENT_KINDS: Dict[str, tuple] = {
    # attempt lifecycle (worker side)
    "attempt_start": ("topology", "n_devices", "pool", "mesh"),
    "resume": ("resumed_step",),
    "first_step": ("compile_s", "restart_to_first_step_s",
                   "fast_forward_s", "restore_s", "backend"),
    "step": ("epoch", "loss", "learning_rate", "grad_norm",
             "tokens_per_sec_per_chip", "mfu", "data_stall_frac"),
    "eval": ("metrics",),
    "ckpt_save": ("save_s", "forced"),
    # async write-ahead checkpointing (ckpt/manager.py, ISSUE 18):
    # ckpt_snapshot is the loop-side device→host snapshot + committer
    # enqueue (snapshot_s = the residual blocking time the ledger books
    # as ckpt_async_s); ckpt_commit is the committer thread's
    # serialize-to-storage lifecycle behind the COMMITTING/COMMITTED
    # marker pair (status: ok | error)
    "ckpt_snapshot": ("snapshot_s", "forced"),
    "ckpt_commit": ("commit_s", "status"),
    # peer-slice hot-state replication (ckpt/peer.py): each slice
    # streams its shards to a peer over the DCN hop at snapshot time;
    # a slice_evict retry restores from the living peer instead of
    # storage (restore_s = the ledger's peer_restore_s float)
    "peer_replicate": ("bytes", "to_slice", "replicate_s"),
    "peer_restore": ("restore_s", "bytes", "from_slice"),
    "epoch_end": ("epoch",),
    "preempt_exit": ("save_s", "grace_remaining_s", "pool"),
    "worker_exit": ("status", "goodput"),
    # attempt lifecycle (driver side — the reconciliation source)
    "attempt_end": ("status", "goodput", "event", "pool", "error",
                    "resumed_step", "ckpt_save_s"),
    "run_end": ("status", "attempts", "preemptions", "goodput"),
    # elastic / supervision
    "reshard": ("from_devices", "to_devices", "from_fingerprint",
                "to_fingerprint", "mesh", "per_device_batch"),
    "stall": ("stalled", "timeout_s"),
    # anomaly-triggered profiling (obs/capture.py)
    "anomaly": ("class", "detail", "trigger_step"),
    "capture": ("class", "artifact", "num_steps", "trigger_step",
                "failed"),
    # serving (serve/engine.py / rayint/serving.py)
    "serve_start": ("replica", "executables"),
    "serve_drained": ("replica", "stats"),
    # autotune search (autotune/search.py): one event per scored
    # candidate (phase: coarse | full | pruned) + the final verdict
    "autotune_candidate": ("fingerprint", "phase", "modeled_step_s",
                           "env"),
    "autotune_result": ("key", "winner", "base", "winner_step_s",
                        "base_step_s", "improvement", "candidates",
                        "compiled", "pruned"),
    # calibration drift teeth (autotune/registry.py ingest): fired when
    # an entry's corrected prediction misses the measured value by more
    # than AUTOTUNE_DRIFT_BAND (the entry goes stale in the same breath)
    "autotune_drift": ("key", "arm", "measured_step_s",
                       "raw_modeled_step_s", "corrected_modeled_step_s",
                       "rel_err", "band", "stale"),
    # entry-script artifacts
    "export": ("path", "what"),
}


class EventError(ValueError):
    """An event violated the pinned schema (unknown kind / stray field)."""


def validate_event(kind: str, payload: Dict[str, Any]) -> None:
    """Schema teeth at the emit site: unknown kinds and undeclared
    payload fields raise — the contract the report/CI rely on is
    enforced where the event is born, not discovered at read time."""
    allowed = EVENT_KINDS.get(kind)
    if allowed is None:
        raise EventError(f"unknown event kind {kind!r}; known: "
                         f"{sorted(EVENT_KINDS)}")
    stray = sorted(set(payload) - set(allowed) - set(STAMP_FIELDS))
    if stray:
        raise EventError(f"event kind {kind!r} does not declare payload "
                         f"fields {stray} (allowed: {sorted(allowed)})")


def _json_safe(v: Any) -> Any:
    """Coerce payload values to JSON-serializable types (numpy scalars
    and arrays arrive from host metric dicts)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)[:200]


class EventLog:
    """Append-only JSONL event writer for one (rank, attempt) stream.

    The file is opened in append mode (a retry in the same process or a
    later attempt writing the same rank file extends, never truncates —
    the ``attempt`` stamp keeps the streams separable) and flushed per
    record: events are boundary-rate, and the record must survive the
    SIGKILL that usually follows the interesting ones.
    """

    def __init__(self, path: str, *, run_id: str, attempt: int,
                 rank: Union[int, str], slice_index: Optional[int] = None,
                 plan_fingerprint: Optional[str] = None):
        self.path = path
        self.run_id = str(run_id)
        self.attempt = int(attempt)
        self.rank = rank
        self.slice_index = slice_index
        self.plan_fingerprint = plan_fingerprint
        self._step: Optional[int] = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def set_step(self, step: Optional[int]) -> None:
        """Current train step, stamped on subsequent records whose
        caller does not pass one (e.g. serve/anomaly paths)."""
        self._step = step

    def emit(self, kind: str, step: Optional[int] = None,
             **payload: Any) -> Dict[str, Any]:
        validate_event(kind, payload)
        rec: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "run_id": self.run_id,
            "attempt": self.attempt,
            "rank": self.rank,
            "slice": self.slice_index,
            "step": self._step if step is None else int(step),
            "plan_fingerprint": self.plan_fingerprint,
            "kind": kind,
        }
        rec.update({k: _json_safe(v) for k, v in payload.items()})
        if self._f is not None and not self._f.closed:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        return rec

    def close(self) -> None:
        try:
            if self._f is not None and not self._f.closed:
                self._f.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass


def events_path(obs_dir: str, rank: Union[int, str]) -> str:
    return os.path.join(obs_dir, f"events-r{rank}.jsonl")


def iter_events(obs_dir: str,
                kinds: Optional[Iterable[str]] = None
                ) -> Iterator[Dict[str, Any]]:
    """Every event record under ``obs_dir`` (all ranks + driver),
    sorted by timestamp. Corrupt lines (a SIGKILL mid-write) are
    skipped with a warning, never fatal — the report must render what
    survived."""
    want = set(kinds) if kinds is not None else None
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return iter(())
    for name in names:
        if not (name.startswith("events-") and name.endswith(".jsonl")):
            continue
        path = os.path.join(obs_dir, name)
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    logger.warning("%s:%d: skipping corrupt event line",
                                   path, i + 1)
                    continue
                if want is None or rec.get("kind") in want:
                    out.append(rec)
    out.sort(key=lambda r: (r.get("ts", 0.0), str(r.get("rank"))))
    return iter(out)


def schema_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "schemas", "events.schema.json")


def load_schema() -> Dict[str, Any]:
    with open(schema_path(), encoding="utf-8") as f:
        return json.load(f)


def check_schema() -> List[str]:
    """Shipped schema file <-> code contract: the file must parse and
    pin exactly the vocabulary this module enforces. Returns findings
    (empty = clean) — the CI lint job and test_obs both call this."""
    findings: List[str] = []
    try:
        doc = load_schema()
    except (OSError, ValueError) as e:
        return [f"events schema unreadable: {type(e).__name__}: {e}"]
    if tuple(doc.get("stamp", ())) != STAMP_FIELDS:
        findings.append(f"schema stamp {doc.get('stamp')} != code "
                        f"STAMP_FIELDS {list(STAMP_FIELDS)}")
    kinds = doc.get("kinds", {})
    if set(kinds) != set(EVENT_KINDS):
        findings.append(
            f"schema kinds {sorted(set(kinds) ^ set(EVENT_KINDS))} "
            "drifted from code EVENT_KINDS")
    for k in set(kinds) & set(EVENT_KINDS):
        if tuple(kinds[k]) != tuple(EVENT_KINDS[k]):
            findings.append(f"schema kind {k!r} fields {kinds[k]} != "
                            f"code {list(EVENT_KINDS[k])}")
    return findings
