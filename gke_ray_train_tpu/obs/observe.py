"""Deterministic ``ObservedRun`` extraction — the obs→autotune bridge
(ISSUE 16 tentpole, part 1).

PR 14's autotune ranks plans by a *static* cost model; the obs stream
(PR 11/13) already records what those plans actually did. This module
closes the gap: :func:`observed_runs` flattens one run dir's artifacts
— the span-derived step windows, the goodput ledger, the serve drain
stats, and any bench records — into small, deterministic rows keyed by
``(plan_fingerprint, surface, topology, chip family, backend)`` that
``autotune ingest`` (autotune/registry.py) can match against registry
entries and ``autotune calibrate`` (autotune/calibrate.py) can fit
correction factors over.

Measurement discipline:

- the measured TRAIN step time is a robust weighted MEDIAN over the
  ``step_window`` spans' per-step compute time ``(dur_s −
  data_stall_s) / steps`` (each window weighted by its step count) —
  one slow window (a GC pause, a noisy neighbour) must not drag the
  number the calibration fits against;
- the measured SERVE number is the drained engine's per-token p50
  (p99 rides along as provenance) — the same quantity the scorer's
  ``modeled_per_token_s`` predicts;
- ``backend`` comes from the run's own record (the ``first_step``
  event / the bench record's ``backend`` tag), NEVER inferred — a
  ``cpu-fallback`` measurement must be refusable at ingest so it can
  never calibrate a TPU ChipSpec;
- every float is rounded once, here, so re-extracting the same
  artifacts is bitwise-identical (the ingest idempotency contract).

Stdlib-only, like everything report-side (the extraction must run on a
laptop pointed at a GCS-FUSE mount, with no jax).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

from gke_ray_train_tpu.obs.events import iter_events
from gke_ray_train_tpu.obs.trace import iter_spans

logger = logging.getLogger(__name__)

# float precision of every measured value (µs on seconds-scale
# numbers): rounding happens ONCE, at extraction, so re-ingesting the
# same artifacts appends nothing and rewrites nothing
ROUND_DIGITS = 6


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(float(v), ROUND_DIGITS)


def weighted_median(pairs: List[Tuple[float, float]]) -> Optional[float]:
    """Median of ``(value, weight)`` pairs: the smallest value at which
    the cumulative weight reaches half the total. Deterministic (sorted
    by value, ties kept in sort order); None on empty/zero weight."""
    pairs = [(float(v), float(w)) for v, w in pairs if w > 0]
    if not pairs:
        return None
    pairs.sort(key=lambda p: (p[0], p[1]))
    total = sum(w for _, w in pairs)
    acc = 0.0
    for v, w in pairs:
        acc += w
        if acc >= total / 2:
            return v
    return pairs[-1][0]          # pragma: no cover - float-sum guard


def chip_family(topology: Optional[str]) -> Optional[str]:
    """The ChipSpec family the topology scores against — the same
    ``split("-", 1)[0]`` rule as ``autotune.score.chip_for_plan`` (kept
    string-level here: this module must import without jax)."""
    if not topology:
        return None
    return str(topology).split("-", 1)[0]


def _bench_rows(obs_dir: str) -> List[Dict[str, Any]]:
    """Observed rows from ``bench_records.jsonl``: the autotune A/B
    record measures BOTH arms (``measured_step_s_default`` /
    ``_tuned`` against their plan fingerprints); any other record with
    a plan fingerprint + a measured step time contributes one row."""
    out: List[Dict[str, Any]] = []
    path = os.path.join(obs_dir, "bench_records.jsonl")
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                logger.warning("%s:%d: skipping corrupt bench record",
                               path, i + 1)
                continue
            backend = rec.get("backend")
            topology = rec.get("topology")
            steps = rec.get("steps")
            for arm in ("default", "tuned"):
                fp = rec.get(f"plan_fingerprint_{arm}")
                step_s = rec.get(f"measured_step_s_{arm}")
                if not fp or not isinstance(step_s, (int, float)):
                    continue
                out.append({
                    "source": "bench",
                    "run_id": rec.get("run_id"),
                    "attempt": 0,
                    "arm_hint": "base" if arm == "default" else "tuned",
                    "plan_fingerprint": fp,
                    "surface": "train",
                    "topology": topology,
                    "chip_family": chip_family(topology),
                    "backend": backend,
                    "steps": int(steps) if steps else None,
                    "measured_step_s": _round(step_s),
                })
    return out


def observed_runs(obs_dir: str) -> List[Dict[str, Any]]:
    """Every deterministic observed row a run dir supports (possibly
    several runs/attempts — event files append). Rows missing the
    identity the registry keys on (a plan fingerprint and a measured
    value) are dropped, not guessed at; ``backend`` may be None here —
    ingest REFUSES such rows rather than this module inventing one."""
    events = list(iter_events(obs_dir))
    spans = list(iter_spans(obs_dir, names=("step_window",)))

    # -- per-(run_id, attempt) event context ---------------------------
    keys: List[Tuple[Optional[str], int]] = []
    ctx: Dict[Tuple[Optional[str], int], Dict[str, Any]] = {}

    def _ctx(rec) -> Dict[str, Any]:
        key = (rec.get("run_id"), int(rec.get("attempt") or 0))
        if key not in ctx:
            keys.append(key)
            ctx[key] = {"run_id": key[0], "attempt": key[1]}
        return ctx[key]

    for e in events:
        c = _ctx(e)
        if c.get("plan_fingerprint") is None \
                and e.get("plan_fingerprint"):
            c["plan_fingerprint"] = e["plan_fingerprint"]
        kind = e.get("kind")
        if kind == "attempt_start" and e.get("topology"):
            c.setdefault("topology", e["topology"])
        elif kind == "first_step" and e.get("backend"):
            c.setdefault("backend", e["backend"])
        elif kind == "attempt_end" and isinstance(e.get("goodput"), dict):
            c["goodput"] = e["goodput"]      # driver side: authoritative
        elif kind == "worker_exit" and isinstance(e.get("goodput"), dict):
            c.setdefault("goodput", e["goodput"])
        elif kind == "serve_drained" and isinstance(e.get("stats"), dict):
            c.setdefault("serve", e["stats"])

    # -- span-derived step windows, weighted by step count -------------
    windows: Dict[Tuple[Optional[str], int], List[Tuple[float, float]]] = {}
    steps_total: Dict[Tuple[Optional[str], int], int] = {}
    for s in spans:
        key = (s.get("run_id"), int(s.get("attempt") or 0))
        n = int(s.get("steps") or 0)
        if n <= 0:
            continue
        per_step = (float(s.get("dur_s") or 0.0)
                    - float(s.get("data_stall_s") or 0.0)) / n
        windows.setdefault(key, []).append((per_step, float(n)))
        steps_total[key] = steps_total.get(key, 0) + n

    rows: List[Dict[str, Any]] = []
    for key in keys:
        c = ctx[key]
        fp = c.get("plan_fingerprint")
        if not fp:
            continue
        g = c.get("goodput") or {}
        wall = float(g.get("wall_s") or 0.0)
        common = {
            "source": "obs",
            "run_id": c["run_id"],
            "attempt": c["attempt"],
            "plan_fingerprint": fp,
            "topology": c.get("topology"),
            "chip_family": chip_family(c.get("topology")),
            "backend": c.get("backend"),
            "goodput_frac": _round(
                float(g.get("step_s", 0.0)) / wall if wall > 0 else None),
            "data_stall_frac": _round(
                float(g.get("data_stall_s", 0.0)) / wall
                if wall > 0 else None),
        }
        med = weighted_median(windows.get(key, []))
        if med is not None:
            rows.append({**common, "surface": "train",
                         "steps": steps_total.get(key, 0),
                         "measured_step_s": _round(med)})
        sv = c.get("serve") or {}
        p50 = sv.get("p50_token_latency_s")
        if isinstance(p50, (int, float)) and p50 > 0:
            rows.append({
                **common, "surface": "serve",
                "steps": int(sv.get("iterations") or 0),
                "measured_per_token_s": _round(p50),
                "serve_p50_token_latency_s": _round(p50),
                "serve_p99_token_latency_s": _round(
                    sv.get("p99_token_latency_s")
                    if isinstance(sv.get("p99_token_latency_s"),
                                  (int, float)) else None),
            })

    rows.extend(_bench_rows(obs_dir))
    rows.sort(key=lambda r: (r["source"], str(r.get("run_id")),
                             r.get("attempt") or 0, r["surface"],
                             r["plan_fingerprint"]))
    return rows


def row_measure(row: Dict[str, Any]) -> Optional[float]:
    """The one measured number a row contributes to calibration/drift:
    step seconds on the train surface, per-token seconds on serve —
    mirroring ``autotune.score.rank_metric``."""
    if row.get("surface") == "serve":
        return row.get("measured_per_token_s")
    return row.get("measured_step_s")
