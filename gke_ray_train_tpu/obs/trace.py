"""Causal span tracing — the Dapper-style layer under the event stream
(ISSUE 14 tentpole, part 1).

PR 10's events say *that* a boundary happened; spans say how long it
took and what it was caused by. Every instrumented region — the worker
attempt and its ledger-timed children (restore / compile /
fast-forward / step windows / eval / checkpoint saves / the preemption
grace save), the elastic reshard (both the plan re-formation and the
resharded restore), and the serve engine's per-request lifecycle
(enqueue → prefill → decode iterations → retire) — lands as ONE JSON
line in ``<obs_dir>/spans-r<rank>.jsonl`` (driver: ``spans-rdriver``),
written when the span ENDS (complete-span records survive the SIGKILL
that usually follows the interesting ones; an in-flight span simply
never lands, which is itself a signal).

Identity is W3C-trace-context shaped:

- ``trace_id`` (32 hex) is derived DETERMINISTICALLY from the run id
  (``sha256(OBS_RUN_ID)``), so every rank of every attempt — including
  driverless multi-rank sessions that never exchange a parent — agrees
  on one trace without another env hop.
- ``span_id`` (16 hex) is random per span; the driver's per-attempt
  span id rides to workers as ``OBS_PARENT_SPAN`` through the same
  env-forwarding path as ``OBS_RUN_ID``/``OBS_ATTEMPT``, so the worker
  attempt spans parent under the driver attempt span and the merged
  DAG is connected across processes.

The span-name vocabulary is CLOSED like the event vocabulary:
:data:`SPAN_NAMES` is pinned by the shipped
``obs/schemas/trace.schema.json`` and enforced AT THE EMIT SITE — an
unknown name or stray attribute raises instead of silently orphaning
``obs/critical.py``'s term mapping.

Hot-path contract (the obs/ discipline): spans are emitted at the
boundaries the ledger already times, from host floats the caller
already measured — never per step (step windows aggregate at the log
cadence), never with a device fetch of their own. The loss stream with
TRACE=1 is asserted BITWISE-identical to obs-off.

Stdlib-only (the report/critical-path side runs with no jax).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
import uuid
from typing import Any, Dict, Iterable, Iterator, List, Optional, Union

logger = logging.getLogger(__name__)

# correlation fields stamped on EVERY span record, in this order.
# ``t0``/``t1`` are wall-clock (time.time) endpoints; ``dur_s`` is the
# authoritative duration — measured by the instrumented site itself
# (perf_counter spans / the exact float the goodput ledger booked), so
# ``obs/critical.py`` can reconcile spans against the ledger EXACTLY
# instead of within wall-clock re-derivation noise.
SPAN_STAMP = ("trace_id", "span_id", "parent_id", "name", "run_id",
              "attempt", "rank", "slice", "step", "t0", "t1", "dur_s")

# the closed span-name vocabulary: name -> allowed attribute fields.
# Pinned by obs/schemas/trace.schema.json + tests (both directions).
SPAN_NAMES: Dict[str, tuple] = {
    # run/attempt skeleton (driver writes `run` + one `attempt` per
    # attempt; every worker writes its own `attempt` span parented
    # under the driver's via OBS_PARENT_SPAN)
    "run": ("status",),
    "attempt": ("status",),
    # the ledger-timed loop boundaries (train/loop.py); durations are
    # the EXACT floats the GoodputLedger booked for the same regions
    "restore": ("resumed_step",),
    "compile": (),
    "fast_forward": (),
    "step_window": ("steps", "data_stall_s"),
    "eval": (),
    "ckpt_save": ("forced",),
    # async-commit save twin of ckpt_save (ISSUE 18): the loop's
    # residual blocking window (snapshot + enqueue) — the exact float
    # booked as ckpt_async_s; the storage commit runs in a background
    # thread and is an EVENT (ckpt_commit), never a span, because it
    # occupies no loop wall-clock to attribute
    "ckpt_snapshot": ("forced",),
    # restore served from a peer slice's hot state (ckpt/peer.py) —
    # the exact float booked as peer_restore_s
    "peer_restore": ("resumed_step",),
    "preempt_save": (),
    # elastic reshard (rayint/elastic.py plan re-formation + the
    # ckpt/manager.py resharded restore — the same twin pair the
    # reshard EVENT merges; `where` tells them apart)
    "reshard": ("from_devices", "to_devices", "where"),
    # serve request lifecycle (serve/engine.py): one request span with
    # three children decomposing "where did my p99 go" — queue wait,
    # prefill, and the decode-iteration region it shared with the
    # continuous batch
    "serve_request": ("rid", "bucket", "prompt_len", "generated",
                      "finish_reason"),
    "serve_enqueue": ("rid",),
    "serve_prefill": ("rid",),
    "serve_decode": ("rid", "iterations"),
}


class SpanError(ValueError):
    """A span violated the pinned schema (unknown name / stray attr)."""


def validate_span(name: str, attrs: Dict[str, Any]) -> None:
    """Schema teeth at the emit site (the events.py discipline): the
    contract critical-path extraction relies on is enforced where the
    span is born, not discovered at read time."""
    allowed = SPAN_NAMES.get(name)
    if allowed is None:
        raise SpanError(f"unknown span name {name!r}; known: "
                        f"{sorted(SPAN_NAMES)}")
    # stamp-named attrs are NOT allowed through: emit writes attrs
    # after the stamp dict, so a payload named `attempt`/`run_id`
    # would silently clobber the correlation fields the report groups
    # on (the explicit emit params — step/span_id/parent_id/t1 — are
    # the only sanctioned way to set those)
    stray = sorted(set(attrs) - set(allowed))
    if stray:
        raise SpanError(f"span {name!r} does not declare attributes "
                        f"{stray} (allowed: {sorted(allowed)})")


def trace_id_for_run(run_id: str) -> str:
    """The run's trace id, derived (not minted): every process that
    knows ``OBS_RUN_ID`` computes the same 32-hex id, so driverless
    multi-rank sessions still merge to ONE trace."""
    return hashlib.sha256(
        ("grt-trace:" + str(run_id)).encode()).hexdigest()[:32]


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class SpanLog:
    """Append-only JSONL span writer for one (rank, attempt) stream —
    the spans twin of ``events.EventLog`` (same append/flush-per-record
    semantics, same correlation stamps)."""

    def __init__(self, path: str, *, run_id: str, attempt: int,
                 rank: Union[int, str],
                 slice_index: Optional[int] = None):
        self.path = path
        self.run_id = str(run_id)
        self.trace_id = trace_id_for_run(run_id)
        self.attempt = int(attempt)
        self.rank = rank
        self.slice_index = slice_index
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def emit(self, name: str, dur_s: float, *,
             t1: Optional[float] = None,
             span_id: Optional[str] = None,
             parent_id: Optional[str] = None,
             step: Optional[int] = None,
             **attrs: Any) -> Dict[str, Any]:
        """Record one FINISHED span. ``dur_s`` is the caller's own
        measurement (authoritative); ``t1`` anchors it on the wall
        clock (default: now) and ``t0`` is derived — callers never
        have to carry two clocks."""
        validate_span(name, attrs)
        t1 = time.time() if t1 is None else float(t1)
        dur = max(float(dur_s), 0.0)
        rec: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": span_id or new_span_id(),
            "parent_id": parent_id,
            "name": name,
            "run_id": self.run_id,
            "attempt": self.attempt,
            "rank": self.rank,
            "slice": self.slice_index,
            "step": None if step is None else int(step),
            "t0": round(t1 - dur, 6),
            "t1": round(t1, 6),
            "dur_s": dur,
        }
        for k, v in attrs.items():
            if v is None or isinstance(v, (bool, int, float, str)):
                rec[k] = v
            else:
                rec[k] = repr(v)[:200]
        if self._f is not None and not self._f.closed:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        return rec

    def close(self) -> None:
        try:
            if self._f is not None and not self._f.closed:
                self._f.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass


def spans_path(obs_dir: str, rank: Union[int, str]) -> str:
    return os.path.join(obs_dir, f"spans-r{rank}.jsonl")


def iter_spans(obs_dir: str,
               names: Optional[Iterable[str]] = None
               ) -> Iterator[Dict[str, Any]]:
    """Every span record under ``obs_dir`` (all ranks + driver), sorted
    by start time. Corrupt lines are skipped with a warning, never
    fatal (the ``iter_events`` contract)."""
    want = set(names) if names is not None else None
    out: List[Dict[str, Any]] = []
    try:
        entries = sorted(os.listdir(obs_dir))
    except OSError:
        return iter(())
    for fname in entries:
        if not (fname.startswith("spans-") and fname.endswith(".jsonl")):
            continue
        path = os.path.join(obs_dir, fname)
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    logger.warning("%s:%d: skipping corrupt span line",
                                   path, i + 1)
                    continue
                if want is None or rec.get("name") in want:
                    out.append(rec)
    out.sort(key=lambda r: (r.get("t0", 0.0), str(r.get("rank"))))
    return iter(out)


def schema_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "schemas", "trace.schema.json")


def load_schema() -> Dict[str, Any]:
    with open(schema_path(), encoding="utf-8") as f:
        return json.load(f)


def check_schema() -> List[str]:
    """Shipped schema file <-> code contract, both directions (the
    events.check_schema shape the CI lint step and tests call)."""
    findings: List[str] = []
    try:
        doc = load_schema()
    except (OSError, ValueError) as e:
        return [f"trace schema unreadable: {type(e).__name__}: {e}"]
    if tuple(doc.get("stamp", ())) != SPAN_STAMP:
        findings.append(f"schema stamp {doc.get('stamp')} != code "
                        f"SPAN_STAMP {list(SPAN_STAMP)}")
    names = doc.get("names", {})
    if set(names) != set(SPAN_NAMES):
        findings.append(
            f"schema names {sorted(set(names) ^ set(SPAN_NAMES))} "
            "drifted from code SPAN_NAMES")
    for k in set(names) & set(SPAN_NAMES):
        if tuple(names[k]) != tuple(SPAN_NAMES[k]):
            findings.append(f"schema name {k!r} attrs {names[k]} != "
                            f"code {list(SPAN_NAMES[k])}")
    return findings
