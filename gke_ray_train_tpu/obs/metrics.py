"""Cross-rank metrics registry + exporters (ISSUE 11 tentpole, part 2).

A lightweight counters/gauges/histograms registry every rank exports as
both a Prometheus textfile (``metrics-r<rank>.prom`` — a GKE-side
node-exporter textfile collector or sidecar scrapes it with NO new
deps) and JSON (``metrics-r<rank>.json`` — what ``obs report`` merges).

The metric NAME vocabulary is closed, like the event vocabulary:
:data:`METRIC_NAMES` is pinned by ``obs/schemas/metrics.schema.json``
and the test_obs contract test, so a renamed metric fails lint instead
of silently forking dashboards. The registry itself is dumb on purpose:
values are pushed by the code that already computed them (the loop's
log-cadence metrics, the goodput ledger at attempt close, the serve
engine's stats, the persistent-cache counters) — there is no second
computation path to drift.

Hot-path contract: ``Counter.inc``/``Gauge.set``/``Histogram.observe``
are a few python ops on host floats. Nothing here touches jax or the
device; ``pull_jax_counters`` reads the already-maintained host-side
``perf.cache`` counters. Stdlib-only.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# the closed metric vocabulary: name -> type. Pinned by
# obs/schemas/metrics.schema.json + tests/test_obs.py. goodput_* mirror
# train/metrics.py LEDGER_TERMS via ledger_metrics() — one source.
METRIC_NAMES: Dict[str, str] = {
    # loop progress / throughput (train/loop.py log cadence)
    "steps_total": "counter",
    "loss": "gauge",
    "learning_rate": "gauge",
    "grad_norm": "gauge",
    "eval_loss": "gauge",
    "tokens_per_sec_per_chip": "gauge",
    "mfu": "gauge",
    "data_stall_frac": "gauge",
    # per-step host timing distributions (obs/capture.py feeds these —
    # host iteration wall, data wait; no device sync involved)
    "step_time_s": "histogram",
    "data_wait_s": "histogram",
    # goodput ledger terms (train/metrics.py LEDGER_TERMS + wall/frac)
    "goodput_compile_s": "gauge",
    "goodput_restore_s": "gauge",
    "goodput_fast_forward_s": "gauge",
    "goodput_data_stall_s": "gauge",
    "goodput_eval_ckpt_stall_s": "gauge",
    "goodput_ckpt_async_s": "gauge",
    "goodput_peer_restore_s": "gauge",
    "goodput_step_s": "gauge",
    "goodput_lost_s": "gauge",
    "goodput_wall_s": "gauge",
    "goodput_frac": "gauge",
    # network traffic of the compiled train step (perf/costs.py
    # StepCostReport, noted once at AOT build time by perf/cache.py —
    # no second computation): collective bytes split by the fabric
    # their replica groups span. grt_dcn_bytes is the cross-slice
    # number DCN_SYNC=hier shrinks; flat-lined at 0 on single-slice
    # pools by construction.
    "ici_bytes": "gauge",
    "dcn_bytes": "gauge",
    # compile-once health (perf/cache.py jax.monitoring counters)
    "compile_cache_hits": "gauge",
    "compile_cache_misses": "gauge",
    "compile_time_saved_s": "gauge",
    "backend_compiles_total": "counter",
    # anomaly-triggered profiling (obs/capture.py)
    "anomalies_total": "counter",
    "captures_total": "counter",
    # serving (serve/engine.py stats — same numbers BENCH_MODE=serve pins)
    "serve_iterations_total": "counter",
    "serve_refills_total": "counter",
    "serve_completed_total": "counter",
    "serve_p50_token_latency_s": "gauge",
    "serve_p99_token_latency_s": "gauge",
    "serve_batch_occupancy": "gauge",
    # multi-tenant serving (ISSUE 17): adapter-pool residency churn
    # (serve/adapters.py LRU), host-side prefix/KV reuse, and the
    # speculative-decode acceptance ledger (proposed draft tokens vs
    # target-verified accepts — the throughput lever's own telemetry)
    "serve_adapter_hits_total": "counter",
    "serve_adapter_misses_total": "counter",
    "serve_adapter_evictions_total": "counter",
    "serve_prefix_hits_total": "counter",
    "serve_spec_proposed_total": "counter",
    "serve_spec_accepted_total": "counter",
    # admitted request length (prompt + max_new_tokens) at the engine's
    # submit path — the workload-shape distribution bucket-padding and
    # MAX_BATCH tuning decisions are made against
    "request_len": "histogram",
}

PROM_PREFIX = "grt_"      # gke_ray_train_tpu, short for scrape configs


class MetricError(ValueError):
    """A metric violated the pinned name/type vocabulary."""


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    """count/sum plus a bounded RESERVOIR sample for p50/p99 — enough
    for the serving-latency shape without a streaming-quantile
    dependency.

    Past ``max_samples`` the sample is maintained by Vitter's
    Algorithm R: observation ``n`` replaces a random slot with
    probability ``max_samples/n``, so the retained sample stays a
    uniform draw over the WHOLE run. The previous rotating-slot scheme
    kept only the most recent window, so a long run's p50/p99 silently
    forgot every earlier regime (and the scheme before that stopped
    admitting entirely — quantiles frozen on the run's first minutes).
    The "randomness" is a fixed-seed 64-bit LCG: two integer ops per
    observation, deterministic across runs, no RNG machinery on the
    hot path."""
    __slots__ = ("name", "count", "sum", "_samples", "_max", "_rng")

    def __init__(self, name: str, max_samples: int = 2048):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self._samples: List[float] = []
        self._max = max_samples
        # deterministic per-instrument seed (name-derived, stable)
        self._rng = (0x9E3779B97F4A7C15
                     ^ int.from_bytes(name.encode()[:8].ljust(8, b"\0"),
                                      "little")) & 0xFFFFFFFFFFFFFFFF

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if len(self._samples) < self._max:
            self._samples.append(value)
        else:
            # Algorithm R with an inline LCG (Knuth MMIX constants)
            self._rng = (self._rng * 6364136223846793005
                         + 1442695040888963407) & 0xFFFFFFFFFFFFFFFF
            j = (self._rng >> 11) % self.count
            if j < self._max:
                self._samples[j] = value

    def quantile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        return s[min(int(q * len(s)), len(s) - 1)]

    def snapshot(self) -> Dict[str, float]:
        return {"count": self.count, "sum": self.sum,
                "p50": self.quantile(0.50), "p99": self.quantile(0.99)}


class MetricsRegistry:
    """One registry per process; named instruments are created on first
    use and must appear in :data:`METRIC_NAMES` with the right type —
    the schema is enforced where the metric is born."""

    def __init__(self, labels: Optional[Dict[str, str]] = None):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self.labels: Dict[str, str] = dict(labels or {})

    def set_labels(self, **labels: Any) -> None:
        self.labels.update({k: str(v) for k, v in labels.items()
                            if v is not None})

    def _get(self, name: str, kind: str, factory):
        declared = METRIC_NAMES.get(name)
        if declared is None:
            raise MetricError(f"metric {name!r} not in the pinned "
                              "vocabulary (obs/metrics.py METRIC_NAMES "
                              "+ schemas/metrics.schema.json)")
        if declared != kind:
            raise MetricError(f"metric {name!r} is declared a "
                              f"{declared}, not a {kind}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory(name)
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, "counter", Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, "gauge", Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, "histogram", Histogram)

    def set_many(self, values: Dict[str, Any]) -> None:
        """Gauges from a metrics dict, keeping only keys the vocabulary
        declares as gauges — the loop feeds its whole log-cadence dict
        and the registry takes the declared slice (unknown keys are the
        caller's own business, not a schema violation)."""
        for k, v in values.items():
            if METRIC_NAMES.get(k) == "gauge" and isinstance(
                    v, (int, float)) and not isinstance(v, bool) \
                    and math.isfinite(float(v)):
                self.gauge(k).set(float(v))

    # -- export --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {"labels": dict(self.labels)}
            for name, m in sorted(self._metrics.items()):
                if isinstance(m, Histogram):
                    out[name] = m.snapshot()
                else:
                    out[name] = m.value
            return out

    def to_prometheus(self) -> str:
        label_s = ",".join(f'{k}="{v}"'
                           for k, v in sorted(self.labels.items()))
        label_s = "{" + label_s + "}" if label_s else ""
        lines: List[str] = []
        with self._lock:
            for name, m in sorted(self._metrics.items()):
                kind = METRIC_NAMES[name]
                pname = PROM_PREFIX + name
                lines.append(f"# TYPE {pname} "
                             f"{'summary' if kind == 'histogram' else kind}")
                if isinstance(m, Histogram):
                    snap = m.snapshot()
                    for q in ("0.5", "0.99"):
                        ql = label_s[:-1] + f',quantile="{q}"}}' \
                            if label_s else f'{{quantile="{q}"}}'
                        lines.append(
                            f"{pname}{ql} "
                            f"{snap['p50' if q == '0.5' else 'p99']:.9g}")
                    lines.append(f"{pname}_sum{label_s} {snap['sum']:.9g}")
                    lines.append(f"{pname}_count{label_s} {snap['count']}")
                else:
                    lines.append(f"{pname}{label_s} {m.value:.9g}")
        return "\n".join(lines) + "\n"

    def export(self, obs_dir: str, rank) -> Dict[str, str]:
        """Write both export formats atomically (tmp + rename — a
        scraper must never read a torn file). Returns the paths."""
        os.makedirs(obs_dir, exist_ok=True)
        paths = {}
        for suffix, payload in (
                (".json", json.dumps(self.snapshot(), sort_keys=True,
                                     indent=1)),
                (".prom", self.to_prometheus())):
            path = os.path.join(obs_dir, f"metrics-r{rank}{suffix}")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
            os.replace(tmp, path)
            paths[suffix] = path
        return paths


def pull_jax_counters(reg: MetricsRegistry) -> None:
    """Mirror the perf.cache jax.monitoring counters into the registry
    (host-side dict reads; safe with no backend and cheap enough for
    the log cadence)."""
    try:
        from gke_ray_train_tpu.perf.cache import cache_stats
        s = cache_stats()
        reg.gauge("compile_cache_hits").set(s["hits"])
        reg.gauge("compile_cache_misses").set(s["misses"])
        reg.gauge("compile_time_saved_s").set(s["compile_time_saved_s"])
    except Exception as e:  # noqa: BLE001 - telemetry is best-effort
        logger.debug("cache counters unavailable: %s", e)


def export_serve_stats(reg: MetricsRegistry, stats: Dict[str, Any]) -> None:
    """serve/engine.py ``stats()`` -> the registry, one mapping (the
    TB satellite and the exporter both read the registry, so serving
    latency/occupancy has exactly one computation path)."""
    for src, dst in (("iterations", "serve_iterations_total"),
                     ("refills", "serve_refills_total"),
                     ("completed", "serve_completed_total"),
                     ("adapter_hits", "serve_adapter_hits_total"),
                     ("adapter_misses", "serve_adapter_misses_total"),
                     ("adapter_evictions", "serve_adapter_evictions_total"),
                     ("prefix_hits", "serve_prefix_hits_total"),
                     ("spec_proposed", "serve_spec_proposed_total"),
                     ("spec_accepted", "serve_spec_accepted_total")):
        if src in stats:
            c = reg.counter(dst)
            c.value = float(stats[src])
    for src, dst in (("p50_token_latency_s", "serve_p50_token_latency_s"),
                     ("p99_token_latency_s", "serve_p99_token_latency_s"),
                     ("batch_occupancy", "serve_batch_occupancy")):
        if src in stats:
            reg.gauge(dst).set(float(stats[src]))


def schema_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "schemas", "metrics.schema.json")


def check_schema() -> List[str]:
    """Shipped metric schema <-> code vocabulary, same contract shape
    as events.check_schema. Also cross-checks the goodput_* names
    against train/metrics.py LEDGER_TERMS — the ledger is the one
    source of those terms."""
    findings: List[str] = []
    try:
        with open(schema_path(), encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"metrics schema unreadable: {type(e).__name__}: {e}"]
    declared = doc.get("metrics", {})
    if declared != METRIC_NAMES:
        drift = sorted(set(declared) ^ set(METRIC_NAMES)) or sorted(
            k for k in declared if declared[k] != METRIC_NAMES.get(k))
        findings.append(f"metrics schema drifted from METRIC_NAMES: "
                        f"{drift}")
    try:
        from gke_ray_train_tpu.train.metrics import LEDGER_TERMS
        want = {f"goodput_{t}" for t in LEDGER_TERMS} | {
            "goodput_wall_s", "goodput_frac"}
        have = {k for k in METRIC_NAMES if k.startswith("goodput_")}
        if want != have:
            findings.append(
                f"goodput metric names {sorted(want ^ have)} drifted "
                "from train/metrics.py LEDGER_TERMS")
    except Exception as e:  # noqa: BLE001 - jax may be unimportable
        logger.debug("ledger cross-check skipped: %s", e)
    return findings
