"""Peer-slice hot-state replication (ISSUE 18 tentpole b).

A slice eviction today costs a full storage round-trip: the rescheduled
attempt restores from the last committed checkpoint and replays every
step since. But on a multi-slice job the OTHER slice is usually still
alive and holds a byte-identical replica of the optimizer+param state
(the data axis — the only axis that spans slices, per the PR-5
contract — replicates state across slices). This module keeps that
replica REACHABLE: at every snapshot each slice streams its state, as
the per-device scattered shards the cross-slice hop of
``parallel/hierarchical.py`` already moves, to its ring neighbor
``(slice + 1) % num_slices``; after a ``slice_evict`` the survivor
serves the resume directly — no storage read, no replay past the last
snapshot.

Emulation shape (the CPU-mesh stand-in for the DCN stream): the hot
store is a process-global dict keyed by the checkpoint directory —
every slice of the emulated mesh lives in this process, so "streaming
to the peer" is a handoff into the peer's keyed slot, and
``evict_slice`` deletes a slot exactly as the eviction kills that
slice's host memory. The BYTES are accounted for real, though: one
round moves ``num_slices x replica_nbytes`` across DCN
(``parallel.hierarchical.peer_replication_elems`` is the static
element oracle; :func:`round_dcn_bytes` the byte one), and
``perf/budget.py`` pins the live counter against it at tolerance 0.

``compress="bf16"`` (``PEER_COMPRESS=bf16``) casts the floating leaves
of the stream to bf16 with error feedback ACROSS ROUNDS — round *k*'s
quantization residual is added back into round *k+1*'s pre-cast value,
the same machinery as ``DCN_COMPRESS`` — halving the replication
bytes. Not bitwise; the restore-bitwise drills run uncompressed.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)

# run_key (checkpoint dir) -> holder slice -> replica record. Process-
# global on purpose: the emulated slices share this process, and the
# store must survive the per-attempt teardown of CheckpointManager
# instances the way a peer slice's memory survives its neighbor's death.
_HOT: Dict[str, Dict[int, dict]] = {}
# run_key -> evicted holder indices. An evicted slice's memory is GONE:
# later snapshots of the same run incarnation must not resurrect its
# slot (the post-eviction grace save would otherwise 'stream to' a
# slice that no longer exists). Cleared with reset() — a whole-job
# retry is a new incarnation where every scheduled slice is back.
_DEAD: Dict[str, set] = {}
_LOCK = threading.Lock()


def state_replica_nbytes(tree: Any) -> int:
    """Bytes of ONE state replica (works on concrete arrays and
    ShapeDtypeStructs — the budget side feeds it eval_shape leaves)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", ())
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        n = 1
        for s in shape:
            n *= int(s)
        total += n * dtype.itemsize
    return total


def round_dcn_bytes(tree: Any, num_slices: int) -> int:
    """DCN bytes one uncompressed replication round moves: every slice
    streams its full replica to its ring neighbor."""
    return max(int(num_slices), 1) * state_replica_nbytes(tree)


def reset(run_key: Optional[str] = None) -> None:
    """Drop hot state (one run's, or everything) — test isolation."""
    with _LOCK:
        if run_key is None:
            _HOT.clear()
            _DEAD.clear()
        else:
            _HOT.pop(str(run_key), None)
            _DEAD.pop(str(run_key), None)


class PeerReplicator:
    """The replication endpoint one CheckpointManager binds
    (``PEER_REPLICATION=1``): ``replicate`` on every snapshot,
    ``peek``/``restore`` on resume, ``evict_slice`` from the fault
    drill."""

    def __init__(self, num_slices: Optional[int] = None, *,
                 compress: str = "none",
                 shards_per_stream: Optional[int] = None):
        if num_slices is None:
            num_slices = int(os.environ.get("NUM_SLICES", "2") or "2")
        self.num_slices = max(int(num_slices), 1)
        if compress not in ("none", "bf16"):
            raise ValueError(f"unknown peer compression {compress!r} "
                             "(none|bf16)")
        self.compress = compress
        if shards_per_stream is None:
            # the per-device shard granularity of the emulated stream:
            # the slice's ICI width (devices per slice) when derivable
            try:
                shards_per_stream = max(
                    jax.device_count() // self.num_slices, 1)
            except Exception:  # noqa: BLE001 - backend-free callers
                shards_per_stream = 1
        self.shards_per_stream = max(int(shards_per_stream), 1)
        # error-feedback residuals for bf16 streams, per run_key + leaf
        self._residual: Dict[str, List[Optional[np.ndarray]]] = {}
        self.last_round_bytes = 0
        self.total_bytes = 0
        self.rounds = 0

    @classmethod
    def from_env(cls) -> "PeerReplicator":
        return cls(compress=os.environ.get("PEER_COMPRESS", "none")
                   or "none")

    # ------------------------------------------------------------------

    def _split(self, arr: np.ndarray) -> List[np.ndarray]:
        """The scattered-shard framing of one leaf's stream (cosmetic
        for byte accounting — concatenate inverts it exactly)."""
        if arr.ndim == 0 or arr.shape[0] < 2:
            return [arr]
        pieces = min(self.shards_per_stream, arr.shape[0])
        return list(np.array_split(arr, pieces, axis=0))

    def _encode(self, run_key: str, leaves: List[np.ndarray]
                ) -> Tuple[List[np.ndarray], int]:
        """(streamed leaves, streamed bytes) — bf16 cast with
        cross-round error feedback when compression is on."""
        if self.compress == "none":
            return leaves, sum(x.nbytes for x in leaves)
        import jax.numpy as jnp
        res = self._residual.setdefault(run_key,
                                        [None] * len(leaves))
        if len(res) != len(leaves):  # tree changed shape: start over
            res = self._residual[run_key] = [None] * len(leaves)
        out: List[np.ndarray] = []
        nbytes = 0
        for i, x in enumerate(leaves):
            if not np.issubdtype(x.dtype, np.floating):
                out.append(x)
                nbytes += x.nbytes
                continue
            y = x if res[i] is None else x + res[i]
            q = np.asarray(jnp.asarray(y, jnp.bfloat16))
            res[i] = np.asarray(y - np.asarray(q, y.dtype), x.dtype)
            out.append(q)
            nbytes += q.nbytes
        return out, nbytes

    def replicate(self, run_key: str, step: int,
                  host_state: Any) -> dict:
        """Stream this snapshot to the ring neighbor of every slice.
        One emulated round: ``num_slices`` identical replicas move (the
        data axis replicates state across slices), so holder ``h`` ends
        up with the state owned by slice ``(h - 1) % num_slices``.
        Returns ``{"bytes", "to_slice", "step"}`` — ``bytes`` is the
        ROUND total (what crosses DCN), ``to_slice`` the ring offset."""
        run_key = str(run_key)
        leaves, treedef = jax.tree.flatten(host_state)
        leaves = [np.asarray(x) for x in leaves]
        streamed, per_stream = self._encode(run_key, leaves)
        chunks = [self._split(x) for x in streamed]
        with _LOCK:
            slot = _HOT.setdefault(run_key, {})
            dead = _DEAD.get(run_key, set())
            alive = [h for h in range(self.num_slices) if h not in dead]
            total = per_stream * len(alive)
            for holder in alive:
                owner = (holder - 1) % self.num_slices
                slot[holder] = {
                    "step": int(step),
                    "from_slice": holder,
                    "owner": owner,
                    "treedef": treedef,
                    "chunks": chunks,
                    "bytes": per_stream,
                    "compress": self.compress,
                }
        self.last_round_bytes = total
        self.total_bytes += total
        self.rounds += 1
        logger.info("peer-replicated step %d: %d B across DCN "
                    "(%d streams x %d B, compress=%s)", step, total,
                    len(alive), per_stream, self.compress)
        return {"bytes": total, "to_slice": 1, "step": int(step)}

    # ------------------------------------------------------------------

    def peek(self, run_key: str) -> Optional[int]:
        """Newest step any SURVIVING holder can serve (None: no hot
        state — fall back to storage)."""
        with _LOCK:
            slot = _HOT.get(str(run_key))
            if not slot:
                return None
            return max(rec["step"] for rec in slot.values())

    def restore(self, run_key: str, template: Any
                ) -> Tuple[Any, dict]:
        """Rebuild the state from a surviving holder's hot replica and
        place it onto the template's shardings. Uncompressed streams
        restore BITWISE-identical to the storage path (both copy the
        same host snapshot). Returns ``(state, {"step", "bytes",
        "from_slice"})``."""
        with _LOCK:
            slot = _HOT.get(str(run_key))
            if not slot:
                raise LookupError(f"no peer hot state for {run_key}")
            holder = max(slot, key=lambda h: (slot[h]["step"], h))
            rec = slot[holder]
        t_leaves, treedef = jax.tree.flatten(template)
        if treedef != rec["treedef"]:
            raise ValueError(
                "peer hot state tree structure does not match the "
                "restore template (plan changed since the snapshot)")
        out_leaves = []
        for pieces, like in zip(rec["chunks"], t_leaves):
            arr = pieces[0] if len(pieces) == 1 \
                else np.concatenate(pieces, axis=0)
            dtype = getattr(like, "dtype", arr.dtype)
            if arr.dtype != dtype:
                arr = arr.astype(dtype)  # decompress (bf16 stream)
            sharding = getattr(like, "sharding", None)
            out_leaves.append(jax.device_put(arr, sharding)
                              if sharding is not None
                              else jax.device_put(arr))
        state = jax.tree.unflatten(treedef, out_leaves)
        meta = {"step": int(rec["step"]), "bytes": int(rec["bytes"]),
                "from_slice": int(holder)}
        logger.info("restored step %d from peer slice %d hot state "
                    "(%d B, no storage read)", rec["step"], holder,
                    rec["bytes"])
        return state, meta

    # ------------------------------------------------------------------

    def evict_slice(self, run_key: str, slice_index: int) -> bool:
        """The eviction kills that slice's memory: its hot slot dies
        with it, and the slot stays dead for the rest of this run
        incarnation (later snapshots — e.g. the post-eviction grace
        save — must not 'stream to' a slice that no longer exists).
        True when there was one to kill."""
        with _LOCK:
            _DEAD.setdefault(str(run_key), set()).add(int(slice_index))
            slot = _HOT.get(str(run_key))
            if slot is None:
                return False
            return slot.pop(int(slice_index), None) is not None

    def holders(self, run_key: str) -> Dict[int, int]:
        """Surviving holder -> step (diagnostics/tests)."""
        with _LOCK:
            slot = _HOT.get(str(run_key), {})
            return {int(h): int(rec["step"])
                    for h, rec in slot.items()}
