"""Sharded checkpointing with retention + resume (SURVEY.md §5.4).

Reference behavior replaced:
- Rank-0 torch.save of model/optimizer/scheduler state_dicts + Ray
  ``Checkpoint.from_directory`` (ray-jobs/pytorch_llm_ray.py:296-310) with
  ``CheckpointConfig(num_to_keep=1, checkpoint_score_attribute="loss",
  order="min")`` retention (:355-359).
- **Resume is never implemented in the reference** (no
  ``train.get_checkpoint()`` anywhere); ``restore_if_available`` fixes
  that gap (§5.3).

TPU redesign: once params are GSPMD-sharded, rank-0-only save is invalid —
orbax writes the distributed pytree collectively (every host participates)
and restores it into the same shardings.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

TOPOLOGY_NOTE = "topology.json"


def _tree_n_devices(tree: Any) -> Optional[int]:
    """Device count of the mesh a concrete pytree lives on (None when
    no leaf carries a sharding — e.g. an abstract template)."""
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        devs = getattr(sharding, "device_set", None)
        if devs:
            return len(devs)
    return None


class CheckpointRestoreError(RuntimeError):
    """A collectively-agreed restore failure. The trainer's retry
    classifier treats this as RETRYABLE even though the underlying
    orbax/tensorstore cause is often a ValueError (which would
    otherwise fail fast as 'deterministic') — a fresh attempt re-reads
    storage and can succeed where a flake failed."""


class CheckpointManager:
    """Thin orbax wrapper carrying the reference's retention contract."""

    def __init__(self, directory: str, *, max_to_keep: int = 1,
                 score_attribute: str = "loss", score_mode: str = "min",
                 save_interval_steps: int = 1, async_save: bool = True):
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            best_fn=(lambda m: m[score_attribute]) if score_attribute else None,
            best_mode=score_mode,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(directory, options=self._options)
        self.directory = directory
        # (saved_n_devices, restored_n_devices) of the last restore
        # that crossed topologies — the elastic-resume witness the
        # trainer/tests read; None = same-topology (or unknown) restore
        self.last_restore_resharded: Optional[Tuple[int, int]] = None

    def _note_topology(self, step: int, state: Any) -> None:
        """Record the saving mesh's device count beside the checkpoints
        (best-effort, host 0) so a later restore can SAY it resharded —
        the save-time topology is not recoverable from orbax metadata."""
        n = _tree_n_devices(state)
        if n is None:
            return
        try:
            if jax.process_index() != 0:
                return
        except Exception:  # noqa: BLE001 - backend-free callers
            pass
        try:
            with open(os.path.join(str(self.directory),
                                   TOPOLOGY_NOTE), "w") as f:
                json.dump({"step": int(step), "n_devices": int(n)}, f)
        except OSError as e:  # pragma: no cover - note is best-effort
            logger.debug("could not write topology note: %s", e)

    def saved_topology(self) -> Optional[dict]:
        try:
            with open(os.path.join(str(self.directory),
                                   TOPOLOGY_NOTE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def save(self, step: int, state: Any, metrics: Optional[dict] = None,
             force: bool = False) -> bool:
        metrics = {k: float(v) for k, v in (metrics or {}).items()}
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                               metrics=metrics, force=force)
        if saved:
            self._note_topology(step, state)
            logger.info("checkpoint saved at step %d (metrics=%s)",
                        step, metrics)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def best_step(self) -> Optional[int]:
        return self._mgr.best_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of ``state_like`` (an abstract
        or concrete pytree — shardings are honored, so a checkpoint saved
        on one mesh restores resharded onto another)."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(abstract))

    def restore_resharded(self, state_like: Any, mesh, spec_tree: Any,
                          step: Optional[int] = None) -> Any:
        """Cross-topology restore: re-derive shardings from the LOGICAL
        PartitionSpec tree on a NEW mesh instead of reusing the saved
        layout — the reshard-on-restore path of elastic resume (ROADMAP
        #1: save on a 16-chip mesh, restore on 8). ``spec_tree`` must be
        structurally isomorphic to ``state_like`` (params:
        ``models.transformer.param_specs``; optimizer state:
        ``train.step.opt_state_specs``; an ExecutionPlan supplies the
        batch/mesh side). ``analysis plancheck`` (PLAN003) statically
        proves every (save, restore) topology pair this path will be
        asked to handle is well-formed — same logical shapes, valid
        shardings on the restore mesh."""
        from jax.sharding import NamedSharding

        abstract = jax.tree.map(
            lambda leaf, spec: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype,
                sharding=NamedSharding(mesh, spec)),
            jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like),
            spec_tree)
        return self.restore(abstract, step=step)

    def restore_raw(self, step: Optional[int] = None) -> Any:
        """Topology-free restore: structure/shapes come from checkpoint
        metadata, everything lands on this host's first device — the
        offline-converter path, where the save-time mesh (TPU pod) does
        not exist on the converting machine."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        meta = self._mgr.item_metadata(step)
        sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=sh),
            meta, is_leaf=lambda x: hasattr(x, "shape"))
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def item_metadata(self, step: Optional[int] = None) -> Any:
        """Shape/dtype metadata tree of a saved checkpoint (no data
        read) — drives the converter's leaf-by-leaf walk.

        Read via a standalone PyTreeCheckpointer on the step's item dir:
        the manager's own ``item_metadata`` returns an EMPTY tree in any
        process that has not yet registered a 'default' handler (i.e.
        every fresh converter process) and only warns about it."""
        import os
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        ckptr = ocp.PyTreeCheckpointer()
        meta = ckptr.metadata(
            os.path.join(str(self.directory), str(step), "default"))
        # orbax >= 0.6 wraps the tree (CheckpointMetadata.item_metadata
        # .tree); older releases hand the metadata tree back directly
        if hasattr(meta, "item_metadata"):
            return meta.item_metadata.tree
        return meta

    def restore_partial(self, abstract: Any,
                        step: Optional[int] = None) -> Any:
        """Restore only a subset of the saved tree — the offline
        converter reads one leaf at a time this way, so a 70B conversion
        needs O(one leaf) RAM instead of the whole tree (VERDICT r3 weak
        #4b).

        On orbax with ``PLACEHOLDER`` support, ``abstract`` is the full
        structure with every unwanted leaf placeholder'd; on older
        releases it is the partial subtree and ``transforms={}`` tells
        the handler to drop checkpoint entries not present in it."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if hasattr(ocp, "PLACEHOLDER"):
            args = ocp.args.PyTreeRestore(item=abstract)
        else:
            args = ocp.args.PyTreeRestore(
                item=abstract, transforms={},
                restore_args=ocp.checkpoint_utils.construct_restore_args(
                    abstract))
        return self._mgr.restore(step, args=args)

    @staticmethod
    def _any_host_failed(local_failed: bool) -> bool:
        """Collective agreement on a restore outcome: every host enters,
        every host leaves with the same verdict — the prerequisite for
        a fallback/quarantine that cannot diverge the slice."""
        if jax.process_count() <= 1:
            return local_failed
        from jax.experimental import multihost_utils
        import numpy as np
        flags = multihost_utils.process_allgather(
            np.asarray(1 if local_failed else 0, np.int32))
        return bool(np.max(flags))

    def _quarantine(self, step: int) -> str:
        """Move an unrestorable step directory aside (``<step>.corrupt``)
        so it never shadows a good checkpoint again, and refresh the
        manager's step cache. All hosts enter (the verdict was
        collective); host 0 renames, everyone syncs before reloading."""
        import os
        import shutil

        src = os.path.join(str(self.directory), str(step))
        dst = src + ".corrupt"
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f"{src}.corrupt{n}"
        multi = jax.process_count() > 1
        if not multi or jax.process_index() == 0:
            if os.path.isdir(src):
                shutil.move(src, dst)
        if multi:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt_quarantine_{step}")
        if hasattr(self._mgr, "reload"):
            self._mgr.reload()
        else:  # pragma: no cover - pre-reload orbax
            self._mgr = ocp.CheckpointManager(self.directory,
                                              options=self._options)
        return dst

    def restore_if_available(self, state_like: Any):
        """(state, resumed_step) — the resume-on-retry behavior the
        reference lacks. Returns (state_like, None) on a fresh start.

        Integrity fallback: the latest step is VERIFIED by restoring it;
        when that fails (an interrupted async save left a committed but
        torn tail — without this, every subsequent resume crashes on the
        same bad step) the newest earlier restorable step is used and
        each newer unrestorable step is quarantined as ``<step>.corrupt``.
        When EVERY step fails the first error re-raises and nothing is
        quarantined: that signature is a template/layout mismatch (the
        caller's pytree, not the data, is wrong — see the ckpt_view
        fallback in train/loop.py), and quarantining healthy checkpoints
        on a caller bug would destroy the run's only resume points.

        Each step gets one bounded retry before being declared
        unrestorable — a transient storage flake must not cost the
        newest checkpoint. On multi-host runs every verdict is
        COLLECTIVE (``_any_host_failed``): a step counts as failed when
        ANY host failed it, all hosts retry/fall back/quarantine in
        lockstep, and a host whose local restore succeeded discards the
        result rather than diverge — per-host divergence here would
        wedge the slice in its next collective."""
        steps = sorted(self._mgr.all_steps(), reverse=True)
        if not steps:
            return state_like, None
        first_err: Optional[Exception] = None
        failed: list = []
        for step in steps:
            out = err = None
            restored = False
            t_restore0 = time.perf_counter()
            for restore_try in range(2):
                try:
                    out = self.restore(state_like, step)
                except Exception as e:  # noqa: BLE001 - classified below
                    err = e
                if not self._any_host_failed(err is not None):
                    restored = True
                    break
                if err is None:
                    # this host restored fine but another did not — the
                    # verdict is collective, so align with the failure
                    err = CheckpointRestoreError(
                        f"step {step} failed to restore on another host")
                out = None
                if restore_try == 0:
                    logger.warning(
                        "restore of step %d failed (%s: %s); retrying "
                        "once before treating it as corrupt", step,
                        type(err).__name__, err)
                    err = None
            if not restored:
                first_err = first_err if first_err is not None else err
                failed.append((step, err))
                continue
            for bad, bad_err in failed:
                logger.warning(
                    "checkpoint step %d is unrestorable (%s: %s); "
                    "quarantining it and resuming from step %d",
                    bad, type(bad_err).__name__, bad_err, step)
                self._quarantine(bad)
            # elastic-resume witness: a restore onto a different device
            # count than the save is a reshard (shardings re-derived
            # from the template) — say so, and leave the evidence for
            # the trainer's attempt log
            self.last_restore_resharded = None
            note = self.saved_topology()
            cur_n = _tree_n_devices(state_like)
            if note and cur_n and int(note.get("n_devices", 0)) and \
                    int(note["n_devices"]) != cur_n:
                self.last_restore_resharded = (int(note["n_devices"]),
                                               cur_n)
                logger.warning(
                    "elastic resume: checkpoint step %d was saved on %d "
                    "devices; restored RESHARDED onto %d (shardings "
                    "re-derived from the restore template)",
                    step, int(note["n_devices"]), cur_n)
                # obs: the resharded restore IS the reshard witness —
                # one event per actual mesh transition (8->4 AND 4->8
                # in the elastic drill), rendered on the attempt
                # timeline by `obs report` (no-op when obs is off)
                from gke_ray_train_tpu.obs import runtime as obs_runtime
                obs_runtime.emit("reshard", step=step,
                                 from_devices=int(note["n_devices"]),
                                 to_devices=cur_n)
                # the restore-level half of the reshard span twin pair
                # (rayint/elastic.py spans the plan re-formation): how
                # long the RESHARDED restore itself took
                obs_runtime.span_add(
                    "reshard", time.perf_counter() - t_restore0,
                    step=step, from_devices=int(note["n_devices"]),
                    to_devices=cur_n, where="restore")
            logger.info("resuming from checkpoint step %d in %s", step,
                        self.directory)
            return out, step
        raise first_err

    def wait(self) -> None:
        """Block until async saves are durable (call before process exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
