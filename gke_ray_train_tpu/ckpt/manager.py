"""Sharded checkpointing with retention + resume (SURVEY.md §5.4).

Reference behavior replaced:
- Rank-0 torch.save of model/optimizer/scheduler state_dicts + Ray
  ``Checkpoint.from_directory`` (ray-jobs/pytorch_llm_ray.py:296-310) with
  ``CheckpointConfig(num_to_keep=1, checkpoint_score_attribute="loss",
  order="min")`` retention (:355-359).
- **Resume is never implemented in the reference** (no
  ``train.get_checkpoint()`` anywhere); ``restore_if_available`` fixes
  that gap (§5.3).

TPU redesign: once params are GSPMD-sharded, rank-0-only save is invalid —
orbax writes the distributed pytree collectively (every host participates)
and restores it into the same shardings.

Write-ahead commit (ISSUE 18 tentpole a): every save is bracketed by
durable markers beside the step directories —

    COMMITTING.<step>   (fsync'd BEFORE any step data is serialized)
    COMMITTED.<step>    (fsync'd only after the step data is durable)

A ``COMMITTING`` marker without its ``COMMITTED`` twin is the on-disk
signature of a mid-commit death. In **async-commit** mode
(``async_commit=True`` / ``ASYNC_CKPT=1``) the caller-facing ``save``
does ONE device→host snapshot and returns; a background committer
thread serializes to storage behind the marker pair, and the restore
path treats the mid-commit signature as "this step never existed" —
quarantined without a restore attempt, falling back to the previous
committed step. In the default synchronous mode the markers are
advisory: ``latest_step()`` never offers a marker-suspect step (the
satellite-1 contract — a quarantined step directory that reappears
after a second crash at the same step), but ``restore_if_available``
still verifies suspects by restoring (the save may well be durable —
only the lazy marker flush was lost with the process) and promotes the
marker on success. Marker-less step directories — every checkpoint
written before this protocol existed — stay trusted.

Peer-slice hot state (ISSUE 18 tentpole b): when a
``ckpt.peer.PeerReplicator`` is bound (``PEER_REPLICATION=1``), every
snapshot also streams to the peer slice's hot store, and
``restore_if_available`` serves the resume from the living peer —
no storage read — whenever the peer's step is at least as new as the
latest committed one (``last_restore_source``/``last_peer_restore``
tell the loop which ledger term to book).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import threading
import time
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

TOPOLOGY_NOTE = "topology.json"

# write-ahead marker names; <name>.<step> files beside the step dirs
_WAL_OPEN = "COMMITTING"
_WAL_DONE = "COMMITTED"


def _env_flag(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "no")


def _tree_n_devices(tree: Any) -> Optional[int]:
    """Device count of the mesh a concrete pytree lives on (None when
    no leaf carries a sharding — e.g. an abstract template)."""
    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        devs = getattr(sharding, "device_set", None)
        if devs:
            return len(devs)
    return None


class CheckpointRestoreError(RuntimeError):
    """A collectively-agreed restore failure. The trainer's retry
    classifier treats this as RETRYABLE even though the underlying
    orbax/tensorstore cause is often a ValueError (which would
    otherwise fail fast as 'deterministic') — a fresh attempt re-reads
    storage and can succeed where a flake failed."""


class CheckpointCommitError(RuntimeError):
    """The background committer thread failed or did not drain in time
    (``CKPT_COMMIT_TIMEOUT_S``) — surfaced from ``wait()`` so the
    attempt fails loudly instead of exiting with a silently-lost
    checkpoint."""


class CheckpointManager:
    """Thin orbax wrapper carrying the reference's retention contract."""

    def __init__(self, directory: str, *, max_to_keep: int = 1,
                 score_attribute: str = "loss", score_mode: str = "min",
                 save_interval_steps: int = 1, async_save: bool = True,
                 async_commit: Optional[bool] = None,
                 commit_timeout_s: Optional[float] = None,
                 storage_delay_s: Optional[float] = None,
                 peer: Any = None):
        if async_commit is None:
            async_commit = _env_flag("ASYNC_CKPT")
        self.async_commit = bool(async_commit)
        if commit_timeout_s is None:
            commit_timeout_s = float(
                os.environ.get("CKPT_COMMIT_TIMEOUT_S", "120"))
        self.commit_timeout_s = float(commit_timeout_s)
        if storage_delay_s is None:
            storage_delay_s = float(
                os.environ.get("CKPT_STORAGE_DELAY_S", "0"))
        # emulated storage latency per commit (the GCS round-trip the
        # chaos drill hides behind the committer thread; the sync
        # baseline arm eats it on the loop's wall-clock)
        self.storage_delay_s = max(float(storage_delay_s), 0.0)
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            best_fn=(lambda m: m[score_attribute]) if score_attribute else None,
            best_mode=score_mode,
            save_interval_steps=save_interval_steps,
            # the committer thread owns durability in async-commit mode:
            # its orbax save is synchronous so COMMITTED means durable
            enable_async_checkpointing=(async_save and
                                        not self.async_commit),
        )
        self._mgr = ocp.CheckpointManager(directory, options=self._options)
        self.directory = directory
        if peer is False:  # explicit opt-out beats the env knob
            peer = None
        elif peer is None and _env_flag("PEER_REPLICATION"):
            from gke_ray_train_tpu.ckpt.peer import PeerReplicator
            peer = PeerReplicator.from_env()
        self.peer = peer
        # (saved_n_devices, restored_n_devices) of the last restore
        # that crossed topologies — the elastic-resume witness the
        # trainer/tests read; None = same-topology (or unknown) restore
        self.last_restore_resharded: Optional[Tuple[int, int]] = None
        # "peer" | "storage" | None — which path served the last
        # restore_if_available (the loop books peer_restore_s vs
        # restore_s off this)
        self.last_restore_source: Optional[str] = None
        # {"step","bytes","from_slice"} of the last peer-served restore
        self.last_peer_restore: Optional[dict] = None
        # orbax-async saves whose COMMITTED marker is still pending
        # (flushed lazily once wait_until_finished proves durability)
        self._pending_marks: set = set()
        # async-commit machinery (committer thread started lazily)
        self._commit_lock = threading.Condition()
        self._commit_queue: list = []
        self._committing_now: Optional[int] = None
        self._abort_step: Optional[int] = None
        self._commit_error: Optional[BaseException] = None
        self._committer: Optional[threading.Thread] = None
        self._stop = False
        self.commits_done = 0
        self.last_torn_step: Optional[int] = None
        # steps already snapshot by THIS manager: the async path must
        # dedupe itself (the sync path gets this from orbax should_save
        # — e.g. the end-of-epoch save re-offering the cadence save's
        # step would otherwise enqueue a second commit that dies on
        # StepAlreadyExists)
        self._snapshotted: set = set()

    # ------------------------------------------------------------------
    # write-ahead markers

    def _is_host0(self) -> bool:
        try:
            return jax.process_index() == 0
        except Exception:  # noqa: BLE001 - backend-free callers
            return True

    def _marker_path(self, kind: str, step: int) -> str:
        return os.path.join(str(self.directory), f"{kind}.{int(step)}")

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(str(self.directory), os.O_RDONLY)
        except OSError:  # pragma: no cover - directory raced away
            return
        try:
            os.fsync(dfd)
        except OSError:  # pragma: no cover - fs without dir fsync
            pass
        finally:
            os.close(dfd)

    def _write_marker(self, kind: str, step: int) -> None:
        """COMMITTING/COMMITTED marker, fsync'd (file then directory) so
        the ordering the recovery rule relies on survives a crash."""
        if not self._is_host0():
            return
        os.makedirs(str(self.directory), exist_ok=True)
        fd = os.open(self._marker_path(kind, step),
                     os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            os.write(fd, f"{kind} step={int(step)}\n".encode())
            os.fsync(fd)
        finally:
            os.close(fd)
        self._fsync_dir()

    def _remove_marker(self, kind: str, step: int) -> None:
        if not self._is_host0():
            return
        try:
            os.remove(self._marker_path(kind, step))
        except FileNotFoundError:
            pass
        except OSError as e:  # pragma: no cover - removal best-effort
            logger.debug("could not remove %s.%d marker: %s", kind, step, e)

    def _mark_committed(self, step: int) -> None:
        self._write_marker(_WAL_DONE, step)
        self._remove_marker(_WAL_OPEN, step)

    def _flush_marks(self) -> None:
        """Promote the write-ahead markers of orbax-async saves that are
        now durable. Lazy on purpose: called where durability is about
        to be asserted anyway (next save / wait / latest_step / restore),
        so the loop's save window never eats a wait_until_finished."""
        if not self._pending_marks:
            return
        self._mgr.wait_until_finished()
        for step in sorted(self._pending_marks):
            self._mark_committed(step)
        self._pending_marks.clear()

    def _step_eligible(self, step: int) -> bool:
        """The recovery rule: a step is offered iff it is NOT in the
        mid-commit state. COMMITTED wins; a bare COMMITTING marker means
        the writer died between the write-ahead record and the durable
        one; no markers at all (pre-protocol checkpoints) stay trusted."""
        step = int(step)
        if step in self._pending_marks:
            return True
        if os.path.exists(self._marker_path(_WAL_DONE, step)):
            return True
        return not os.path.exists(self._marker_path(_WAL_OPEN, step))

    def _purge_uncommitted(self) -> None:
        """Async-commit recovery sweep: every COMMITTING-without-
        COMMITTED step on disk 'never existed' — quarantine it (or just
        drop the orphan marker when the death landed before any step
        data) so the restore walk only ever sees committed steps."""
        pattern = os.path.join(str(self.directory), _WAL_OPEN + ".*")
        for path in sorted(glob.glob(pattern)):
            try:
                step = int(os.path.basename(path).split(".", 1)[1])
            except (IndexError, ValueError):
                continue
            if step in self._pending_marks:
                continue
            if os.path.exists(self._marker_path(_WAL_DONE, step)):
                # death landed between COMMITTED and the marker cleanup:
                # the step is durable, only the stale twin lingers
                self._remove_marker(_WAL_OPEN, step)
                continue
            logger.warning(
                "checkpoint step %d has a write-ahead marker but no "
                "commit record — the previous attempt died mid-commit; "
                "treating the step as never saved and falling back to "
                "the last committed one", step)
            if os.path.exists(os.path.join(str(self.directory),
                                           str(step))):
                self._quarantine(step)
            else:
                self._remove_marker(_WAL_OPEN, step)

    # ------------------------------------------------------------------
    # topology note

    def _write_topology_note(self, step: int, n: Optional[int]) -> None:
        if n is None:
            return
        if not self._is_host0():
            return
        try:
            with open(os.path.join(str(self.directory),
                                   TOPOLOGY_NOTE), "w") as f:
                json.dump({"step": int(step), "n_devices": int(n)}, f)
        except OSError as e:  # pragma: no cover - note is best-effort
            logger.debug("could not write topology note: %s", e)

    def _note_topology(self, step: int, state: Any) -> None:
        """Record the saving mesh's device count beside the checkpoints
        (best-effort, host 0) so a later restore can SAY it resharded —
        the save-time topology is not recoverable from orbax metadata."""
        self._write_topology_note(step, _tree_n_devices(state))

    def saved_topology(self) -> Optional[dict]:
        try:
            with open(os.path.join(str(self.directory),
                                   TOPOLOGY_NOTE)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # ------------------------------------------------------------------
    # peer replication

    def _replicate(self, step: int, host_state: Any) -> None:
        t0 = time.perf_counter()
        try:
            meta = self.peer.replicate(str(self.directory), int(step),
                                       host_state)
        except Exception as e:  # noqa: BLE001 - replication best-effort
            logger.warning("peer replication of step %d failed "
                           "(%s: %s); storage path unaffected",
                           step, type(e).__name__, e)
            return
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        obs_runtime.emit("peer_replicate", step=int(step),
                         bytes=int(meta.get("bytes", 0)),
                         to_slice=int(meta.get("to_slice", 0)),
                         replicate_s=time.perf_counter() - t0)

    # ------------------------------------------------------------------
    # save

    def save(self, step: int, state: Any, metrics: Optional[dict] = None,
             force: bool = False) -> bool:
        metrics = {k: float(v) for k, v in (metrics or {}).items()}
        if self.async_commit:
            return self._save_async(step, state, metrics, force)
        self._flush_marks()
        self._write_marker(_WAL_OPEN, step)
        try:
            saved = self._mgr.save(step, args=ocp.args.StandardSave(state),
                                   metrics=metrics, force=force)
        except BaseException:
            self._remove_marker(_WAL_OPEN, step)
            raise
        if saved:
            if self.storage_delay_s:
                # the emulated storage round-trip: the sync path blocks
                # the loop on it, which is exactly what the goodput
                # drill's baseline arm measures
                time.sleep(self.storage_delay_s)
            self._pending_marks.add(int(step))
            if self.peer is not None:
                self._replicate(step, jax.device_get(state))
            self._note_topology(step, state)
            logger.info("checkpoint saved at step %d (metrics=%s)",
                        step, metrics)
        else:
            self._remove_marker(_WAL_OPEN, step)
        return saved

    def _save_async(self, step: int, state: Any, metrics: dict,
                    force: bool) -> bool:
        """The caller-facing half of an async-commit save: ONE
        device→host snapshot, replicate to the peer slice, enqueue for
        the committer — the loop blocks only for the snapshot."""
        if self._stop:
            # a torn manager (kill_during_commit) is 'dead': the real
            # process would never reach another save
            return False
        if self._commit_error is not None:
            self.wait()  # re-raise the committer's failure loudly
        if int(step) in self._snapshotted:
            # already snapshot (queued, in-flight or committed): the
            # durability the caller wants is one wait() away
            return False
        if not force and not self._mgr.should_save(step):
            return False
        host_state = jax.device_get(state)
        n_devices = _tree_n_devices(state)
        if self.peer is not None:
            self._replicate(step, host_state)
        with self._commit_lock:
            self._ensure_committer()
            self._commit_queue.append(
                (int(step), host_state, metrics, n_devices))
            self._snapshotted.add(int(step))
            self._commit_lock.notify_all()
        logger.info("checkpoint snapshot taken at step %d "
                    "(commit queued; metrics=%s)", step, metrics)
        return True

    def _ensure_committer(self) -> None:
        if self._committer is None or not self._committer.is_alive():
            self._committer = threading.Thread(
                target=self._commit_loop, name="ckpt-committer",
                daemon=True)
            self._committer.start()

    def _commit_loop(self) -> None:
        while True:
            with self._commit_lock:
                while not self._commit_queue and not self._stop:
                    self._commit_lock.wait()
                if not self._commit_queue and self._stop:
                    return
                step, host_state, metrics, n_devices = \
                    self._commit_queue.pop(0)
                self._committing_now = step
            try:
                self._commit_one(step, host_state, metrics, n_devices)
            except BaseException as e:  # noqa: BLE001 - surfaced in wait()
                logger.exception("background commit of step %d failed",
                                 step)
                with self._commit_lock:
                    if self._commit_error is None:
                        self._commit_error = e
                    self._committing_now = None
                    self._commit_lock.notify_all()
                continue
            with self._commit_lock:
                self._committing_now = None
                self.commits_done += 1
                self._commit_lock.notify_all()

    def _commit_one(self, step: int, host_state: Any, metrics: dict,
                    n_devices: Optional[int]) -> None:
        """One write-ahead commit: COMMITTING → serialize → COMMITTED.
        A death anywhere inside leaves the COMMITTING signature and the
        step is recovered as never-saved."""
        t0 = time.perf_counter()
        self._write_marker(_WAL_OPEN, step)
        if self.storage_delay_s:
            time.sleep(self.storage_delay_s)
        with self._commit_lock:
            aborted = self._abort_step == step
        if not aborted:
            # force=True: the should_save/retention gate already ran on
            # the caller thread at snapshot time
            self._mgr.save(step, args=ocp.args.StandardSave(host_state),
                           metrics=metrics, force=True)
            self._mgr.wait_until_finished()
            with self._commit_lock:
                aborted = self._abort_step == step
        if aborted:
            # drill cooperation (tear_mid_commit): emulate the SIGKILL
            # landing before COMMITTED — the marker pair stays torn
            logger.warning("commit of step %d torn mid-flight "
                           "(kill_during_commit drill)", step)
            self._emit_commit_event(step, time.perf_counter() - t0,
                                    "torn")
            return
        self._mark_committed(step)
        self._write_topology_note(step, n_devices)
        self._emit_commit_event(step, time.perf_counter() - t0, "ok")
        logger.info("checkpoint committed at step %d (metrics=%s)",
                    step, metrics)

    @staticmethod
    def _emit_commit_event(step: int, commit_s: float,
                           status: str) -> None:
        try:
            from gke_ray_train_tpu.obs import runtime as obs_runtime
            obs_runtime.emit("ckpt_commit", step=int(step),
                             commit_s=float(commit_s), status=status)
        except Exception:  # noqa: BLE001 - telemetry never kills commits
            logger.debug("ckpt_commit event emission failed",
                         exc_info=True)

    def tear_mid_commit(self) -> int:
        """Drill hook for ``kill_during_commit``: freeze the in-flight
        commit in its mid-commit state (COMMITTING on disk, no
        COMMITTED), purge anything else queued — exactly the on-disk +
        in-memory state a SIGKILL during the commit leaves behind —
        and report the torn step. The manager is 'dead' afterwards
        (further saves no-op), like the process it stands in for."""
        if not self.async_commit:
            raise RuntimeError(
                "tear_mid_commit requires an async-commit manager "
                "(ASYNC_CKPT=1); the sync save path has no commit "
                "window to kill inside")
        with self._commit_lock:
            if self._commit_queue:
                step = int(self._commit_queue[-1][0])
                self._commit_queue.clear()
                self._stop = True
                self._commit_lock.notify_all()
                in_flight = False
            elif self._committing_now is not None:
                step = int(self._committing_now)
                self._abort_step = step
                deadline = time.monotonic() + self.commit_timeout_s
                while self._committing_now is not None:
                    if time.monotonic() > deadline:  # pragma: no cover
                        raise CheckpointCommitError(
                            f"committer did not tear step {step} within "
                            f"{self.commit_timeout_s}s")
                    self._commit_lock.wait(timeout=0.05)
                self._stop = True
                self._commit_lock.notify_all()
                in_flight = True
            else:
                raise RuntimeError(
                    "kill_during_commit fired with no in-flight commit; "
                    "schedule it on a step the checkpoint cadence "
                    "actually saves")
        if not in_flight:
            # the kill landed between the write-ahead record and the
            # serialize: marker + a torn partial step directory
            self._write_marker(_WAL_OPEN, step)
            d = os.path.join(str(self.directory), str(step))
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "_PARTIAL"), "wb") as f:
                f.write(b"\x00" * 64)
        self.last_torn_step = step
        logger.warning("checkpoint commit of step %d torn by "
                       "kill_during_commit drill", step)
        return step

    # ------------------------------------------------------------------
    # queries

    def latest_step(self) -> Optional[int]:
        self._flush_marks()
        steps = [int(s) for s in self._mgr.all_steps()
                 if self._step_eligible(int(s))]
        return max(steps) if steps else None

    def best_step(self) -> Optional[int]:
        return self._mgr.best_step()

    # ------------------------------------------------------------------
    # restore

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the shardings/dtypes of ``state_like`` (an abstract
        or concrete pytree — shardings are honored, so a checkpoint saved
        on one mesh restores resharded onto another)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(abstract))

    def restore_resharded(self, state_like: Any, mesh, spec_tree: Any,
                          step: Optional[int] = None) -> Any:
        """Cross-topology restore: re-derive shardings from the LOGICAL
        PartitionSpec tree on a NEW mesh instead of reusing the saved
        layout — the reshard-on-restore path of elastic resume (ROADMAP
        #1: save on a 16-chip mesh, restore on 8). ``spec_tree`` must be
        structurally isomorphic to ``state_like`` (params:
        ``models.transformer.param_specs``; optimizer state:
        ``train.step.opt_state_specs``; an ExecutionPlan supplies the
        batch/mesh side). ``analysis plancheck`` (PLAN003) statically
        proves every (save, restore) topology pair this path will be
        asked to handle is well-formed — same logical shapes, valid
        shardings on the restore mesh."""
        from jax.sharding import NamedSharding

        abstract = jax.tree.map(
            lambda leaf, spec: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype,
                sharding=NamedSharding(mesh, spec)),
            jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like),
            spec_tree)
        return self.restore(abstract, step=step)

    def restore_raw(self, step: Optional[int] = None) -> Any:
        """Topology-free restore: structure/shapes come from checkpoint
        metadata, everything lands on this host's first device — the
        offline-converter path, where the save-time mesh (TPU pod) does
        not exist on the converting machine."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        meta = self._mgr.item_metadata(step)
        sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=sh),
            meta, is_leaf=lambda x: hasattr(x, "shape"))
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def item_metadata(self, step: Optional[int] = None) -> Any:
        """Shape/dtype metadata tree of a saved checkpoint (no data
        read) — drives the converter's leaf-by-leaf walk.

        Read via a standalone PyTreeCheckpointer on the step's item dir:
        the manager's own ``item_metadata`` returns an EMPTY tree in any
        process that has not yet registered a 'default' handler (i.e.
        every fresh converter process) and only warns about it."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        ckptr = ocp.PyTreeCheckpointer()
        meta = ckptr.metadata(
            os.path.join(str(self.directory), str(step), "default"))
        # orbax >= 0.6 wraps the tree (CheckpointMetadata.item_metadata
        # .tree); older releases hand the metadata tree back directly
        if hasattr(meta, "item_metadata"):
            return meta.item_metadata.tree
        return meta

    def restore_partial(self, abstract: Any,
                        step: Optional[int] = None) -> Any:
        """Restore only a subset of the saved tree — the offline
        converter reads one leaf at a time this way, so a 70B conversion
        needs O(one leaf) RAM instead of the whole tree (VERDICT r3 weak
        #4b).

        On orbax with ``PLACEHOLDER`` support, ``abstract`` is the full
        structure with every unwanted leaf placeholder'd; on older
        releases it is the partial subtree and ``transforms={}`` tells
        the handler to drop checkpoint entries not present in it."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        if hasattr(ocp, "PLACEHOLDER"):
            args = ocp.args.PyTreeRestore(item=abstract)
        else:
            args = ocp.args.PyTreeRestore(
                item=abstract, transforms={},
                restore_args=ocp.checkpoint_utils.construct_restore_args(
                    abstract))
        return self._mgr.restore(step, args=args)

    @staticmethod
    def _any_host_failed(local_failed: bool) -> bool:
        """Collective agreement on a restore outcome: every host enters,
        every host leaves with the same verdict — the prerequisite for
        a fallback/quarantine that cannot diverge the slice."""
        if jax.process_count() <= 1:
            return local_failed
        from jax.experimental import multihost_utils
        import numpy as np
        flags = multihost_utils.process_allgather(
            np.asarray(1 if local_failed else 0, np.int32))
        return bool(np.max(flags))

    def _quarantine(self, step: int) -> str:
        """Move an unrestorable step directory aside (``<step>.corrupt``)
        so it never shadows a good checkpoint again, drop its write-ahead
        markers (the marker state must always describe the CURRENT save
        of a step — a later re-save of the same step writes fresh ones),
        and refresh the manager's step cache. All hosts enter (the
        verdict was collective); host 0 renames, everyone syncs before
        reloading."""
        import shutil

        src = os.path.join(str(self.directory), str(step))
        dst = src + ".corrupt"
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f"{src}.corrupt{n}"
        multi = jax.process_count() > 1
        if not multi or jax.process_index() == 0:
            if os.path.isdir(src):
                shutil.move(src, dst)
        self._remove_marker(_WAL_OPEN, step)
        self._remove_marker(_WAL_DONE, step)
        self._pending_marks.discard(int(step))
        if multi:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"ckpt_quarantine_{step}")
        if hasattr(self._mgr, "reload"):
            self._mgr.reload()
        else:  # pragma: no cover - pre-reload orbax
            self._mgr = ocp.CheckpointManager(self.directory,
                                              options=self._options)
        return dst

    def restore_if_available(self, state_like: Any):
        """(state, resumed_step) — the resume-on-retry behavior the
        reference lacks. Returns (state_like, None) on a fresh start.

        Recovery order: (1) the write-ahead sweep — in async-commit mode
        every mid-commit step (COMMITTING without COMMITTED) 'never
        existed' and is quarantined WITHOUT a restore attempt; (2) the
        peer slice's hot state, when a replicator is bound and its step
        is at least as new as the latest committed one — no storage
        read at all; (3) the verify-by-restoring walk below.

        Integrity fallback: the latest step is VERIFIED by restoring it;
        when that fails (an interrupted async save left a committed but
        torn tail — without this, every subsequent resume crashes on the
        same bad step) the newest earlier restorable step is used and
        each newer unrestorable step is quarantined as ``<step>.corrupt``.
        When EVERY step fails the first error re-raises and nothing is
        quarantined: that signature is a template/layout mismatch (the
        caller's pytree, not the data, is wrong — see the ckpt_view
        fallback in train/loop.py), and quarantining healthy checkpoints
        on a caller bug would destroy the run's only resume points.

        Each step gets one bounded retry before being declared
        unrestorable — a transient storage flake must not cost the
        newest checkpoint. On multi-host runs every verdict is
        COLLECTIVE (``_any_host_failed``): a step counts as failed when
        ANY host failed it, all hosts retry/fall back/quarantine in
        lockstep, and a host whose local restore succeeded discards the
        result rather than diverge — per-host divergence here would
        wedge the slice in its next collective."""
        self.last_restore_source = None
        self.last_peer_restore = None
        self._flush_marks()
        if self.async_commit:
            self._purge_uncommitted()
        steps = sorted(int(s) for s in self._mgr.all_steps())
        steps.reverse()
        # sync mode: marker-suspect steps (lazy flush lost with the
        # process) are still verified below — promoted on success
        suspects = {s for s in steps if not self._step_eligible(s)}
        if self.peer is not None:
            latest_ok = max((s for s in steps if s not in suspects),
                            default=None)
            peer_step = self.peer.peek(str(self.directory))
            if peer_step is not None and (latest_ok is None or
                                          int(peer_step) >= latest_ok):
                try:
                    out, meta = self.peer.restore(str(self.directory),
                                                  state_like)
                except Exception as e:  # noqa: BLE001 - fall to storage
                    logger.warning(
                        "peer hot-state restore failed (%s: %s); "
                        "falling back to storage",
                        type(e).__name__, e)
                else:
                    self.last_restore_resharded = None
                    self.last_restore_source = "peer"
                    self.last_peer_restore = dict(meta)
                    logger.info(
                        "resuming from PEER slice %s hot state at step "
                        "%d (no storage read)",
                        meta.get("from_slice"), int(peer_step))
                    return out, int(peer_step)
        if not steps:
            return state_like, None
        first_err: Optional[Exception] = None
        failed: list = []
        for step in steps:
            out = err = None
            restored = False
            t_restore0 = time.perf_counter()
            for restore_try in range(2):
                try:
                    out = self.restore(state_like, step)
                except Exception as e:  # noqa: BLE001 - classified below
                    err = e
                if not self._any_host_failed(err is not None):
                    restored = True
                    break
                if err is None:
                    # this host restored fine but another did not — the
                    # verdict is collective, so align with the failure
                    err = CheckpointRestoreError(
                        f"step {step} failed to restore on another host")
                out = None
                if restore_try == 0:
                    logger.warning(
                        "restore of step %d failed (%s: %s); retrying "
                        "once before treating it as corrupt", step,
                        type(err).__name__, err)
                    err = None
            if not restored:
                first_err = first_err if first_err is not None else err
                failed.append((step, err))
                continue
            for bad, bad_err in failed:
                logger.warning(
                    "checkpoint step %d is unrestorable (%s: %s); "
                    "quarantining it and resuming from step %d",
                    bad, type(bad_err).__name__, bad_err, step)
                self._quarantine(bad)
            if step in suspects:
                # the save was durable after all — only the marker
                # flush died with the process; heal the record
                self._mark_committed(step)
            # elastic-resume witness: a restore onto a different device
            # count than the save is a reshard (shardings re-derived
            # from the template) — say so, and leave the evidence for
            # the trainer's attempt log
            self.last_restore_resharded = None
            self.last_restore_source = "storage"
            note = self.saved_topology()
            cur_n = _tree_n_devices(state_like)
            if note and cur_n and int(note.get("n_devices", 0)) and \
                    int(note["n_devices"]) != cur_n:
                self.last_restore_resharded = (int(note["n_devices"]),
                                               cur_n)
                logger.warning(
                    "elastic resume: checkpoint step %d was saved on %d "
                    "devices; restored RESHARDED onto %d (shardings "
                    "re-derived from the restore template)",
                    step, int(note["n_devices"]), cur_n)
                # obs: the resharded restore IS the reshard witness —
                # one event per actual mesh transition (8->4 AND 4->8
                # in the elastic drill), rendered on the attempt
                # timeline by `obs report` (no-op when obs is off)
                from gke_ray_train_tpu.obs import runtime as obs_runtime
                obs_runtime.emit("reshard", step=step,
                                 from_devices=int(note["n_devices"]),
                                 to_devices=cur_n)
                # the restore-level half of the reshard span twin pair
                # (rayint/elastic.py spans the plan re-formation): how
                # long the RESHARDED restore itself took
                obs_runtime.span_add(
                    "reshard", time.perf_counter() - t_restore0,
                    step=step, from_devices=int(note["n_devices"]),
                    to_devices=cur_n, where="restore")
            logger.info("resuming from checkpoint step %d in %s", step,
                        self.directory)
            return out, step
        raise first_err

    # ------------------------------------------------------------------
    # lifecycle

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every save is durable AND committed (call before
        process exit). In async-commit mode this drains the committer
        queue — bounded by ``CKPT_COMMIT_TIMEOUT_S`` — and re-raises any
        background commit failure so it cannot be silently lost."""
        if self.async_commit:
            budget = self.commit_timeout_s if timeout is None \
                else float(timeout)
            deadline = time.monotonic() + budget
            with self._commit_lock:
                while (self._commit_queue
                       or self._committing_now is not None):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise CheckpointCommitError(
                            f"checkpoint commit queue did not drain "
                            f"within {budget}s "
                            f"(CKPT_COMMIT_TIMEOUT_S)")
                    self._commit_lock.wait(timeout=min(remaining, 0.1))
                if self._commit_error is not None:
                    err = self._commit_error
                    self._commit_error = None
                    raise CheckpointCommitError(
                        "background checkpoint commit failed") from err
        self._mgr.wait_until_finished()
        self._flush_marks()

    def close(self) -> None:
        if self._committer is not None and self._committer.is_alive():
            with self._commit_lock:
                self._stop = True
                self._commit_lock.notify_all()
            self._committer.join(timeout=self.commit_timeout_s)
        try:
            self._flush_marks()
        except Exception:  # noqa: BLE001 - close is best-effort
            logger.debug("marker flush on close failed", exc_info=True)
        self._mgr.close()
