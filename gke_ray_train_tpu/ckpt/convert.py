"""Offline orbax → HF safetensors converter (VERDICT r1 missing #5).

Multi-host runs export the merged model as an orbax tree (collective
save — rank-0 ``save_pretrained`` stops being valid once params are
sharded, SURVEY.md §5.4) plus a ``model_config.json`` sidecar. This tool
completes the path the reference guarantees with ``save_pretrained``
(/root/reference/ray-jobs/fine_tune_llama_ray.py:354-355): run it
anywhere with filesystem access to produce the HF-layout artifact.

Usage:
    python -m gke_ray_train_tpu.ckpt.convert <orbax_dir> <out_dir> \
        [--step N] [--dtype bfloat16] [--model-config path.json]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

logger = logging.getLogger(__name__)

SIDECAR = "model_config.json"


def write_sidecar(cfg, orbax_dir: str) -> str:
    """Write the ModelConfig sidecar the converter needs (called by the
    multi-host export path, host 0)."""
    os.makedirs(orbax_dir, exist_ok=True)
    path = os.path.join(orbax_dir, SIDECAR)
    with open(path, "w") as f:
        json.dump(cfg.to_dict(), f, indent=2)
    return path


def convert(orbax_dir: str, out_dir: str, *, step: int = None,
            dtype: str = "bfloat16", model_config: str = None) -> str:
    """Restore the orbax params tree and export HF safetensors; returns
    ``out_dir``."""
    from gke_ray_train_tpu.ckpt.hf_io import save_hf_checkpoint
    from gke_ray_train_tpu.ckpt.manager import CheckpointManager
    from gke_ray_train_tpu.models.config import ModelConfig

    cfg_path = model_config or os.path.join(orbax_dir, SIDECAR)
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f"no {SIDECAR} beside {orbax_dir} and no --model-config "
            "given; the export step writes this sidecar — for older "
            "checkpoints, craft one from ModelConfig.to_dict()")
    with open(cfg_path) as f:
        cfg = ModelConfig.from_dict(json.load(f))

    mgr = CheckpointManager(orbax_dir, score_attribute=None,
                            async_save=False)
    params = mgr.restore_raw(step)
    mgr.close()
    save_hf_checkpoint(params, cfg, out_dir, dtype=dtype)
    logger.info("converted %s (step %s) -> %s", orbax_dir,
                step if step is not None else "latest", out_dir)
    return out_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("orbax_dir")
    p.add_argument("out_dir")
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--model-config", default=None)
    a = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    # offline tool: run on host CPU regardless of what accelerator
    # plugin is attached (must precede any backend init)
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:  # backend already initialized by the embedder
        pass
    convert(a.orbax_dir, a.out_dir, step=a.step, dtype=a.dtype,
            model_config=a.model_config)
    return 0


if __name__ == "__main__":
    sys.exit(main())
