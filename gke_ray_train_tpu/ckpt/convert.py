"""Offline orbax → HF safetensors converter (VERDICT r1 missing #5).

Multi-host runs export the merged model as an orbax tree (collective
save — rank-0 ``save_pretrained`` stops being valid once params are
sharded, SURVEY.md §5.4) plus a ``model_config.json`` sidecar. This tool
completes the path the reference guarantees with ``save_pretrained``
(/root/reference/ray-jobs/fine_tune_llama_ray.py:354-355): run it
anywhere with filesystem access to produce the HF-layout artifact.

Usage:
    python -m gke_ray_train_tpu.ckpt.convert <orbax_dir> <out_dir> \
        [--step N] [--dtype bfloat16] [--model-config path.json]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

logger = logging.getLogger(__name__)

SIDECAR = "model_config.json"


def write_sidecar(cfg, orbax_dir: str) -> str:
    """Write the ModelConfig sidecar the converter needs (called by the
    multi-host export path, host 0)."""
    os.makedirs(orbax_dir, exist_ok=True)
    path = os.path.join(orbax_dir, SIDECAR)
    with open(path, "w") as f:
        json.dump(cfg.to_dict(), f, indent=2)
    return path


def unstack_for_export(params):
    """[R, ...] block leaves → lists of per-layer arrays (device slices,
    shardings preserved). The multi-host export saves THIS layout so the
    offline converter can partial-restore one layer at a time — a 70B
    conversion then needs O(one layer) RAM instead of O(37 GB per
    stacked leaf) (VERDICT r3 weak #4b)."""
    out = dict(params)
    out["blocks"] = [
        {k: [v[r] for r in range(v.shape[0])] for k, v in blk.items()}
        for blk in params["blocks"]]
    return out


def _path_parts(path):
    return [p.key if hasattr(p, "key") else p.idx for p in path]


def convert(orbax_dir: str, out_dir: str, *, step: int = None,
            dtype: str = "bfloat16", model_config: str = None,
            max_shard_bytes: int = 4 << 30) -> str:
    """Stream the orbax params tree into HF safetensors shards.

    Leaf-by-leaf: each leaf is partial-restored alone (every other leaf
    PLACEHOLDER'd), renamed to its HF tensor name(s), appended to the
    sharded writer, and freed — peak RAM is O(one leaf). New exports
    store per-layer leaves (``unstack_for_export``) so one leaf is one
    layer; legacy stacked checkpoints still convert, at O(one stacked
    leaf) peak. Returns ``out_dir``."""
    import jax
    import numpy as np

    from gke_ray_train_tpu.ckpt.hf_io import (
        ShardedSafetensorsWriter, _EXPERT_KEYS, _hf_expert_names,
        _hf_layer_names, _maybe_t, hf_dtype_np, write_hf_config)
    from gke_ray_train_tpu.ckpt.manager import CheckpointManager
    from gke_ray_train_tpu.models.config import ModelConfig

    cfg_path = model_config or os.path.join(orbax_dir, SIDECAR)
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f"no {SIDECAR} beside {orbax_dir} and no --model-config "
            "given; the export step writes this sidecar — for older "
            "checkpoints, craft one from ModelConfig.to_dict()")
    with open(cfg_path) as f:
        cfg = ModelConfig.from_dict(json.load(f))
    P_ = len(cfg.block_pattern)

    mgr = CheckpointManager(orbax_dir, score_attribute=None,
                            async_save=False)
    if step is None:
        step = mgr.latest_step()
    meta = mgr.item_metadata(step)
    is_leaf = (lambda x: hasattr(x, "shape"))
    leaves, treedef = jax.tree_util.tree_flatten_with_path(
        meta, is_leaf=is_leaf)
    sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])

    def restore_leaf(i):
        import orbax.checkpoint as ocp
        sds = jax.ShapeDtypeStruct(leaves[i][1].shape,
                                   leaves[i][1].dtype, sharding=sh)
        if hasattr(ocp, "PLACEHOLDER"):
            flat = [ocp.PLACEHOLDER] * len(leaves)
            flat[i] = sds
            out = mgr.restore_partial(
                jax.tree_util.tree_unflatten(treedef, flat), step)
        else:  # pre-PLACEHOLDER orbax: partial item tree + transforms={}
            sub = sds
            for part in reversed(_path_parts(leaves[i][0])):
                sub = {part: sub}
            out = mgr.restore_partial(sub, step)
        (leaf,) = [x for x in jax.tree.leaves(out) if x is not ...]
        return np.asarray(jax.device_get(leaf))

    w = ShardedSafetensorsWriter(out_dir, max_shard_bytes=max_shard_bytes)
    try:
        for i, (path, m) in enumerate(leaves):
            parts = _path_parts(path)
            arr = restore_leaf(i)
            if parts[0] == "embed":
                w.add("model.embed_tokens.weight", hf_dtype_np(arr, dtype))
            elif parts[0] == "final_norm":
                w.add("model.norm.weight", hf_dtype_np(arr, dtype))
            elif parts[0] == "lm_head":
                w.add("lm_head.weight", hf_dtype_np(arr.T, dtype))
            elif parts[0] == "blocks":
                p, key = parts[1], parts[2]
                moe_bank = cfg.n_experts > 0 and key in _EXPERT_KEYS

                def emit(layer, a):
                    if moe_bank:  # a: [E, d_in, d_out] → per-expert names
                        for e in range(cfg.n_experts):
                            w.add(_hf_expert_names(layer, e)[key],
                                  hf_dtype_np(_maybe_t(a[e], key), dtype))
                    else:
                        w.add(_hf_layer_names(cfg, layer)[key],
                              hf_dtype_np(_maybe_t(a, key), dtype))

                if len(parts) == 4:   # per-layer export layout
                    emit(parts[3] * P_ + p, arr)
                else:                 # legacy stacked [R, ...] leaf
                    for r in range(arr.shape[0]):
                        emit(r * P_ + p, arr[r])
            else:
                raise ValueError(
                    f"unexpected leaf path {parts} in {orbax_dir}")
            del arr
    except BaseException:
        # a mid-stream death (OOM, disk full) must not leave tens of GB
        # of model-tmp-* shards for the retry to trip over
        w.abort()
        raise
    finally:
        mgr.close()
    w.finish()
    write_hf_config(cfg, out_dir, dtype)
    # carry the tokenizer through: the export step saves it under
    # <orbax_dir>/tokenizer so the converted dir is a self-contained
    # artifact (reference ships the tokenizer with every model dir,
    # fine_tune_llama_ray.py:355,374)
    tok_dir = os.path.join(orbax_dir, "tokenizer")
    if os.path.isdir(tok_dir):
        import shutil
        shutil.copytree(tok_dir, out_dir, dirs_exist_ok=True)
    logger.info("converted %s (step %s) -> %s", orbax_dir, step, out_dir)
    return out_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("orbax_dir")
    p.add_argument("out_dir")
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--model-config", default=None)
    a = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    # offline tool: run on host CPU regardless of what accelerator
    # plugin is attached (must precede any backend init)
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:  # backend already initialized by the embedder
        pass
    convert(a.orbax_dir, a.out_dir, step=a.step, dtype=a.dtype,
            model_config=a.model_config)
    return 0


if __name__ == "__main__":
    sys.exit(main())
