"""HF-format weight interop (safetensors, torch-free).

Replaces ``AutoModelForCausalLM.from_pretrained``
(ray-jobs/fine_tune_llama_ray.py:240) for loading pretrained Llama /
Mistral / Gemma-2 weights into the sharded pytree, and ``save_pretrained``
(:354-355, :373-374) for exporting — final artifacts stay in HF
safetensors layout for ecosystem parity (SURVEY.md §5.4).

Implementation notes:
- ``safetensors.safe_open`` streams one tensor at a time (never the whole
  model) and each tensor is ``device_put`` straight into its target
  sharding — hosts keep at most one full tensor in RAM (SURVEY.md §7
  "hard parts" #1).
- torch Linear stores W as [out, in]; our layout is [in, out] → transpose
  on both directions. Embeddings and norm scales copy as-is. HF Gemma-2
  RMSNorm uses the same (1 + w) convention as ``norm_scale_plus_one``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.transformer import (
    Params, init_params, param_specs)
from gke_ray_train_tpu.parallel.sharding import tree_shardings


def _hf_layer_names(cfg: ModelConfig, i: int) -> Dict[str, str]:
    """our-key → HF tensor name for decoder layer i."""
    base = f"model.layers.{i}"
    names = {
        "wq": f"{base}.self_attn.q_proj.weight",
        "wk": f"{base}.self_attn.k_proj.weight",
        "wv": f"{base}.self_attn.v_proj.weight",
        "wo": f"{base}.self_attn.o_proj.weight",
        "w_gate": f"{base}.mlp.gate_proj.weight",
        "w_up": f"{base}.mlp.up_proj.weight",
        "w_down": f"{base}.mlp.down_proj.weight",
        "attn_norm": f"{base}.input_layernorm.weight",
    }
    if cfg.post_block_norm:  # Gemma-2 has four norms per block
        names["attn_post_norm"] = f"{base}.post_attention_layernorm.weight"
        names["mlp_norm"] = f"{base}.pre_feedforward_layernorm.weight"
        names["mlp_post_norm"] = f"{base}.post_feedforward_layernorm.weight"
    else:
        names["mlp_norm"] = f"{base}.post_attention_layernorm.weight"
    return names

_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


def _open_shards(model_dir: str):
    """Yield (name → (file, tensorname)) index over all safetensors shards."""
    from safetensors import safe_open

    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    files: Dict[str, str] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
        for tname, fname in weight_map.items():
            files[tname] = os.path.join(model_dir, fname)
    else:
        single = os.path.join(model_dir, "model.safetensors")
        if not os.path.exists(single):
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] in {model_dir}")
        with safe_open(single, framework="numpy") as f:
            for tname in f.keys():
                files[tname] = single
    return files


def load_hf_checkpoint(model_dir: str, cfg: ModelConfig, *,
                       mesh=None,
                       quantize: Optional[str] = None) -> Params:
    """Stream HF safetensors into the (optionally mesh-sharded) pytree.

    ``quantize``: "nf4" | "int8" — quantize the projection matrices
    *during* the stream (one layer-slice at a time, quantized result
    held in host RAM), so an 8B QLoRA base loads onto a single 16 GB
    chip without the full-precision tree ever existing on device. The
    equivalent of the reference loading with BitsAndBytesConfig
    (fine_tune_llama_ray.py:216-227,240). Norms/embed/lm_head stay full
    precision, like bnb.
    """
    from safetensors import safe_open

    specs = param_specs(cfg)
    shardings = (tree_shardings(mesh, specs) if mesh is not None else None)
    pdt = jnp.dtype(cfg.param_dtype)
    P_ = len(cfg.block_pattern)
    R = cfg.n_repeats
    handles: Dict[str, object] = {}

    def read(tname: str) -> np.ndarray:
        path = files[tname]
        if path not in handles:
            handles[path] = safe_open(path, framework="numpy")
        # bf16 tensors come back as ml_dtypes.bfloat16, which jnp converts
        return np.asarray(handles[path].get_tensor(tname))

    files = _open_shards(model_dir)

    def place(arr: np.ndarray, spec_path) -> jax.Array:
        arr = jnp.asarray(arr, pdt)
        if shardings is None:
            return arr
        return jax.device_put(arr, spec_path)

    def load_quantized(p: int, key: str):
        """Per-layer-slice quantize: device sees one [1, D, F] slice at
        a time; codes/scales accumulate in host RAM, then placed."""
        from gke_ray_train_tpu.ops.quant import (
            QTensor, quant_specs, quantize_tensor)
        codes_l, scales_l = [], []
        kind = group = None
        for r in range(R):
            w = _maybe_t(read(_hf_layer_names(cfg, r * P_ + p)[key]), key)
            qt = quantize_tensor(jnp.asarray(w, jnp.bfloat16)[None],
                                 quantize)
            kind, group = qt.kind, qt.group
            codes_l.append(np.asarray(jax.device_get(qt.codes)))
            scales_l.append(np.asarray(jax.device_get(qt.scales)))
            del qt
        host_qt = QTensor(np.concatenate(codes_l),
                          np.concatenate(scales_l), kind, group)
        if mesh is None:
            return QTensor(jnp.asarray(host_qt.codes),
                           jnp.asarray(host_qt.scales), kind, group)
        q_spec = quant_specs(specs["blocks"][p][key], host_qt, mesh)
        return jax.device_put(host_qt, tree_shardings(mesh, q_spec))

    # per-(pattern-position, key): gather the R per-layer tensors, stack
    from gke_ray_train_tpu.train.lora import ALL_TARGETS as _PROJ_KEYS
    blocks = []
    for p in range(P_):
        blk: Dict[str, jax.Array] = {}
        keys = _hf_layer_names(cfg, 0).keys()
        for key in keys:
            if quantize and key in _PROJ_KEYS:
                blk[key] = load_quantized(p, key)
                continue
            stacked = np.stack([
                _maybe_t(read(_hf_layer_names(cfg, r * P_ + p)[key]), key)
                for r in range(R)])
            tgt = shardings["blocks"][p][key] if shardings is not None else None
            blk[key] = place(stacked, tgt)
        blocks.append(blk)

    params: Params = {
        "embed": place(read("model.embed_tokens.weight"),
                       shardings["embed"] if shardings else None),
        "blocks": blocks,
        "final_norm": place(read("model.norm.weight"),
                            shardings["final_norm"] if shardings else None),
    }
    if not cfg.tie_embeddings:
        name = ("lm_head.weight" if "lm_head.weight" in files
                else "model.embed_tokens.weight")  # some exports tie anyway
        params["lm_head"] = place(read(name).T,
                                  shardings["lm_head"] if shardings else None)
    for h in handles.values():
        del h
    return params


def _maybe_t(arr: np.ndarray, key: str) -> np.ndarray:
    return arr.T if key in _TRANSPOSED else arr


def save_hf_checkpoint(params: Params, cfg: ModelConfig, out_dir: str,
                       *, dtype: str = "bfloat16") -> None:
    """Export the pytree to single-file HF safetensors + minimal
    config.json (save_pretrained parity)."""
    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    P_ = len(cfg.block_pattern)
    out_np: Dict[str, np.ndarray] = {}

    def to_np(x) -> np.ndarray:
        arr = np.asarray(jax.device_get(x))
        if dtype == "bfloat16":
            import ml_dtypes
            arr = arr.astype(ml_dtypes.bfloat16)
        else:
            arr = arr.astype(np.dtype(dtype))
        # astype(order='K') keeps F-order on transposed views and
        # safetensors serializes the raw buffer ignoring strides — force C
        return np.ascontiguousarray(arr)

    out_np["model.embed_tokens.weight"] = to_np(params["embed"])
    out_np["model.norm.weight"] = to_np(params["final_norm"])
    if not cfg.tie_embeddings:
        out_np["lm_head.weight"] = to_np(params["lm_head"].T)
    for p, blk in enumerate(params["blocks"]):
        for r in range(cfg.n_repeats):
            names = _hf_layer_names(cfg, r * P_ + p)
            for key, tname in names.items():
                arr = jax.device_get(blk[key][r])
                out_np[tname] = to_np(_maybe_t(np.asarray(arr), key))
    save_file(out_np, os.path.join(out_dir, "model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "architectures": ["GkeRayTrainTpuForCausalLM"],
            "model_family": cfg.name,
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "num_key_value_heads": cfg.n_kv_heads,
            "intermediate_size": cfg.d_ff,
            "head_dim": cfg.resolved_head_dim,
            "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.norm_eps,
            "tie_word_embeddings": cfg.tie_embeddings,
            "torch_dtype": dtype,
        }, f, indent=2)
