"""HF-format weight interop (safetensors, torch-free).

Replaces ``AutoModelForCausalLM.from_pretrained``
(ray-jobs/fine_tune_llama_ray.py:240) for loading pretrained Llama /
Mistral / Gemma-2 weights into the sharded pytree, and ``save_pretrained``
(:354-355, :373-374) for exporting — final artifacts stay in HF
safetensors layout for ecosystem parity (SURVEY.md §5.4).

Implementation notes:
- ``safetensors.safe_open`` streams one tensor at a time (never the whole
  model) and each tensor is ``device_put`` straight into its target
  sharding — hosts keep at most one full tensor in RAM (SURVEY.md §7
  "hard parts" #1).
- torch Linear stores W as [out, in]; our layout is [in, out] → transpose
  on both directions. Embeddings and norm scales copy as-is. HF Gemma-2
  RMSNorm uses the same (1 + w) convention as ``norm_scale_plus_one``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.transformer import (
    Params, init_params, param_specs)
from gke_ray_train_tpu.parallel.sharding import tree_shardings


def _hf_layer_names(cfg: ModelConfig, i: int) -> Dict[str, str]:
    """our-key → HF tensor name for decoder layer i (per-layer tensors;
    MoE expert banks are per-(layer, expert), see _hf_expert_names)."""
    base = f"model.layers.{i}"
    names = {
        "wq": f"{base}.self_attn.q_proj.weight",
        "wk": f"{base}.self_attn.k_proj.weight",
        "wv": f"{base}.self_attn.v_proj.weight",
        "wo": f"{base}.self_attn.o_proj.weight",
        "attn_norm": f"{base}.input_layernorm.weight",
    }
    if cfg.attn_qkv_bias:  # Qwen-2 layout
        names["bq"] = f"{base}.self_attn.q_proj.bias"
        names["bk"] = f"{base}.self_attn.k_proj.bias"
        names["bv"] = f"{base}.self_attn.v_proj.bias"
    if cfg.n_experts > 0:  # Mixtral layout
        names["router"] = f"{base}.block_sparse_moe.gate.weight"
    else:
        names["w_gate"] = f"{base}.mlp.gate_proj.weight"
        names["w_up"] = f"{base}.mlp.up_proj.weight"
        names["w_down"] = f"{base}.mlp.down_proj.weight"
    if cfg.post_block_norm:  # Gemma-2 has four norms per block
        names["attn_post_norm"] = f"{base}.post_attention_layernorm.weight"
        names["mlp_norm"] = f"{base}.pre_feedforward_layernorm.weight"
        names["mlp_post_norm"] = f"{base}.post_feedforward_layernorm.weight"
    else:
        names["mlp_norm"] = f"{base}.post_attention_layernorm.weight"
    return names


# Mixtral expert naming: w1 = gate, w2 = down, w3 = up
_EXPERT_HF = {"w_gate": "w1", "w_up": "w3", "w_down": "w2"}
_EXPERT_KEYS = ("w_gate", "w_up", "w_down")


def _hf_expert_names(i: int, e: int) -> Dict[str, str]:
    base = f"model.layers.{i}.block_sparse_moe.experts.{e}"
    return {k: f"{base}.{v}.weight" for k, v in _EXPERT_HF.items()}


# HF stores every projection (and the Mixtral router) as [out, in];
# this pytree keeps [in, out] so matmuls read x @ w
_TRANSPOSED = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
               "router"}


def _open_shards(model_dir: str):
    """Yield (name → (file, tensorname)) index over all safetensors shards."""
    from safetensors import safe_open

    index_path = os.path.join(model_dir, "model.safetensors.index.json")
    files: Dict[str, str] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
        for tname, fname in weight_map.items():
            files[tname] = os.path.join(model_dir, fname)
    else:
        single = os.path.join(model_dir, "model.safetensors")
        if not os.path.exists(single):
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] in {model_dir}")
        with safe_open(single, framework="numpy") as f:
            for tname in f.keys():
                files[tname] = single
    return files


def load_hf_checkpoint(model_dir: str, cfg: ModelConfig, *,
                       mesh=None,
                       quantize: Optional[str] = None) -> Params:
    """Stream HF safetensors into the (optionally mesh-sharded) pytree.

    ``quantize``: "nf4" | "int8" — quantize the projection matrices
    *during* the stream (one layer-slice at a time, quantized result
    held in host RAM), so an 8B QLoRA base loads onto a single 16 GB
    chip without the full-precision tree ever existing on device. The
    equivalent of the reference loading with BitsAndBytesConfig
    (fine_tune_llama_ray.py:216-227,240). Norms/embed/lm_head stay full
    precision, like bnb.
    """
    from safetensors import safe_open

    specs = param_specs(cfg)
    shardings = (tree_shardings(mesh, specs) if mesh is not None else None)
    pdt = jnp.dtype(cfg.param_dtype)
    P_ = len(cfg.block_pattern)
    R = cfg.n_repeats
    handles: Dict[str, object] = {}

    def read(tname: str) -> np.ndarray:
        path = files[tname]
        if path not in handles:
            handles[path] = safe_open(path, framework="numpy")
        # bf16 tensors come back as ml_dtypes.bfloat16, which jnp converts
        return np.asarray(handles[path].get_tensor(tname))

    files = _open_shards(model_dir)

    def place(arr: np.ndarray, spec_path) -> jax.Array:
        arr = jnp.asarray(arr, pdt)
        if shardings is None:
            return arr
        return jax.device_put(arr, spec_path)

    def _accumulate(shape, dtype, sharding, slices):
        """Stream per-slice [1, ..] (or [1, 1, ..] for expert banks)
        device arrays into a stacked leaf living at its final (sharded)
        home: zeros-allocate once, then one donated dynamic_update_slice
        per slice. Host RAM peak stays one tensor (VERDICT r3 weak #4a:
        np.stack of all R slices held ~37 GB host RAM for a single 70B
        leaf). ``slices`` yields (lead-index tuple, array)."""
        kw = {} if sharding is None else {"out_shardings": sharding}
        out = jax.jit(lambda: jnp.zeros(shape, dtype), **kw)()
        upd = jax.jit(
            lambda o, a, idx: jax.lax.dynamic_update_slice(
                o, a.astype(dtype),
                tuple(idx) + (0,) * (len(shape) - len(idx))),
            donate_argnums=(0,))
        for idx, a in slices:
            out = upd(out, a, idx)
        return out

    def _indices_and_names(p: int, key: str, experts: bool):
        """(lead index tuples, idx→tensor-name) for a stacked leaf:
        [R] per-layer tensors, or [R, E] per-(layer, expert) for MoE
        banks (Mixtral layout)."""
        if experts:
            idxs = [(r, e) for r in range(R)
                    for e in range(cfg.n_experts)]
            return idxs, (lambda idx: _hf_expert_names(
                idx[0] * P_ + p, idx[1])[key])
        return [(r,) for r in range(R)], (lambda idx: _hf_layer_names(
            cfg, idx[0] * P_ + p)[key])

    def _slice_sharding(spec, n_lead: int):
        """Sharding for ONE streamed slice: the full leaf's spec with
        its lead (stack) dims replaced by None — a [1, 1, D, F] expert
        slice cannot be partitioned along its size-1 expert dim even
        though the assembled [R, E, D, F] leaf is."""
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(mesh, PartitionSpec(
            *([None] * n_lead + list(spec)[n_lead:])))

    def load_stacked(p: int, key: str, *, experts: bool = False):
        idxs, name = _indices_and_names(p, key, experts)
        n_lead = len(idxs[0])
        tgt = shardings["blocks"][p][key] if shardings is not None else None
        # idxs[0] reuses the shape-probe read (one disk read per tensor)
        first = _maybe_t(read(name(idxs[0])), key)
        slice_tgt = (None if tgt is None else
                     _slice_sharding(specs["blocks"][p][key], n_lead))

        def slices():
            for idx in idxs:
                w = first if idx == idxs[0] else _maybe_t(
                    read(name(idx)), key)
                a = w[(None,) * n_lead]
                yield idx, (a if slice_tgt is None
                            else jax.device_put(a, slice_tgt))

        lead = tuple(d + 1 for d in idxs[-1])
        return _accumulate(lead + first.shape, pdt, tgt, slices())

    def load_quantized(p: int, key: str, *, experts: bool = False):
        """Per-slice quantize: device sees one layer (or one (layer,
        expert)) slice at a time; codes/scales stream straight into
        their device-resident (sharded) homes — neither the bf16 tree
        nor the stacked codes ever exist in host RAM (VERDICT r3 weak
        #4a)."""
        from jax.sharding import NamedSharding
        from gke_ray_train_tpu.ops.quant import (
            QTensor, quant_specs, quantize_tensor)
        idxs, name = _indices_and_names(p, key, experts)
        n_lead = len(idxs[0])

        def qt_for(idx):
            w = _maybe_t(read(name(idx)), key)
            return quantize_tensor(
                jnp.asarray(w, jnp.bfloat16)[(None,) * n_lead], quantize)

        first = qt_for(idxs[0])
        kind, group = first.kind, first.group
        lead = tuple(d + 1 for d in idxs[-1])
        c_shape = lead + first.codes.shape[n_lead:]
        s_shape = lead + first.scales.shape[n_lead:]
        c_shard = s_shard = None
        if mesh is not None:
            q_spec = quant_specs(specs["blocks"][p][key], QTensor(
                jax.ShapeDtypeStruct(c_shape, first.codes.dtype),
                jax.ShapeDtypeStruct(s_shape, first.scales.dtype),
                kind, group), mesh)
            c_shard = NamedSharding(mesh, q_spec.codes)
            s_shard = NamedSharding(mesh, q_spec.scales)

        # one read+quantize pass per tensor, feeding BOTH accumulators
        kwc = {} if c_shard is None else {"out_shardings": c_shard}
        kws = {} if s_shard is None else {"out_shardings": s_shard}
        codes = jax.jit(lambda: jnp.zeros(c_shape, first.codes.dtype),
                        **kwc)()
        scales = jax.jit(lambda: jnp.zeros(s_shape, first.scales.dtype),
                         **kws)()
        upd = jax.jit(
            lambda o, a, idx: jax.lax.dynamic_update_slice(
                o, a, tuple(idx) + (0,) * (len(o.shape) - n_lead)),
            donate_argnums=(0,))
        for idx in idxs:
            qt = first if idx == idxs[0] else qt_for(idx)
            codes = upd(codes, qt.codes, idx)
            scales = upd(scales, qt.scales, idx)
        return QTensor(codes, scales, kind, group)

    # per-(pattern-position, key): stream the R per-layer tensors
    from gke_ray_train_tpu.models.config import PROJ_TARGETS as _PROJ_KEYS
    blocks = []
    for p in range(P_):
        blk: Dict[str, jax.Array] = {}
        keys = _hf_layer_names(cfg, 0).keys()
        for key in keys:
            if quantize and key in _PROJ_KEYS:
                blk[key] = load_quantized(p, key)
                continue
            blk[key] = load_stacked(p, key)
        for key in (_EXPERT_KEYS if cfg.n_experts > 0 else ()):
            blk[key] = (load_quantized(p, key, experts=True) if quantize
                        else load_stacked(p, key, experts=True))
        blocks.append(blk)

    params: Params = {
        "embed": place(read("model.embed_tokens.weight"),
                       shardings["embed"] if shardings else None),
        "blocks": blocks,
        "final_norm": place(read("model.norm.weight"),
                            shardings["final_norm"] if shardings else None),
    }
    if not cfg.tie_embeddings:
        name = ("lm_head.weight" if "lm_head.weight" in files
                else "model.embed_tokens.weight")  # some exports tie anyway
        params["lm_head"] = place(read(name).T,
                                  shardings["lm_head"] if shardings else None)
    for h in handles.values():
        del h
    return params


def _maybe_t(arr: np.ndarray, key: str) -> np.ndarray:
    return arr.T if key in _TRANSPOSED else arr


class ShardedSafetensorsWriter:
    """Incremental HF-layout safetensors writer with bounded host RAM.

    Tensors accumulate into an in-memory shard until ``max_shard_bytes``,
    then flush to ``model-XXXXX-of-YYYYY.safetensors``; ``finish()``
    renames the shards with the final count and writes
    ``model.safetensors.index.json`` (the layout ``_open_shards``
    reads back). A model that fits one shard is written as plain
    ``model.safetensors`` with no index — identical to the old
    single-file export. Peak host RAM = max_shard_bytes + one tensor."""

    def __init__(self, out_dir: str, *, max_shard_bytes: int = 4 << 30):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.max_shard_bytes = max_shard_bytes
        self._cur: Dict[str, np.ndarray] = {}
        self._cur_bytes = 0
        self._shards = []          # temp file paths, in order
        self._weight_maps = []     # [names] per shard

    def add(self, name: str, arr: np.ndarray) -> None:
        if self._cur and self._cur_bytes + arr.nbytes > self.max_shard_bytes:
            self._flush()
        self._cur[name] = arr
        self._cur_bytes += arr.nbytes

    def _flush(self) -> None:
        from safetensors.numpy import save_file
        path = os.path.join(self.out_dir,
                            f"model-tmp-{len(self._shards):05d}.safetensors")
        save_file(self._cur, path)
        self._shards.append(path)
        self._weight_maps.append(list(self._cur))
        self._cur = {}
        self._cur_bytes = 0

    def abort(self) -> None:
        """Remove tmp shards after a mid-stream failure so a retry does
        not inherit stale model-tmp-* files."""
        for tmp in self._shards:
            try:
                os.remove(tmp)
            except OSError:
                pass
        self._shards = []
        self._weight_maps = []
        self._cur = {}
        self._cur_bytes = 0

    def finish(self) -> None:
        if self._cur or not self._shards:
            self._flush()
        n = len(self._shards)
        if n == 1:
            os.replace(self._shards[0],
                       os.path.join(self.out_dir, "model.safetensors"))
            return
        weight_map = {}
        for i, (tmp, names) in enumerate(zip(self._shards,
                                             self._weight_maps)):
            fname = f"model-{i + 1:05d}-of-{n:05d}.safetensors"
            os.replace(tmp, os.path.join(self.out_dir, fname))
            for t in names:
                weight_map[t] = fname
        with open(os.path.join(self.out_dir,
                               "model.safetensors.index.json"), "w") as f:
            json.dump({"metadata": {}, "weight_map": weight_map}, f)


def hf_dtype_np(arr, dtype: str) -> np.ndarray:
    arr = np.asarray(jax.device_get(arr))
    if dtype == "bfloat16":
        import ml_dtypes
        arr = arr.astype(ml_dtypes.bfloat16)
    else:
        arr = arr.astype(np.dtype(dtype))
    # astype(order='K') keeps F-order on transposed views and
    # safetensors serializes the raw buffer ignoring strides — force C
    return np.ascontiguousarray(arr)


def save_hf_checkpoint(params: Params, cfg: ModelConfig, out_dir: str,
                       *, dtype: str = "bfloat16",
                       max_shard_bytes: int = 4 << 30) -> None:
    """Export the pytree to HF safetensors (sharded above
    ``max_shard_bytes``) + minimal config.json (save_pretrained parity).
    Tensors are pulled off device one LAYER at a time and flushed
    incrementally — host RAM stays O(max_shard_bytes), not O(model)
    (VERDICT r3 weak #4: the 70B export must not buffer every tensor)."""
    P_ = len(cfg.block_pattern)
    w = ShardedSafetensorsWriter(out_dir, max_shard_bytes=max_shard_bytes)

    def to_np(x) -> np.ndarray:
        return hf_dtype_np(x, dtype)

    w.add("model.embed_tokens.weight", to_np(params["embed"]))
    w.add("model.norm.weight", to_np(params["final_norm"]))
    if not cfg.tie_embeddings:
        w.add("lm_head.weight", to_np(params["lm_head"].T))
    for p, blk in enumerate(params["blocks"]):
        for r in range(cfg.n_repeats):
            names = _hf_layer_names(cfg, r * P_ + p)
            for key, tname in names.items():
                arr = jax.device_get(blk[key][r])
                w.add(tname, to_np(_maybe_t(np.asarray(arr), key)))
            for key in (_EXPERT_KEYS if cfg.n_experts > 0 else ()):
                for e in range(cfg.n_experts):
                    arr = jax.device_get(blk[key][r, e])
                    w.add(_hf_expert_names(r * P_ + p, e)[key],
                          to_np(_maybe_t(np.asarray(arr), key)))
    w.finish()
    write_hf_config(cfg, out_dir, dtype)


# cfg.name prefix → (HF architectures entry, model_type). Known
# families export a REAL HF config so `AutoConfig`/`AutoModelForCausalLM
# .from_pretrained(out_dir)` work with stock transformers — the same
# directly-loadable artifact the reference's save_pretrained produces
# (/root/reference/ray-jobs/fine_tune_llama_ray.py:354-355). Unknown
# (from-scratch) families keep the custom tag.
_HF_ARCH = (
    ("llama", ("LlamaForCausalLM", "llama")),
    ("mixtral", ("MixtralForCausalLM", "mixtral")),
    ("mistral", ("MistralForCausalLM", "mistral")),
    ("gemma2", ("Gemma2ForCausalLM", "gemma2")),
    ("qwen2", ("Qwen2ForCausalLM", "qwen2")),
)


def write_hf_config(cfg: ModelConfig, out_dir: str,
                    dtype: str = "bfloat16") -> None:
    arch, model_type = next(
        (v for pfx, v in _HF_ARCH if cfg.name.startswith(pfx)),
        ("GkeRayTrainTpuForCausalLM", None))
    out = {
        "architectures": [arch],
        "model_family": cfg.name,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.d_model,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.d_ff,
        "head_dim": cfg.resolved_head_dim,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": dtype,
        "max_position_embeddings": cfg.max_seq_len,
        "hidden_act": ("gelu_pytorch_tanh"
                       if cfg.activation == "gelu_tanh" else "silu"),
        **({"num_local_experts": cfg.n_experts,
            "num_experts_per_tok": cfg.expert_top_k}
           if cfg.n_experts else {}),
    }
    if model_type is not None:
        out["model_type"] = model_type
    if cfg.sliding_window is not None:
        out["sliding_window"] = cfg.sliding_window
    if cfg.attn_qkv_bias:
        out["attention_bias"] = True
    if cfg.rope_scaling:
        rs = dict(cfg.rope_scaling)
        # Exported bit-identical to training: every rope_scaling field
        # (factor, band factors, original_max_position_embeddings) FEEDS
        # HF's _compute_llama3_parameters, so clamping any of them would
        # silently change the loaded model's rotary frequencies.
        # max_position_embeddings stays the context the model was
        # actually built/trained with (cfg.max_seq_len) — the old
        # original*factor inflation (65536 for the Llama-3.1 preset)
        # advertised a context matching neither this model nor the stock
        # HF checkpoint (ADVICE r5 #2). When max_seq_len <= original,
        # HF's llama3 validation logs a warning (original must be <
        # max_position_embeddings) but loads fine — frequencies depend
        # only on rope_scaling, never on max_position_embeddings.
        out["rope_scaling"] = {"rope_type": "llama3", **rs}
    if model_type == "gemma2":
        if cfg.attn_softcap is not None:
            out["attn_logit_softcapping"] = cfg.attn_softcap
        if cfg.logit_softcap is not None:
            out["final_logit_softcapping"] = cfg.logit_softcap
        if cfg.attn_scale is not None:
            out["query_pre_attn_scalar"] = round(cfg.attn_scale ** -2)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(out, f, indent=2)
