from gke_ray_train_tpu.ckpt.manager import CheckpointManager  # noqa: F401
from gke_ray_train_tpu.ckpt.hf_io import (  # noqa: F401
    load_hf_checkpoint, save_hf_checkpoint)
from gke_ray_train_tpu.ckpt.hub import (  # noqa: F401
    acquire_pretrained, fetch_pretrained)
