"""Pretrained-weight acquisition from the HF hub (VERDICT r1 missing #2).

The reference's flagship job pulls Llama-3.1-8B with
``AutoModelForCausalLM.from_pretrained(MODEL_ID)``
(/root/reference/ray-jobs/fine_tune_llama_ray.py:240). TPU equivalent:
``huggingface_hub.snapshot_download`` of ONLY the safetensors shards +
index + tokenizer/config files (never torch .bin), then the streaming
loader (ckpt/hf_io.py) device_puts each tensor straight into its mesh
sharding — no host ever materializes the whole model.

Multi-host etiquette: host 0 downloads first (warming any shared
HF_HOME, e.g. the /mnt/hf_cache emptyDir contract from the RayCluster
CR), everyone barriers, then the rest resolve — a cache hit when the
cache is shared, a parallel download when it is not (same behavior as
every rank calling from_pretrained in the reference).
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger(__name__)

# everything the fine-tune path needs; notably NOT *.bin / *.pth /
# original/ consolidated checkpoints
WEIGHT_PATTERNS = [
    "*.safetensors",
    "*.safetensors.index.json",
    "config.json",
    "generation_config.json",
    "tokenizer*",
    "special_tokens_map.json",
    # newer HF repos ship the chat template as its own file
    # (chat_template.jinja / chat_template.json); without it the
    # inference comparison renders a silently different prompt format
    "chat_template*",
]


def fetch_pretrained(model_id: str, *, token: Optional[str] = None,
                     cache_dir: Optional[str] = None) -> str:
    """snapshot_download the weight/tokenizer files; returns the local
    snapshot directory (raises on network/auth failure — callers decide
    the fallback)."""
    from huggingface_hub import snapshot_download

    path = snapshot_download(
        model_id, token=token, cache_dir=cache_dir,
        allow_patterns=WEIGHT_PATTERNS)
    logger.info("hub snapshot for %s at %s", model_id, path)
    return path


def acquire_pretrained(model_id: str, *, token: Optional[str] = None,
                       cache_dir: Optional[str] = None,
                       num_hosts: int = 1,
                       host_id: int = 0) -> Optional[str]:
    """Hub acquisition with multi-host ordering; returns the local dir
    holding safetensors, or None when the hub is unreachable (offline
    smoke environments) — the caller warns and falls back.
    """
    path = None
    err = None
    if host_id == 0:
        try:
            path = fetch_pretrained(model_id, token=token,
                                    cache_dir=cache_dir)
        except Exception as e:  # noqa: BLE001 — offline is a supported mode
            err = e
    if num_hosts > 1:
        # the use-pretrained-or-fallback decision must be COLLECTIVE:
        # hosts disagreeing on random vs pretrained init would silently
        # train garbage. Host 0's outcome is broadcast to everyone.
        import numpy as np
        from jax.experimental import multihost_utils
        ok = multihost_utils.broadcast_one_to_all(
            np.asarray(1 if (host_id != 0 or path is not None) else 0,
                       np.int32))
        if int(ok) == 0:
            if host_id == 0:
                logger.warning("hub acquisition for %s failed (%s: %s); "
                               "all hosts falling back", model_id,
                               type(err).__name__, err)
            return None
        if host_id != 0:
            # host 0 succeeded — a follower failing here would leave the
            # SPMD program inconsistent, so it is fatal, not a fallback
            path = fetch_pretrained(model_id, token=token,
                                    cache_dir=cache_dir)
        return path
    if path is None:
        logger.warning("hub acquisition for %s failed (%s: %s)",
                       model_id, type(err).__name__, err)
    return path
