"""shardlint level 3 — opt-in runtime teeth for the linted properties.

Three guards, each enabled by an env/config knob (audited in
``config.py`` KNOWN_KEYS, forwarded to Ray workers by the trainer):

- ``TRANSFER_GUARD=disallow|log`` — wraps the hot loop in JAX's
  device→host transfer guard so an implicit fetch (a stray ``.item()``,
  ``np.asarray`` on a device array) raises/logs AT THE CALL SITE
  instead of silently serializing the step pipeline. The loop's
  legitimate fetches (the once-per-log-step batched metrics fetch, the
  checkpoint save, eval) run inside :func:`allow_transfers` — the
  explicit allow-list the ISSUE's policy demands. No-op on the CPU
  backend (host "transfers" are zero-copy there; the knob still
  exercises the config plumbing in CI).

- ``RECOMPILE_LIMIT=N`` — the trace-level recompile *detector*
  (jaxprcheck.py) turned into a hard error: more than N compiles of any
  one function raises :class:`RecompileLimitExceeded` from inside the
  compile path, naming the function and the signature churn that caused
  it. Catches shape/dtype/sharding churn the moment it happens instead
  of as a mysteriously slow run.

- ``DIVERGENCE_GUARD=1`` — multi-host lowered-HLO agreement: before the
  first step each host fingerprints its lowered step-fn StableHLO and
  allgathers the digest. Hosts tracing DIFFERENT programs (data-
  dependent Python branching, version skew, divergent config) today
  present as an unexplained collective deadlock the PR-3 watchdog can
  only name; the guard fails fast with the per-host diff instead.
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)


class GuardViolation(RuntimeError):
    """Base class for runtime-guard failures."""


class RecompileLimitExceeded(GuardViolation):
    """One function was compiled more often than RECOMPILE_LIMIT allows."""


class HloDivergenceError(GuardViolation):
    """Hosts lowered DIFFERENT step programs — collectives would wedge."""


def _knob(name: str, config: Optional[dict] = None) -> Optional[str]:
    """Config key wins over env (same precedence as every other knob)."""
    if config is not None and name in config:
        return str(config[name])
    return os.environ.get(name)


# ---------------------------------------------------------------------------
# transfer guard
# ---------------------------------------------------------------------------

def transfer_guard_mode(config: Optional[dict] = None) -> Optional[str]:
    raw = (_knob("TRANSFER_GUARD", config) or "").strip().lower()
    if raw in ("", "0", "off", "false", "allow"):
        return None
    if raw in ("log", "disallow"):
        return raw
    logger.warning("TRANSFER_GUARD=%r not recognized "
                   "(expected log|disallow|off); guard disabled", raw)
    return None


def transfer_guard_ctx(mode: Optional[str]):
    """Context manager enforcing ``mode`` on device→host transfers for
    the current thread. Only d2h is guarded: the input pipeline's
    host→device placement is the loop's own legitimate traffic."""
    if mode is None:
        return contextlib.nullcontext()
    import jax
    return jax.transfer_guard_device_to_host(mode)


def allow_transfers():
    """The explicit allow-list: wrap the loop's sanctioned fetch sites
    (batched metrics fetch, eval, checkpoint save, host collectives)."""
    import jax
    return jax.transfer_guard_device_to_host("allow")


# ---------------------------------------------------------------------------
# recompile limit (hard-error form of jaxprcheck.RecompileDetector)
# ---------------------------------------------------------------------------

_LIMIT_STATE: Dict[str, Any] = {"detector": None, "limit": 0}


def recompile_limit(config: Optional[dict] = None) -> int:
    raw = _knob("RECOMPILE_LIMIT", config)
    try:
        return max(int(raw), 0) if raw else 0
    except ValueError:
        logger.warning("RECOMPILE_LIMIT=%r is not an int; guard disabled",
                       raw)
        return 0


def install_recompile_limit(limit: Optional[int] = None,
                            config: Optional[dict] = None) -> bool:
    """Arm the hard limit: the (limit+1)-th compile of any single
    function raises :class:`RecompileLimitExceeded` from the compile
    path, carrying the signature diff. Returns True when armed."""
    limit = recompile_limit(config) if limit is None else limit
    if limit <= 0:
        return False
    from gke_ray_train_tpu.analysis.jaxprcheck import RecompileDetector

    def on_excess(name, sigs):
        raise RecompileLimitExceeded(
            f"function {name!r} compiled {len(sigs)} times "
            f"(RECOMPILE_LIMIT={limit}). A step fn must compile once — "
            "look for shape/dtype/sharding churn in its inputs:\n"
            + RecompileDetector.describe_churn(sigs))

    uninstall_recompile_limit()
    det = RecompileDetector(on_compile_over=on_excess, over_count=limit)
    det.start()
    _LIMIT_STATE.update(detector=det, limit=limit)
    logger.info("recompile limit armed: hard error past %d compiles "
                "of any one function", limit)
    return True


def uninstall_recompile_limit() -> None:
    det = _LIMIT_STATE.get("detector")
    if det is not None:
        det.stop()
    _LIMIT_STATE.update(detector=None, limit=0)


# ---------------------------------------------------------------------------
# multi-host divergence guard
# ---------------------------------------------------------------------------

def divergence_guard_enabled(config: Optional[dict] = None) -> bool:
    raw = (_knob("DIVERGENCE_GUARD", config) or "").strip().lower()
    return raw not in ("", "0", "off", "false")

# StableHLO text capped per host for the post-mismatch diff exchange:
# digests (64 hex chars) establish DISAGREEMENT cheaply; the capped
# text is only shipped once a mismatch is already certain
_DIFF_TEXT_CAP = 64 * 1024
_BARRIER_TIMEOUT_MS = 120_000
# per-process round counter: the guard is collective (every host calls
# it in lockstep), so the sequence numbers — and therefore the KV keys
# — agree across hosts without any coordination
_ROUND = [0]


def hlo_fingerprint(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


def _distributed_client():
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001 - private API drift
        return None


def _allgather_str(value: str, tag: str, n_procs: int, rank: int) -> list:
    """Exchange one string per host over the jax.distributed KV store.

    Deliberately NOT an XLA collective: the guard must work on the CPU
    multi-process harness (whose backend has no cross-process XLA
    collectives) and, more importantly, must stay usable exactly when
    device collectives are the thing about to deadlock — the KV
    store/barrier is the same control-plane rendezvous
    ``jax.distributed.initialize`` already stood up."""
    import base64
    client = _distributed_client()
    if client is None:
        raise RuntimeError("jax.distributed client unavailable")
    client.key_value_set(f"{tag}/{rank}",
                         base64.b64encode(value.encode()).decode())
    client.wait_at_barrier(f"{tag}/barrier", _BARRIER_TIMEOUT_MS)
    return [
        base64.b64decode(
            client.blocking_key_value_get(f"{tag}/{r}",
                                          _BARRIER_TIMEOUT_MS)).decode()
        for r in range(n_procs)]


def check_host_hlo_agreement(step_fn, *abstract_args,
                             label: str = "train_step") -> Optional[str]:
    """Exchange a fingerprint of this host's lowered step-fn HLO and
    fail fast — with the per-host diff — when hosts disagree.

    ``step_fn`` needs a ``.lower`` (a jitted function or the AOT
    GuardedStep passthrough); args may be concrete or abstract. Returns
    the agreed fingerprint (None when lowering or the distributed
    client is unavailable — an opt-in guard fails open, loudly, rather
    than killing a run it cannot check).
    """
    import jax
    if jax.process_count() <= 1:
        return None
    n_procs, rank = jax.process_count(), jax.process_index()

    def exec_text():
        # AOT fast path: a GuardedStep already holds a compiled
        # executable — re-texting it is free, where .lower() would
        # re-TRACE the whole step (jit's AOT lower never populates the
        # dispatch cache, so that trace would be duplicated by the
        # first real step call; restart_to_first_step_s money at 8B)
        compiled = getattr(step_fn, "_compiled", None)
        if compiled is None:
            return None
        try:
            return compiled.as_text()
        except Exception:  # noqa: BLE001 - some backends cannot re-text
            return None

    def mlir_text():
        lower = getattr(step_fn, "lower", None)
        if lower is None:
            return None
        try:
            # one extra trace+MLIR-lowering at attempt start (no XLA
            # compile) — the opt-in cost of the check on the jit path
            return lower(*abstract_args).as_text()
        except Exception as e:  # noqa: BLE001 - guard must not kill run
            logger.warning("divergence guard: lowering failed (%s: %s)",
                           type(e).__name__, e)
            return None

    text = exec_text()
    fmt = "exec" if text is not None else "mlir"
    if text is None:
        text = mlir_text()

    _ROUND[0] += 1
    tag = f"shardlint/divergence/{_ROUND[0]}"

    def exchange(sub, fmt, text):
        """One (format, digest) exchange round; returns (fmts, digests).
        A host that could not produce text sends a sentinel — the
        gathered view is identical everywhere, so every host reaches
        the same skip/compare/recompute verdict in lockstep."""
        payload = f"{fmt}\n{hlo_fingerprint(text) if text else ''}"
        rows = _allgather_str(payload, f"{tag}/{sub}", n_procs, rank)
        fmts, digests = zip(*(r.split("\n", 1) for r in rows))
        return list(fmts), list(digests)

    try:
        fmts, digests = exchange("digest", fmt if text else "none", text)
        if "none" in fmts:
            logger.warning("divergence guard: host(s) %s could not "
                           "produce program text; check skipped",
                           [i for i, f in enumerate(fmts) if f == "none"])
            return None
        if len(set(fmts)) > 1:
            # hosts derived their text DIFFERENTLY (one re-texted its
            # AOT executable, another lowered fresh) — the digests are
            # incomparable across formats and must not be read as
            # divergence. Every host falls back to the one universally
            # derivable format (lowered MLIR) and compares again.
            logger.info("divergence guard: mixed text sources %s; "
                        "re-deriving via lower() on every host",
                        sorted(set(fmts)))
            if fmt != "mlir":
                text = mlir_text()
            fmts, digests = exchange("digest2", "mlir" if text else "none",
                                     text)
            if "none" in fmts:
                logger.warning("divergence guard: lowering unavailable "
                               "on host(s) %s; check skipped",
                               [i for i, f in enumerate(fmts)
                                if f == "none"])
                return None
    except Exception as e:  # noqa: BLE001 - control-plane hiccup
        logger.warning("divergence guard: fingerprint exchange failed "
                       "(%s: %s); skipped", type(e).__name__, e)
        return None
    if len(set(digests)) == 1:
        logger.info("divergence guard: %d hosts agree on %s HLO %s",
                    n_procs, label, digests[0][:12])
        return digests[0]
    # disagreement is certain — every host ships capped text for a
    # real per-host diff (all hosts computed the same verdict, so the
    # second exchange is symmetric)
    import difflib
    per_host = ", ".join(f"host {i}: {d[:12]}"
                         for i, d in enumerate(digests))
    try:
        texts = _allgather_str(text[:_DIFF_TEXT_CAP], f"{tag}/text",
                               n_procs, rank)
    except Exception as e:  # noqa: BLE001 - the VERDICT must survive a
        # control-plane failure here (a diverged peer may already be
        # dying): raise the nonretryable divergence error with the
        # fingerprints, not a retryable generic that buries them
        raise HloDivergenceError(
            f"hosts lowered DIFFERENT {label} programs — the "
            f"collectives they emit will deadlock, not train. "
            f"Fingerprints: {per_host}. (per-host diff unavailable: "
            f"text exchange failed with {type(e).__name__}: {e})")
    # diff OWN program against the first DISAGREEING peer — diffing
    # against host 0 unconditionally hands host 0 (and every host that
    # agrees with it) an empty diff about its own program
    peer = next((i for i in range(n_procs)
                 if digests[i] != digests[rank]), None)
    diff = [] if peer is None else list(difflib.unified_diff(
        texts[rank].splitlines(), texts[peer].splitlines(),
        lineterm="", fromfile=f"host {rank} (this host)",
        tofile=f"host {peer}"))[:40]
    raise HloDivergenceError(
        f"hosts lowered DIFFERENT {label} programs — the collectives "
        f"they emit will deadlock, not train. Fingerprints: {per_host}.\n"
        "Likely causes: data-dependent Python branching in the step, "
        "per-host config drift, or jax/jaxlib version skew.\n"
        + ("\n".join(diff) if diff
           else "(programs differ beyond the diff cap)"))


# ---------------------------------------------------------------------------
# the bundle run_training consumes
# ---------------------------------------------------------------------------

class RuntimeGuards:
    """Resolved guard configuration for one training run."""

    def __init__(self, *, transfer_mode: Optional[str] = None,
                 divergence: bool = False):
        self.transfer_mode = transfer_mode
        self.divergence = divergence

    @staticmethod
    def from_config(config: Optional[dict] = None) -> "RuntimeGuards":
        """Env/config resolution (config key wins). Also the from-env
        default ``run_training`` builds when handed no guards."""
        return RuntimeGuards(
            transfer_mode=transfer_guard_mode(config),
            divergence=divergence_guard_enabled(config))

    def transfer_ctx(self):
        return transfer_guard_ctx(self.transfer_mode)

    def check_divergence(self, step_fn, state, batch,
                         label: str = "train_step") -> None:
        if self.divergence:
            check_host_hlo_agreement(step_fn, state, batch, label=label)

    def __repr__(self) -> str:  # pragma: no cover - logging nicety
        return (f"RuntimeGuards(transfer={self.transfer_mode or 'off'}, "
                f"divergence={'on' if self.divergence else 'off'})")
