"""shardlint level 1 — AST rules over the repo's own source.

Catches the sharding/host-sync bug classes that destroy TPU throughput
*before any code runs*, on a CPU-only CI runner:

========  ==========================================================
code      what it catches
========  ==========================================================
TPU001    host-sync in the hot path: ``.item()`` / ``float()`` /
          ``np.asarray`` / ``jax.device_get`` /
          ``jax.block_until_ready`` inside a jit-reachable function
          (anything transitively called from a ``train_step`` /
          ``eval_step`` body or passed to a tracing transform), and
          per-element ``jax.device_get`` inside a loop/comprehension
          anywhere (N round-trips where one batched fetch of the
          tree would do)
TPU002    ``PartitionSpec`` axis name outside the mesh-axis
          vocabulary declared by ``parallel/mesh.py`` — a
          ``P("fsdb", None)`` typo silently REPLICATES the tensor
TPU003    jitted step-like function (takes a state pytree, returns
          one) without ``donate_argnums`` — doubles peak HBM for
          params + optimizer state
TPU004    impure calls in traced code (``np.random.*``,
          ``time.time()``, ``random.*``) — baked in as constants at
          trace time, the bug class the runtime bans elsewhere
TPU005    ``jnp.array(...)`` of Python/host data inside a traced
          function — hidden host→device transfer re-staged every
          trace, plus constant-folding blowup in XLA
TPU000    a ``# shardlint: disable=...`` suppression with no reason
          string (the suppression policy: every waiver says why)
========  ==========================================================

Suppression syntax (same line as the finding)::

    x = batch["n"].item()  # shardlint: disable=TPU001 -- probe path, once

The reachability analysis is name-based and project-local: step-named
defs (``STEP_FN_NAMES`` — the train/eval steps plus the serving
engine's prefill/decode/insert bodies), functions passed to tracing transforms
(``jit``/``grad``/``scan``/``shard_map``/``pallas_call``/...), and
functions decorated with them seed the traced set; the set closes over
same-named project defs called from traced bodies, and lexically nested
defs. Deliberately over-approximate — a false "traced" marking surfaces
at lint time and is cheap to inspect; a missed one ships a sync.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

RULES = {
    "TPU000": "suppression lacks a reason string",
    "TPU001": "host-device sync in the hot path",
    "TPU002": "PartitionSpec axis not in the mesh-axis vocabulary",
    "TPU003": "jitted step-like function without donate_argnums",
    "TPU004": "impure call in traced code",
    "TPU005": "host-data jnp.array inside a traced function",
}

# tracing transforms: a function passed to (or decorated with) one of
# these runs under trace — host syncs and impurity inside are bugs
TRACE_TRANSFORMS = frozenset({
    "jit", "pjit", "grad", "value_and_grad", "vmap", "pmap", "scan",
    "while_loop", "fori_loop", "cond", "switch", "checkpoint", "remat",
    "shard_map", "pallas_call", "custom_vjp", "custom_jvp", "associative_scan",
})

STEP_FN_NAMES = frozenset({
    "train_step", "eval_step",
    # the serving engine's jit-reachable bodies (serve/engine.py): they
    # compile through compile_step_with_plan rather than a literal
    # jax.jit call site, so name-seeding is what puts the continuous-
    # batching decode loop under TPU001/TPU004/TPU005
    "prefill_step", "decode_step", "insert_slot"})

# host-sync callables by resolved dotted path (module aliases resolved)
HOST_SYNC_PATHS = frozenset({
    "jax.device_get", "jax.block_until_ready",
    "numpy.asarray", "numpy.array",
})

IMPURE_PATHS = frozenset({
    "time.time", "time.perf_counter", "time.monotonic",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})
IMPURE_PREFIXES = ("numpy.random.", "random.")

# params that, by this repo's naming convention, carry array data —
# float()/int() of these inside traced code concretizes a tracer.
# Deliberately an allowlist: traced helpers legitimately int() their
# static Python config args (microbatch counts, capacities, seq lens),
# and a blocklist of "static-looking" names cannot keep up with them.
_ARRAY_PARAM_NAMES = frozenset({
    "state", "batch", "params", "grads", "grad", "x", "y", "q", "k", "v",
    "logits", "loss", "inputs", "targets", "weights", "m", "metrics",
    "nll", "w", "out", "lora", "micro", "carry", "acc", "hidden",
})

_SUPPRESS_RE = re.compile(
    r"#\s*shardlint:\s*disable=([A-Z0-9, ]+?)\s*(?:--\s*(\S.*))?$")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} " \
               f"{self.message}"


# ---------------------------------------------------------------------------
# mesh-axis vocabulary: read from the ExecutionPlan's declared axis
# names (plan.py), never hardcoded — adding a mesh axis must not
# require touching the linter
# ---------------------------------------------------------------------------

def mesh_axis_vocabulary(mesh_py_source: str) -> Set[str]:
    """The axis names a MESH_AXES tuple declares, resolving AXIS_*
    constants. Kept for linting OTHER codebases' mesh modules; the
    repo's own default vocabulary now comes from
    :func:`default_mesh_vocabulary` (the plan, not source parsing)."""
    tree = ast.parse(mesh_py_source)
    consts: Dict[str, str] = {}
    vocab: Set[str] = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        target = node.targets[0].id
        if isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[target] = node.value.value
        elif target == "MESH_AXES" and isinstance(node.value, ast.Tuple):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    vocab.add(elt.value)
                elif isinstance(elt, ast.Name) and elt.id in consts:
                    vocab.add(consts[elt.id])
    if not vocab:
        raise ValueError("could not parse MESH_AXES out of parallel/mesh.py "
                         "— the TPU002 vocabulary would be empty")
    return vocab


def default_mesh_vocabulary() -> Set[str]:
    """TPU002's axis vocabulary, read from the ExecutionPlan (the
    ROADMAP #5 fix: the linter used to re-parse parallel/mesh.py
    source, a second source of truth that could silently drift)."""
    from gke_ray_train_tpu.plan import ExecutionPlan
    return set(ExecutionPlan.axis_names())


# ---------------------------------------------------------------------------
# per-module model: imports, function defs, call names
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[List[str]]:
    """Attribute/Name chain → ["np", "random", "normal"]; None if the
    root is a call/subscript (dynamic, unresolvable)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


class _Module:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        # local alias -> dotted module/object path
        self.imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        # every def, with its lexical parent def (None at module level)
        self.defs: List[ast.FunctionDef] = []
        self.parent: Dict[ast.AST, Optional[ast.AST]] = {}
        self._collect_defs(self.tree, None)
        # suppressions: line -> (codes, reason|None)
        self.suppressions: Dict[int, Tuple[Set[str], Optional[str]]] = {}
        for i, raw in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(raw)
            if m:
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                self.suppressions[i] = (codes, m.group(2))

    def _collect_defs(self, node: ast.AST, parent_def) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.append(child)
                self.parent[child] = parent_def
                self._collect_defs(child, child)
            else:
                self._collect_defs(child, parent_def)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Call target → dotted path with the root alias resolved
        ("np.random.normal" → "numpy.random.normal")."""
        parts = _dotted(node)
        if not parts:
            return None
        root = self.imports.get(parts[0], parts[0])
        return ".".join([root] + parts[1:])

    def terminal_name(self, node: ast.AST) -> Optional[str]:
        parts = _dotted(node)
        return parts[-1] if parts else None


# ---------------------------------------------------------------------------
# traced-set computation (project-wide)
# ---------------------------------------------------------------------------

def _fn_args(fn) -> List[str]:
    a = fn.args
    names = [x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _is_trace_transform(mod: _Module, call: ast.Call) -> bool:
    name = mod.terminal_name(call.func)
    return name in TRACE_TRANSFORMS


def _compute_traced(modules: List[_Module]) -> Dict[int, bool]:
    """id(def-node) -> traced, closed over name-matched project calls."""
    by_name: Dict[str, List[Tuple[_Module, ast.AST]]] = {}
    for mod in modules:
        for fn in mod.defs:
            by_name.setdefault(fn.name, []).append((mod, fn))

    traced: Set[int] = set()

    def mark(fn) -> bool:
        if id(fn) in traced:
            return False
        traced.add(id(fn))
        return True

    # seeds: step-named defs, transform operands, transform decorators
    for mod in modules:
        for fn in mod.defs:
            if fn.name in STEP_FN_NAMES:
                mark(fn)
            for dec in fn.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                if mod.terminal_name(d) in TRACE_TRANSFORMS:
                    mark(fn)
        local_defs = {f.name: f for f in mod.defs}
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and _is_trace_transform(mod, node)):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in local_defs:
                    mark(local_defs[arg.id])
                elif isinstance(arg, ast.Lambda):
                    # lambdas are handled positionally during rule
                    # visits (they have no def entry); nothing to mark
                    pass

    # closure: calls from traced bodies pull in same-named defs; nested
    # defs inherit the enclosing fn's tracedness
    changed = True
    while changed:
        changed = False
        for mod in modules:
            for fn in mod.defs:
                parent = mod.parent.get(fn)
                if parent is not None and id(parent) in traced \
                        and id(fn) not in traced:
                    traced.add(id(fn))
                    changed = True
                if id(fn) not in traced:
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = mod.terminal_name(node.func)
                    for m2, f2 in by_name.get(callee, ()):
                        if id(f2) not in traced:
                            traced.add(id(f2))
                            changed = True
    return {i: True for i in traced}


# ---------------------------------------------------------------------------
# rule visitors
# ---------------------------------------------------------------------------

def _subtree_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _lint_module(mod: _Module, traced: Dict[int, bool],
                 vocab: Set[str]) -> List[Finding]:
    raw: List[Finding] = []

    def add(node, code, message):
        raw.append(Finding(mod.path, node.lineno, node.col_offset,
                           code, message))

    # which defs (by containment) each node sits in
    enclosing: Dict[int, List[ast.AST]] = {}

    def fill(node, stack):
        for child in ast.iter_child_nodes(node):
            is_def = isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
            enclosing[id(child)] = stack
            fill(child, stack + [child] if is_def else stack)

    fill(mod.tree, [])

    def in_traced(node) -> Optional[ast.AST]:
        for fn in reversed(enclosing.get(id(node), [])):
            if traced.get(id(fn)):
                return fn
        return None

    # PartitionSpec binding names in this module (TPU002 applies only
    # to names actually bound to jax's PartitionSpec)
    pspec_names = {alias for alias, target in mod.imports.items()
                   if target.endswith(".PartitionSpec")}

    # loop/comprehension targets in scope of a node (for the
    # per-element device_get rule)
    loop_vars: Dict[int, Set[str]] = {}

    def fill_loops(node, vars_):
        for child in ast.iter_child_nodes(node):
            v = vars_
            if isinstance(child, ast.For):
                v = vars_ | _subtree_names(child.target)
            elif isinstance(child, (ast.ListComp, ast.SetComp, ast.DictComp,
                                    ast.GeneratorExp)):
                v = set(vars_)
                for gen in child.generators:
                    v |= _subtree_names(gen.target)
            loop_vars[id(child)] = v
            fill_loops(child, v)

    fill_loops(mod.tree, set())

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        path = mod.resolve(node.func)
        fn = in_traced(node)

        # ---- TPU001: per-element device_get in a loop (anywhere) ----
        if path == "jax.device_get" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) \
                    and arg.id in loop_vars.get(id(node), set()):
                add(node, "TPU001",
                    "per-element jax.device_get inside a loop/"
                    "comprehension — one host round-trip per element; "
                    "batch into a single jax.device_get of the whole "
                    "tree, then index on the host")

        if fn is not None:
            # ---- TPU001: host sync inside traced code ----
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                add(node, "TPU001",
                    ".item() inside a traced function blocks on the "
                    "device — keep metrics device-resident and fetch "
                    "once outside the step")
            elif path in HOST_SYNC_PATHS:
                add(node, "TPU001",
                    f"{path} inside a traced function forces a "
                    "host-device sync (or fails to trace at all) — "
                    "hoist it out of the jit-reachable region")
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int") and node.args:
                arg_names = _subtree_names(node.args[0])
                params = set(_fn_args(fn)) & _ARRAY_PARAM_NAMES
                if arg_names & params:
                    add(node, "TPU001",
                        f"{node.func.id}() of traced array data "
                        "concretizes the tracer (host sync / trace "
                        "error) — use jnp ops, or fetch on the host "
                        "after the step")

            # ---- TPU004: impurity inside traced code ----
            if path is not None and (
                    path in IMPURE_PATHS
                    or path.startswith(IMPURE_PREFIXES)):
                add(node, "TPU004",
                    f"{path} inside a traced function is baked in as "
                    "a compile-time constant (and retraces never "
                    "refresh it) — thread jax.random keys / step "
                    "counters through the function args")

            # ---- TPU005: host-data jnp.array in traced code ----
            if path in ("jax.numpy.array", "jax.numpy.asarray") \
                    and node.args:
                arg = node.args[0]
                host_literal = isinstance(arg, (ast.List, ast.Tuple,
                                                ast.Dict))
                np_call = (isinstance(arg, ast.Call)
                           and (mod.resolve(arg.func) or "")
                           .startswith("numpy."))
                if host_literal or np_call:
                    add(node, "TPU005",
                        "jnp.array of Python/host data inside a traced "
                        "function: a hidden host→device transfer staged "
                        "at every trace, constant-folded into the "
                        "program — build it once outside the jit and "
                        "close over (or pass) the device array")

        # ---- TPU002: PartitionSpec axis vocabulary ----
        term = mod.terminal_name(node.func)
        if (term in pspec_names or (path or "").endswith(".PartitionSpec")):
            def check_axis(e):
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, str) \
                        and e.value not in vocab:
                    add(e, "TPU002",
                        f"PartitionSpec names axis {e.value!r} but the "
                        f"mesh vocabulary (parallel/mesh.py MESH_AXES) "
                        f"is {sorted(vocab)} — an unknown axis silently "
                        "REPLICATES the dimension")
                elif isinstance(e, (ast.Tuple, ast.List)):
                    for sub in e.elts:
                        check_axis(sub)
            for a in node.args:
                check_axis(a)

        # ---- TPU003: step-like jit without donation ----
        if term in ("jit", "pjit") and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                local = {f.name: f for f in mod.defs}
                tfn = local.get(target.id)
                if tfn is not None and _is_step_like(tfn) and not any(
                        kw.arg in ("donate_argnums", "donate_argnames")
                        for kw in node.keywords):
                    add(node, "TPU003",
                        f"jit of step-like {target.id!r} (takes and "
                        "returns a state pytree) without donate_argnums "
                        "— the old params+optimizer buffers stay live "
                        "across the update, doubling peak HBM")

    # decorator form of TPU003: @jax.jit (bare) on a step-like def
    for fn in mod.defs:
        if not _is_step_like(fn):
            continue
        for dec in fn.decorator_list:
            d = dec.func if isinstance(dec, ast.Call) else dec
            if mod.terminal_name(d) in ("jit", "pjit"):
                kws = dec.keywords if isinstance(dec, ast.Call) else []
                if not any(kw.arg in ("donate_argnums", "donate_argnames")
                           for kw in kws):
                    raw.append(Finding(
                        mod.path, fn.lineno, fn.col_offset, "TPU003",
                        f"jitted step-like {fn.name!r} without "
                        "donate_argnums — the old params+optimizer "
                        "buffers stay live across the update, doubling "
                        "peak HBM"))

    # ---- suppression accounting ----
    out: List[Finding] = []
    reasonless_reported: Set[int] = set()
    for f in raw:
        sup = mod.suppressions.get(f.line)
        if sup and f.code in sup[0]:
            if sup[1]:
                continue  # suppressed, with a reason — honored
            if f.line not in reasonless_reported:
                reasonless_reported.add(f.line)
                out.append(Finding(
                    mod.path, f.line, 0, "TPU000",
                    "suppression lacks a reason string — write "
                    "'# shardlint: disable=CODE -- why it is safe'"))
            continue
        out.append(f)
    # a reasonless suppression is a finding even when nothing fired on
    # the line (it would silently swallow future findings)
    for line, (codes, reason) in mod.suppressions.items():
        if not reason and line not in reasonless_reported:
            out.append(Finding(
                mod.path, line, 0, "TPU000",
                "suppression lacks a reason string — write "
                "'# shardlint: disable=CODE -- why it is safe'"))
    return out


def _is_step_like(fn) -> bool:
    """Takes a state pytree (first arg named *state*) and RETURNS one —
    a returned value (or top-level tuple element) that is a *state name
    or a *State(...) constructor. Top-level only: an eval step that
    merely PASSES state into a loss call returns scalars, not a state,
    and needs no donation."""
    args = _fn_args(fn)
    if not args or "state" not in args[0]:
        return False

    def is_statey(e) -> bool:
        if isinstance(e, ast.Name) and "state" in e.id:
            return True
        if isinstance(e, ast.Call):
            parts = _dotted(e.func)
            return bool(parts and "State" in parts[-1])
        return False

    for node in ast.walk(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        cands = (list(node.value.elts)
                 if isinstance(node.value, ast.Tuple) else [node.value])
        if any(is_statey(c) for c in cands):
            return True
    return False


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_sources(sources: Dict[str, str],
                 vocab: Optional[Set[str]] = None) -> List[Finding]:
    """Project-wide lint over {path: source}. The traced set is closed
    over ALL given sources, so cross-module reachability works."""
    if vocab is None:
        vocab = default_mesh_vocabulary()
    modules = []
    findings: List[Finding] = []
    for path, src in sorted(sources.items()):
        try:
            modules.append(_Module(path, src))
        except SyntaxError as e:
            findings.append(Finding(path, e.lineno or 0, 0, "TPU000",
                                    f"unparseable: {e.msg}"))
    traced = _compute_traced(modules)
    for mod in modules:
        findings.extend(_lint_module(mod, traced, vocab))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col))


def lint_source(source: str, path: str = "<string>",
                vocab: Optional[Set[str]] = None) -> List[Finding]:
    return lint_sources({path: source}, vocab=vocab)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f) for f in filenames
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def lint_paths(paths: Iterable[str],
               vocab: Optional[Set[str]] = None) -> List[Finding]:
    sources = {}
    for f in iter_py_files(paths):
        with open(f) as fh:
            sources[f] = fh.read()
    return lint_sources(sources, vocab=vocab)
