"""The shardlint CLI — ``python -m gke_ray_train_tpu.analysis``.

``lint``      AST pass (level 1) over the repo source; exit 1 on findings.
``trace``     print the level-2 compile ledger per preset (informational).
``check``     level-2 assertions per preset (unbudgeted collectives,
              dropped donation, recompiles); exit 1 on findings.
``plancheck`` level-4 static ExecutionPlan verification (plancheck.py):
              topology feasibility, model-dim divisibility, the
              checkpoint-portability matrix, budget/fingerprint
              consistency, KNOWN_KEYS drift; exit 1 on findings.
``kernelcheck`` level-5 kernel verification (kernelcheck.py): static
              grid/VMEM/mesh-contract rules + the jaxpr numerics lint
              (KER001-006), then registry-driven differential sweeps
              of every accelerated op against its oracle vs the pinned
              tolerance ledger (KER100-102); exit 1 on findings.
              ``--record`` / ``TOLERANCE_UPDATE=1`` re-records the
              ledger, ``--static-only`` skips the sweeps.

``trace``/``check`` need the canonical 8-fake-device CPU mesh, so —
like ``perf.budget`` — they re-exec themselves into a child with the
forced-CPU env when not already on it; ``kernelcheck``'s differential
sweeps do the same (its static half runs anywhere). ``lint`` is pure
AST and runs anywhere; ``plancheck`` is pure shape arithmetic +
``jax.eval_shape`` (no backend, no devices — it never probes the
possibly-dead accelerator), so both run on the CI lint runner.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the repo's runtime surface; tests/ are deliberately excluded (their
# fixtures CONTAIN the bad snippets the rules must keep catching)
DEFAULT_LINT_PATHS = ("gke_ray_train_tpu", "ray-jobs", "bench.py",
                      "__graft_entry__.py")


def _lint(paths: List[str]) -> int:
    from gke_ray_train_tpu.analysis.astlint import lint_paths
    paths = paths or [os.path.join(REPO_ROOT, p)
                      for p in DEFAULT_LINT_PATHS
                      if os.path.exists(os.path.join(REPO_ROOT, p))]
    findings = lint_paths(paths)
    for f in findings:
        path = os.path.relpath(f.path, REPO_ROOT) \
            if os.path.isabs(f.path) else f.path
        print(f"{path}:{f.line}:{f.col}: {f.code} {f.message}")
    n = len(findings)
    print(f"shardlint: {n} finding(s)" if n else "shardlint: clean")
    # findings always fail the lint verb; the --fail-on-findings flag
    # exists so the CI step states its contract explicitly
    return 1 if findings else 0


def _preset_names(names: List[str]) -> List[str]:
    from gke_ray_train_tpu.perf.budget import all_preset_names
    return names or all_preset_names()


def _plancheck(paths: List[str], budget_dir: str = None) -> int:
    # plancheck is static: make sure abstract tracing can never probe a
    # (possibly dead) accelerator backend, exactly like the tier-1 env
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from gke_ray_train_tpu.analysis.plancheck import (
        check_paths, default_config_paths)
    paths = paths or default_config_paths(REPO_ROOT)
    findings = check_paths(paths, budget_dir=budget_dir)
    for f in findings:
        print(f"FINDING {f}")
    if findings:
        print(f"plancheck: {len(findings)} finding(s) over "
              f"{len(paths)} config(s)")
        return 1
    import json as _json

    from gke_ray_train_tpu.plan import ExecutionPlan
    for p in paths:
        with open(p) as fh:
            plan = ExecutionPlan.from_config(_json.load(fh))
        print(f"{os.path.relpath(p, REPO_ROOT)}: plan "
              f"{plan.fingerprint()} feasible on {plan.topology}; "
              "portability + budget + KNOWN_KEYS consistent")
    print(f"plancheck: clean ({len(paths)} config(s))")
    return 0


def _reexec_on_cpu_mesh(argv: List[str]) -> int:
    from gke_ray_train_tpu.perf.cache import cpu_mesh_env
    return subprocess.run(
        [sys.executable, "-m", "gke_ray_train_tpu.analysis"] + argv,
        env=cpu_mesh_env(_ANALYSIS_CLI_NATIVE="1"), cwd=REPO_ROOT
    ).returncode


def _kernelcheck(args) -> int:
    from gke_ray_train_tpu.analysis.kernelcheck import main_check
    return main_check(
        args.names or None, static_only=args.static_only,
        diff_only=args.diff_only, record=args.record,
        ledger_dir=args.ledger_dir,
        config_paths=args.configs or None)


def _on_canonical_mesh() -> bool:
    import jax
    return jax.devices()[0].platform == "cpu" and len(jax.devices()) == 8


def _trace(names: List[str]) -> int:
    from gke_ray_train_tpu.analysis.jaxprcheck import trace_preset
    for name in _preset_names(names):
        print(trace_preset(name))
    return 0


def _check(names: List[str]) -> int:
    from gke_ray_train_tpu.analysis.jaxprcheck import check_preset
    from gke_ray_train_tpu.perf.budget import plan_for_preset
    rc = 0
    for name in _preset_names(names):
        findings = check_preset(name)
        for f in findings:
            print(f"FINDING {f}")
        if findings:
            rc = 1
        else:
            # the fingerprint printed here is the SAME ExecutionPlan
            # identity the budget CLI and the budget JSON carry — one
            # plan across trainer, budget check and analysis check
            print(f"{name}: clean (collectives within budget, donation "
                  "held, one compile per fn; plan "
                  f"{plan_for_preset(name).fingerprint()})")
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m gke_ray_train_tpu.analysis",
        description="shardlint: sharding & host-sync static analysis "
                    "(AST lint / trace-level analyzers, CPU-only)")
    sub = parser.add_subparsers(dest="command", required=True)
    p_lint = sub.add_parser("lint", help="AST rules TPU001-TPU005")
    p_lint.add_argument("paths", nargs="*",
                        help="files/dirs (default: the repo's runtime "
                             "surface, tests excluded)")
    p_lint.add_argument("--fail-on-findings", action="store_true",
                        help="exit 1 on any finding (also the default)")
    p_trace = sub.add_parser(
        "trace", help="print the compile-level ledger per preset")
    p_trace.add_argument("names", nargs="*")
    p_check = sub.add_parser(
        "check", help="assert collectives/donation/compile-once per preset")
    p_check.add_argument("names", nargs="*")
    p_plan = sub.add_parser(
        "plancheck",
        help="statically verify ExecutionPlans: feasibility, "
             "portability matrix, budget/fingerprint + KNOWN_KEYS "
             "consistency (no backend needed)")
    p_plan.add_argument("configs", nargs="*",
                        help="config JSONs (default: the shipped "
                             "ray-jobs/fine_tune_config*.json presets)")
    p_plan.add_argument("--budget-dir", default=None,
                        help="budget directory (default tests/budgets)")
    p_ker = sub.add_parser(
        "kernelcheck",
        help="level 5: static kernel rules (KER001-006) + differential "
             "kernel-vs-oracle sweeps against the tolerance ledger "
             "(KER100-102)")
    p_ker.add_argument("names", nargs="*",
                       help="registered kernels (default: all)")
    p_ker.add_argument("--static-only", action="store_true",
                       help="KER001-006 only (no devices needed)")
    p_ker.add_argument("--diff-only", action="store_true",
                       help="differential sweeps only")
    p_ker.add_argument("--record", action="store_true",
                       help="re-record tests/tolerances/*.json "
                            "(same as TOLERANCE_UPDATE=1)")
    p_ker.add_argument("--ledger-dir", default=None,
                       help="tolerance directory (default "
                            "tests/tolerances)")
    p_ker.add_argument("--configs", nargs="*", default=None,
                       help="config JSONs for the static rules "
                            "(default: the shipped presets)")
    args = parser.parse_args(argv)

    if args.command == "lint":
        return _lint(args.paths)
    if args.command == "plancheck":
        return _plancheck(args.configs, args.budget_dir)
    if args.command == "kernelcheck" and args.static_only:
        return _kernelcheck(args)   # pure arithmetic + jaxpr tracing
    if os.environ.get("_ANALYSIS_CLI_NATIVE") != "1" \
            and not _on_canonical_mesh():
        argv_out = [args.command] + args.names
        if args.command == "kernelcheck":
            argv_out += (["--diff-only"] if args.diff_only else []) \
                + (["--record"] if args.record else []) \
                + (["--ledger-dir", args.ledger_dir]
                   if args.ledger_dir else []) \
                + (["--configs"] + args.configs if args.configs else [])
        return _reexec_on_cpu_mesh(argv_out)
    if args.command == "kernelcheck":
        return _kernelcheck(args)
    return _trace(args.names) if args.command == "trace" \
        else _check(args.names)


if __name__ == "__main__":
    sys.exit(main())
