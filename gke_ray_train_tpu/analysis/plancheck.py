"""shardlint level 4 — static ExecutionPlan verification (plancheck).

PR 5 proved accelerator-free analysis catches real defects in *code*;
this level applies the same discipline to *configuration*: every
shipped config resolves to one :class:`~gke_ray_train_tpu.plan
.ExecutionPlan`, and plancheck proves — with shape/divisibility
arithmetic and ``jax.eval_shape``, no backend, no hardware — that the
plan is runnable, portable, and consistent with every artifact that
claims to describe it:

========  ===========================================================
rule      what it proves
========  ===========================================================
PLAN000   the config parses and every plan field validates
PLAN001   topology feasibility: every mesh axis size tiles the chip
          count of the declared v5e/v5p/cpu topology preset
PLAN002   model-dim divisibility: every sharded dim (embed, heads,
          mlp hidden, vocab, stacked-layer/pipe) divides the product
          of the mesh axes its logical PartitionSpec names — via
          ``jax.eval_shape`` over the real ``init_params``
PLAN003   checkpoint portability: for each (save, restore) pair of
          the fake-device topologies (cpu-4/8/16) the reshard-on-
          restore path in ``ckpt/manager.py`` is well-formed — the
          state's logical spec re-derives valid shardings on the
          restore mesh (the static half of elastic resume, ROADMAP #1)
PLAN004   cross-artifact identity: the ``tests/budgets/*.json``
          preset a plan pins was recorded under that preset plan's
          fingerprint (a stale budget is a lint failure, not a
          silently-wrong gate); AOT sidecar keys embed the same
          fingerprint by construction (``perf/cache.py``)
PLAN005   dialect drift: every ExecutionPlan config key is in
          ``config.py`` KNOWN_KEYS *and* declared PLAN_SCOPED, and
          every PLAN_SCOPED key maps back to a plan field — a renamed
          knob fails lint instead of being silently ignored
========  ===========================================================

Portability semantics (PLAN003): the *structural* axes (model,
context, pipe — they change the compiled program and the logical
layout) are kept; the data-parallel axes (data, fsdp) reflow to fill
whatever chip count the restore pool offers, exactly how elastic
resume re-derives shardings from the logical spec rather than the
saved layout.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Mapping, Optional, Tuple

from gke_ray_train_tpu.plan import (
    CONFIG_KEYS, COMPILE_RELEVANT_FIELDS, ExecutionPlan, PlanError)

RULES = {
    "PLAN000": "config unparseable or plan field invalid",
    "PLAN001": "mesh axes cannot tile the declared topology",
    "PLAN002": "sharded model dim does not divide its mesh axes",
    "PLAN003": "save/restore topology pair has no valid reshard",
    "PLAN004": "budget preset / plan fingerprint mismatch",
    "PLAN005": "ExecutionPlan <-> KNOWN_KEYS drift",
}

# a smoke config trains the deterministic tiny model (the entry sizes
# vocab to the tokenizer, >= 260) — plancheck uses the same floor so
# divisibility verdicts match what the smoke run would compile
_SMOKE_VOCAB = 260


@dataclasses.dataclass(frozen=True)
class PlanFinding:
    rule: str
    field: str          # the offending field/key/pair, for the report
    message: str
    config: str = ""    # config path or label

    def __str__(self) -> str:
        where = f"{self.config}: " if self.config else ""
        return f"{where}{self.rule} [{self.field}] {self.message}"


# ---------------------------------------------------------------------------
# model resolution (static: no weights, no tokenizer, no hub)
# ---------------------------------------------------------------------------

def model_config_for(config: Mapping[str, Any], plan: ExecutionPlan):
    """The ModelConfig a config would train, derived statically. Returns
    None when the config names no model (plain mesh-only checks apply)."""
    from gke_ray_train_tpu.models.config import preset_for_model_id, tiny
    if config.get("SMOKE_TEST"):
        # the smoke entry sizes depth to the RESOLVED pipe axis — a
        # declared -1 (fill) must resolve the same way here, or a
        # correct config draws a false divisibility finding
        try:
            pipe_depth = plan.resolved_sizes()["pipe"]
        except ValueError:
            pipe_depth = max(plan.pipe, 1)
        return tiny(vocab_size=_SMOKE_VOCAB, max_seq_len=plan.max_seq_len,
                    n_layers=max(2, pipe_depth * plan.pipe_virtual_stages))
    model_id = config.get("MODEL_ID")
    if model_id:
        return preset_for_model_id(str(model_id))
    return None


# ---------------------------------------------------------------------------
# per-rule checks
# ---------------------------------------------------------------------------

def feasibility_findings(plan: ExecutionPlan, model_cfg=None,
                         label: str = "") -> List[PlanFinding]:
    """PLAN001 + PLAN002 on the plan's declared topology."""
    out: List[PlanFinding] = []
    for msg in plan.mesh_findings():
        out.append(PlanFinding("PLAN001", "MESH_*", msg, label))
    if out or model_cfg is None:
        return out
    for msg in plan.model_findings(model_cfg):
        field = ("MAX_SEQ_LENGTH" if "max_seq_len" in msg else
                 "MESH_MODEL" if "n_heads" in msg or "n_kv_heads" in msg
                 else "MESH_*")
        out.append(PlanFinding("PLAN002", field, msg, label))
    return out


def _portability_plan(plan: ExecutionPlan) -> ExecutionPlan:
    """The reshard dialect: structural axes kept, dp axes reflowed."""
    return dataclasses.replace(plan, data=1, fsdp=-1, num_slices=1)


def portability_chip_counts(plan: ExecutionPlan) -> Dict[str, int]:
    """The fake-device topologies a plan's checkpoints must port
    across: HALF, the declared count, and DOUBLE — the elastic-resume
    contract (ROADMAP #1: a 16-chip job degrades to 8 and recovers).
    Scaled to the declared topology so a legitimately large
    tensor-parallel plan is not judged against a 4-chip toy it will
    never restore on (for the canonical cpu-8 plan this is exactly
    fake-4/8/16)."""
    n = plan.chips
    return {f"fake-{c}": c
            for c in sorted({max(n // 2, 1), n, n * 2})}


def portability_findings(plan: ExecutionPlan, model_cfg=None,
                         topologies: Optional[Mapping[str, int]] = None,
                         label: str = "") -> List[PlanFinding]:
    """PLAN003: the checkpoint-portability matrix. For each (save,
    restore) topology pair, the restore side must re-derive valid
    shardings from the SAME logical spec (shapes are topology-free by
    construction — ``ckpt/manager.py::restore`` honors the target
    template's shardings, so static validity of the template IS the
    well-formedness of the reshard path)."""
    port = _portability_plan(plan)
    if topologies is None:
        topologies = portability_chip_counts(plan)
    verdicts: Dict[str, List[str]] = {}
    for topo, chips in topologies.items():
        verdicts[topo] = port.feasibility(model_cfg, chips)
    out: List[PlanFinding] = []
    for save in topologies:
        if verdicts[save]:
            continue                       # nothing savable to port
        for restore in topologies:
            if restore == save or not verdicts[restore]:
                continue
            out.append(PlanFinding(
                "PLAN003", f"{save}->{restore}",
                f"checkpoint saved on {save} has no valid reshard onto "
                f"{restore}: {verdicts[restore][0]}", label))
    return out


def budget_findings(plan: ExecutionPlan, *,
                    budget_dir: Optional[str] = None,
                    label: str = "") -> List[PlanFinding]:
    """PLAN004 for one plan: its pinned budget preset exists, is
    recorded, and was recorded under the preset plan's fingerprint."""
    if plan.budget_preset is None:
        return []
    from gke_ray_train_tpu.perf.budget import (
        PRESETS, SERVE_PRESETS, all_preset_names, budget_path,
        load_budget, plan_for_preset)
    name = plan.budget_preset
    if name not in PRESETS and name not in SERVE_PRESETS:
        return [PlanFinding(
            "PLAN004", "BUDGET_PRESET",
            f"unknown budget preset {name!r}; known: "
            f"{all_preset_names()}", label)]
    path = budget_path(name, budget_dir)
    if not os.path.exists(path):
        return [PlanFinding(
            "PLAN004", "BUDGET_PRESET",
            f"no recorded budget at {path} — run: python -m "
            "gke_ray_train_tpu.perf.budget record", label)]
    doc = load_budget(path)
    preset_plan = plan_for_preset(name)
    want = preset_plan.fingerprint()
    have = doc.get("_plan_fingerprint")
    if have != want:
        return [PlanFinding(
            "PLAN004", "BUDGET_PRESET",
            f"budget {path} was recorded under plan {have or '<none>'} "
            f"but preset {name!r} now resolves to plan {want} — stale "
            "budget; re-record and review the diff", label)]
    # the pinned budget only describes THIS run if the compile-relevant
    # plan fields agree — comparing a differently-meshed/batched step
    # against it would report drift that is really apples-to-oranges.
    # Mesh axes compare RESOLVED (a -1 fill that lands on the preset's
    # size is the same compiled program, not a mismatch).
    from gke_ray_train_tpu.plan import CHIP_COUNTS
    mesh_axes = ("data", "fsdp", "model", "context", "pipe")
    try:
        run_sizes = plan.resolved_sizes(CHIP_COUNTS[preset_plan.topology])
    except ValueError as e:
        return [PlanFinding(
            "PLAN004", "BUDGET_PRESET",
            f"plan pins budget preset {name!r} but cannot tile its "
            f"canonical {preset_plan.topology} mesh: {e}", label)]
    want_sizes = preset_plan.resolved_sizes()
    diff = {a: (run_sizes[a], want_sizes[a]) for a in mesh_axes
            if run_sizes[a] != want_sizes[a]}
    diff.update({f: (getattr(plan, f), getattr(preset_plan, f))
                 for f in COMPILE_RELEVANT_FIELDS if f not in mesh_axes
                 and getattr(plan, f) != getattr(preset_plan, f)})
    if diff:
        detail = ", ".join(f"{f}: {a} vs preset {b}"
                           for f, (a, b) in sorted(diff.items()))
        return [PlanFinding(
            "PLAN004", "BUDGET_PRESET",
            f"plan {plan.fingerprint()} pins budget preset {name!r} "
            f"(plan {want}) but differs on compile-relevant fields "
            f"({detail}) — the budget cannot describe this step", label)]
    return []


def repo_budget_findings(budget_dir: Optional[str] = None
                         ) -> List[PlanFinding]:
    """PLAN004, repo level: every checked-in budget JSON matches the
    fingerprint of the preset plan that would re-record it."""
    from gke_ray_train_tpu.perf.budget import (
        BUDGET_DIR, all_preset_names, budget_path, load_budget,
        plan_for_preset)
    out: List[PlanFinding] = []
    bdir = budget_dir or BUDGET_DIR
    for name in all_preset_names():
        path = budget_path(name, bdir)
        if not os.path.exists(path):
            continue   # unrecorded presets are perf.budget's business
        doc = load_budget(path)
        want = plan_for_preset(name).fingerprint()
        have = doc.get("_plan_fingerprint")
        if have != want:
            out.append(PlanFinding(
                "PLAN004", name,
                f"budget {path} records plan {have or '<none>'} but "
                f"preset {name!r} resolves to plan {want} — stale "
                "budget (re-record and review the diff like code)",
                "tests/budgets"))
    return out


def drift_findings() -> List[PlanFinding]:
    """PLAN005: the plan's config-key mapping, config.py KNOWN_KEYS and
    the PLAN_SCOPED_KEYS declaration agree in both directions."""
    from gke_ray_train_tpu.config import KNOWN_KEYS, PLAN_SCOPED_KEYS
    plan_keys = set(CONFIG_KEYS.values())
    out: List[PlanFinding] = []
    for key in sorted(plan_keys - set(KNOWN_KEYS)):
        out.append(PlanFinding(
            "PLAN005", key,
            "ExecutionPlan maps a field to this config key but "
            "config.py KNOWN_KEYS does not list it — the key would be "
            "warned about as unknown and silently ignored", "config.py"))
    for key in sorted(plan_keys - set(PLAN_SCOPED_KEYS)):
        out.append(PlanFinding(
            "PLAN005", key,
            "ExecutionPlan owns this config key but config.py does not "
            "declare it PLAN_SCOPED — add it to PLAN_SCOPED_KEYS",
            "config.py"))
    for key in sorted(set(PLAN_SCOPED_KEYS) - plan_keys):
        out.append(PlanFinding(
            "PLAN005", key,
            "config.py declares this key plan-scoped but no "
            "ExecutionPlan field maps to it — the plan and the config "
            "surface have diverged", "config.py"))
    return out


# ---------------------------------------------------------------------------
# whole-config / whole-repo entry points
# ---------------------------------------------------------------------------

def check_config(config: Mapping[str, Any], *, label: str = "",
                 budget_dir: Optional[str] = None) -> List[PlanFinding]:
    """All per-config findings (PLAN000-PLAN004) for one flat config."""
    try:
        plan = ExecutionPlan.from_config(config)
    except PlanError as e:
        return [PlanFinding("PLAN000", "plan", str(e), label)]
    try:
        model_cfg = model_config_for(config, plan)
    except ValueError as e:
        return [PlanFinding("PLAN000", "MODEL_ID", str(e), label)]
    out = feasibility_findings(plan, model_cfg, label=label)
    out.extend(portability_findings(plan, model_cfg, label=label))
    out.extend(budget_findings(plan, budget_dir=budget_dir, label=label))
    return out


def check_config_file(path: str, *, budget_dir: Optional[str] = None
                      ) -> List[PlanFinding]:
    label = os.path.relpath(path)
    try:
        with open(path) as f:
            config = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [PlanFinding("PLAN000", "config",
                            f"unreadable config: {e}", label)]
    return check_config(config, label=label, budget_dir=budget_dir)


def default_config_paths(repo_root: str) -> List[str]:
    """The shipped configs plancheck gates: every fine-tune preset JSON
    (they declare their v5e/v5p topology via the TOPOLOGY key)."""
    import glob
    return sorted(glob.glob(os.path.join(
        repo_root, "ray-jobs", "fine_tune_config*.json")))


def check_paths(paths: List[str], *, budget_dir: Optional[str] = None
                ) -> List[PlanFinding]:
    """The CLI body: per-config checks plus the repo-level consistency
    rules (budget fingerprints, KNOWN_KEYS drift) that hold regardless
    of which config is being trained."""
    findings: List[PlanFinding] = []
    for p in paths:
        findings.extend(check_config_file(p, budget_dir=budget_dir))
    findings.extend(repo_budget_findings(budget_dir))
    findings.extend(drift_findings())
    return findings
