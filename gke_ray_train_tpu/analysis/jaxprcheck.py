"""shardlint level 2 — trace/compile-level analyzers (no accelerator).

Everything here runs on the 8-fake-device CPU mesh CI uses (the same
re-exec recipe as ``perf.budget``): the presets' real step functions go
through ``jit(...).lower(...).compile()`` and three properties are
asserted off XLA's own compile-time ledger:

- **No unintended reshard**: collectives in the optimized HLO beyond
  the counts the checked-in budget (``tests/budgets/*.json``) allows —
  an extra all-gather in the grad path is the GSPMD signature of a
  ``PartitionSpec`` typo silently replicating an operand. Composes
  with ``perf/budget.py`` (the budget is the "intended collective
  set") instead of duplicating its comparator.
- **Donation actually held**: ``memory_analysis`` alias bytes must
  cover the state (``perf.costs.assert_state_donation``); when XLA
  drops a donation the finding names the alias shortfall and the
  aliasing the module header DID keep.
- **Compile-once**: :class:`RecompileDetector` counts compiles per
  function (a ``jax.monitoring`` hook counts backend compiles; the
  ``jax_log_compiles`` stream supplies the per-function signature) and
  reports any function compiled more than once WITH the
  shape/dtype/sharding diff that caused it.
"""

from __future__ import annotations

import difflib
import logging
import re
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# the jax_log_compiles line pxla emits per compile:
#   Compiling <name> with global shapes and types [ShapedArray(...)].
#   Argument mapping: (<shardings>).
_COMPILE_LOG_RE = re.compile(
    r"Compiling ([^\s]+) with global shapes and types "
    r"(\[.*?\])\. Argument mapping: (\(.*\))", re.DOTALL)
_PXLA_LOGGER = "jax._src.interpreters.pxla"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_PRIMITIVE_NAMES: Optional[frozenset] = None


def _primitive_names() -> frozenset:
    """Names of jax's registered primitives. The apply-primitive path
    wraps single ops in jits NAMED AFTER the primitive
    (``broadcast_in_dim``, ``convert_element_type``, ...) and recompiles
    them per static shape with an identical-looking signature — op-level
    noise, not the step-fn churn the detector exists for, and it must
    never trip the RECOMPILE_LIMIT hard error."""
    global _PRIMITIVE_NAMES
    if _PRIMITIVE_NAMES is None:
        names = set()
        try:
            from jax._src.interpreters import mlir
            names = {p.name for p in mlir._lowerings}
        except Exception as e:  # noqa: BLE001 - private API drift
            logger.warning("primitive registry unavailable (%s); "
                           "op-level compile noise may be attributed "
                           "to user functions", e)
        _PRIMITIVE_NAMES = frozenset(names)
    return _PRIMITIVE_NAMES


class RecompileDetector:
    """Counts compiles per function signature while active.

    Two sources, cross-checked: a ``jax.monitoring`` duration hook
    counts every backend compile (no names attached), and the
    ``jax_log_compiles`` log stream attributes each compile to a
    function name + abstract signature + sharding mapping. ``report()``
    returns every function compiled more than once, with the diff
    between consecutive signatures — the shape/dtype/sharding churn
    that caused the retrace.

    ``on_compile_over``: callback fired (name, signatures) the moment
    one function exceeds ``over_count`` compiles — the hard-error hook
    ``analysis.guards.install_recompile_limit`` uses. Exceptions it
    raises propagate out of the offending compile call by design.

    Caveat: attribution rides the log stream, so a global
    ``logging.disable(WARNING)`` (or raising the pxla logger past
    WARNING) blinds the detector — the backend-compile monitoring
    counter still ticks, the per-function table does not.
    """

    def __init__(self, on_compile_over: Optional[Callable] = None,
                 over_count: Optional[int] = None):
        self.compiles: Dict[str, List[str]] = {}
        self.backend_compiles = 0
        self._on_over = on_compile_over
        self._over = over_count
        self._handler: Optional[logging.Handler] = None
        self._prev_flag: Optional[bool] = None
        self._dur_listener = None

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "RecompileDetector":
        import jax
        detector = self

        class _Handler(logging.Handler):
            def emit(self, record):
                detector._on_log(record.getMessage())

        self._handler = _Handler(level=logging.DEBUG)
        self._prev_flag = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        logging.getLogger(_PXLA_LOGGER).addHandler(self._handler)
        try:
            from jax._src import monitoring

            def on_duration(event, duration, **kw):
                if event == _BACKEND_COMPILE_EVENT:
                    detector.backend_compiles += 1

            self._dur_listener = on_duration
            monitoring.register_event_duration_secs_listener(on_duration)
        except Exception as e:  # noqa: BLE001 - counters stay log-only
            logger.warning("jax.monitoring unavailable (%s); backend "
                           "compile counter disabled", e)
        return self

    def stop(self) -> None:
        import jax
        if self._handler is not None:
            logging.getLogger(_PXLA_LOGGER).removeHandler(self._handler)
            self._handler = None
        if self._prev_flag is not None:
            jax.config.update("jax_log_compiles", self._prev_flag)
            self._prev_flag = None
        if self._dur_listener is not None:
            try:
                from jax._src import monitoring
                monitoring._unregister_event_duration_listener_by_callback(
                    self._dur_listener)
            except Exception:  # noqa: BLE001 - private API; leak one noop
                pass
            self._dur_listener = None

    def __enter__(self) -> "RecompileDetector":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- accounting ---------------------------------------------------
    def _on_log(self, message: str) -> None:
        m = _COMPILE_LOG_RE.search(message)
        if not m:
            return
        name, avals, mapping = m.groups()
        if name in _primitive_names():
            return
        sigs = self.compiles.setdefault(name, [])
        sigs.append(f"shapes {avals} shardings {mapping}")
        if self._on_over is not None and self._over is not None \
                and len(sigs) > self._over:
            self._on_over(name, list(sigs))

    def recompiled(self) -> Dict[str, List[str]]:
        """name -> signatures, for every fn compiled more than once."""
        return {k: v for k, v in self.compiles.items() if len(v) > 1}

    @staticmethod
    def describe_churn(sigs: List[str], cap: int = 12) -> str:
        """Unified diff between consecutive signatures — the concrete
        shape/dtype/sharding change that caused each retrace."""
        out: List[str] = []
        for i in range(1, len(sigs)):
            if sigs[i - 1] == sigs[i]:
                out.append(f"compile {i} -> {i + 1}: identical visible "
                           "signature (static-arg or weak-type churn, "
                           "or a trace-cache miss)")
                continue
            delta = [ln for ln in difflib.ndiff([sigs[i - 1]], [sigs[i]])
                     if ln[:1] in "+-?"]
            out.append(f"compile {i} -> {i + 1}:")
            out.extend("  " + ln for ln in delta[:cap])
        return "\n".join(out)

    def findings(self) -> List[str]:
        out = []
        for name, sigs in sorted(self.recompiled().items()):
            out.append(
                f"{name!r} compiled {len(sigs)} times — a step fn must "
                "compile once; signature churn:\n"
                + self.describe_churn(sigs))
        return out


# ---------------------------------------------------------------------------
# collective / donation analyzers (compose with perf.budget's ledger)
# ---------------------------------------------------------------------------

def unbudgeted_collectives(report: Any, budget: Dict[str, Any]) -> List[str]:
    """Collectives beyond what the checked-in budget sanctions. One-
    sided by design: EXTRA collectives are the reshard/replication
    signal; "fewer than budget" is the budget comparator's own
    (two-sided) business."""
    from gke_ray_train_tpu.perf.budget import _hlo_delta
    from gke_ray_train_tpu.perf.costs import COLLECTIVE_KINDS
    if hasattr(report, "to_dict"):
        report = report.to_dict()
    want = budget.get("collective_counts") or {}
    have = report.get("collective_counts") or {}
    out: List[str] = []
    extra = [k for k in COLLECTIVE_KINDS
             if int(have.get(k, 0)) > int(want.get(k, 0))]
    if extra:
        detail = ", ".join(f"{k}: {have.get(k, 0)} vs budgeted "
                           f"{want.get(k, 0)}" for k in extra)
        lines = _hlo_delta(report.get("collective_lines", []),
                           budget.get("collective_lines", []))
        out.append(
            f"collectives beyond the budgeted set ({detail}) — an "
            "unbudgeted all-gather/all-reduce usually means a sharded "
            "operand is being RESHARDED to replicated (PartitionSpec "
            "typo or missing constraint)\n" + "\n".join(lines))
    return out


def unbudgeted_dcn_bytes(report: Any, budget: Dict[str, Any],
                         *, headroom: float = 0.10) -> List[str]:
    """Cross-slice (DCN) bytes beyond what the checked-in budget
    sanctions. One-sided like :func:`unbudgeted_collectives` — EXTRA
    bytes over the slow inter-slice link are the reshard signal (a
    PartitionSpec change that re-replicates an operand silently turns
    an intra-slice gather into a slice-spanning one); *fewer* DCN
    bytes is the two-sided comparator's business. The finding carries
    the per-op slice-crossing delta so the fattened hop is named."""
    from gke_ray_train_tpu.perf.budget import _hlo_delta
    if hasattr(report, "to_dict"):
        report = report.to_dict()
    want = budget.get("dcn_bytes")
    if want is None:        # pre-DCN budget: nothing to gate against
        return []
    have = int(report.get("dcn_bytes", 0))
    if have <= int(want) * (1.0 + headroom):
        return []
    lines = _hlo_delta(report.get("dcn_lines", []),
                       budget.get("dcn_lines", []))
    return [
        f"cross-slice DCN bytes beyond the budgeted set ({have} vs "
        f"budgeted {want}, headroom {headroom:.0%}) — a reshard is "
        "fattening the slice-spanning hop (full-payload traffic the "
        "hierarchical sync exists to avoid crossing DCN)\n"
        + "\n".join(lines)]


def donation_findings(compiled, state: Any, *, min_frac: float = 0.8,
                      label: str = "train_step") -> List[str]:
    """Did the declared donation actually hold? ``memory_analysis``
    alias bytes must cover ≥ min_frac of the per-device state bytes;
    a shortfall names the gap (XLA drops donations whose layouts or
    liveness don't line up — silently, unless asked to warn)."""
    from gke_ray_train_tpu.perf.costs import assert_state_donation
    try:
        assert_state_donation(compiled, state, min_frac=min_frac)
        return []
    except AssertionError as e:
        kept = "none"
        try:
            header = compiled.as_text().splitlines()[0]
            m = re.search(r"input_output_alias=\{([^}]*(?:\{[^}]*\}[^}]*)*)\}",
                          header)
            if m:
                kept = f"only {m.group(1).count('(')} aliased buffers"
        except Exception:  # noqa: BLE001 - diagnostics are best-effort
            pass
        return [f"{label}: {e} (module header kept {kept})"]


# ---------------------------------------------------------------------------
# preset-level check/trace (the CLI's `check` and `trace` verbs)
# ---------------------------------------------------------------------------

def check_serve_preset(name: str, *, budget_dir: Optional[str] = None
                       ) -> List[str]:
    """Level-2 findings for a serving-decode preset (serve/engine.py):
    the decode step must stay within its checked-in budget (any
    collective showing up in the mesh-local decode is a reshard bug),
    its KV-pool donation must hold, and a second same-signature decode
    dispatch must be a trace-cache hit — the continuous-batching loop
    runs it thousands of times per request stream."""
    import os

    import jax

    from gke_ray_train_tpu.perf.budget import (
        budget_path, build_serve_preset_step, load_budget)
    from gke_ray_train_tpu.perf.costs import step_cost_report

    findings: List[str] = []
    compiled, params, state, jitted, lora_arg = build_serve_preset_step(
        name, with_jitted=True)

    report = step_cost_report(compiled)
    bpath = budget_path(name, budget_dir)
    if os.path.exists(bpath):
        findings.extend(unbudgeted_collectives(report, load_budget(bpath)))
    else:
        logger.warning("no budget at %s; collective check skipped "
                       "(run: python -m gke_ray_train_tpu.perf.budget "
                       "record)", bpath)

    # the serve state (dominated by the [max_batch, bucket] KV pool) is
    # donated through every decode iteration — a dropped donation
    # doubles the pool's footprint at exactly max_batch scale
    findings.extend(donation_findings(compiled, state,
                                      label=f"{name} decode_step"))

    with RecompileDetector() as det:
        state1 = jax.block_until_ready(jitted(params, state, lora_arg))
        jax.block_until_ready(jitted(params, state1, lora_arg))
    findings.extend(det.findings())
    return [f"{name}: {f}" for f in findings]


def check_preset(name: str, *, budget_dir: Optional[str] = None
                 ) -> List[str]:
    """All level-2 findings for one perf.budget preset: unbudgeted
    collectives, dropped donation, and any recompile on a second
    same-signature step call. Serve presets route to
    :func:`check_serve_preset`."""
    import os

    import jax

    from gke_ray_train_tpu.perf.budget import (
        SERVE_PRESETS, budget_path, build_preset_step, load_budget)
    from gke_ray_train_tpu.perf.costs import step_cost_report

    if name in SERVE_PRESETS:
        return check_serve_preset(name, budget_dir=budget_dir)

    findings: List[str] = []

    # one undonated build serves BOTH the collective check (donate=False
    # matches the recorded budget baseline exactly) and the compile-once
    # probe below — a preset build is a full model+state construction
    # plus an XLA compile, not something to repeat for free
    compiled, state, batch, jitted = build_preset_step(name,
                                                       with_jitted=True)

    # 1) collectives vs the checked-in budget (the DCN attribution runs
    #    against the preset's declared slice layout) — plus the
    #    one-sided cross-slice byte rule: a reshard that fattens the
    #    DCN hop fails `analysis check` even inside the two-sided
    #    comparator's tolerance band
    from gke_ray_train_tpu.perf.budget import PRESETS
    preset = PRESETS[name]
    report = step_cost_report(compiled, num_slices=preset.num_slices)
    bpath = budget_path(name, budget_dir)
    if os.path.exists(bpath):
        budget = load_budget(bpath)
        findings.extend(unbudgeted_collectives(report, budget))
        findings.extend(unbudgeted_dcn_bytes(report, budget))
    else:
        logger.warning("no budget at %s; collective check skipped "
                       "(run: python -m gke_ray_train_tpu.perf.budget "
                       "record)", bpath)

    # 2) donation holds on the donated build
    donated, state_d, _ = build_preset_step(name, donate=True)
    findings.extend(donation_findings(donated, state_d,
                                      label=f"{name} train_step"))

    # 3) compile-once: dispatch the JITTED step twice with identical
    #    signatures — the second call must be a trace-cache hit
    #    (donate=False so the same placed batch is reusable)
    with RecompileDetector() as det:
        state1, _ = jax.block_until_ready(jitted(state, batch))
        jax.block_until_ready(jitted(state1, batch))
    findings.extend(det.findings())
    return [f"{name}: {f}" for f in findings]


def trace_preset(name: str) -> str:
    """Human-readable level-2 report for one preset (the CLI `trace`
    verb): the cost ledger + donation + collective census."""
    from gke_ray_train_tpu.perf.budget import (
        SERVE_PRESETS, build_preset_step, build_serve_preset_step)
    from gke_ray_train_tpu.perf.costs import step_cost_report

    if name in SERVE_PRESETS:
        compiled, _, state = build_serve_preset_step(name)
        label = "decode_step"
    else:
        compiled, state, _ = build_preset_step(name, donate=True)
        label = "train_step"
    report = step_cost_report(compiled)
    lines = [f"== {name} =="]
    for k, v in sorted(report.summary().items()):
        lines.append(f"  {k}: {v}")
    don = donation_findings(compiled, state, label=label)
    lines.append("  donation: " + (don[0] if don else "held"))
    for hlo in report.collective_lines:
        lines.append(f"  HLO {hlo}")
    return "\n".join(lines)
