"""shardlint — five-level sharding, host-sync & kernel analysis.

Level 1 (:mod:`analysis.astlint`): AST rules TPU001–TPU005 over the
repo's own source — host-syncs in jit-reachable code, PartitionSpec
axis typos, undonated step fns, impure traced code, host-data
constants — with reasoned inline suppressions.

Level 2 (:mod:`analysis.jaxprcheck`): the presets' real step functions
lowered/compiled on the 8-fake-device CPU mesh and checked against
XLA's own ledger — no unbudgeted reshard collectives, donation held,
one compile per function (with the signature diff when not).

Level 3 (:mod:`analysis.guards`): opt-in production teeth —
``TRANSFER_GUARD`` wraps the hot loop, ``RECOMPILE_LIMIT`` makes
retrace churn a hard error, ``DIVERGENCE_GUARD`` fails fast (with a
per-host diff) when multi-host step programs diverge.

Level 4 (:mod:`analysis.plancheck`): static ExecutionPlan
verification — topology feasibility and model-dim divisibility by
pure shape arithmetic + ``jax.eval_shape``, the checkpoint-portability
matrix across fake-device topologies, and cross-artifact consistency
(budget fingerprints, KNOWN_KEYS drift). No backend, no hardware.

Level 5 (:mod:`analysis.kernelcheck` over :mod:`ops.registry`):
kernel verification — static grid/VMEM/mesh-contract rules and a jaxpr
numerics lint (KER001–006, recursing into ``pallas_call`` bodies),
plus registry-driven differential value+grad sweeps of every
accelerated op against its reference oracle, gated by the checked-in
tolerance ledger (``tests/tolerances/*.json``, two-sided: KER100–102).

CLI: ``python -m gke_ray_train_tpu.analysis
lint|trace|check|plancheck|kernelcheck``.
"""

from gke_ray_train_tpu.analysis.astlint import (  # noqa: F401
    Finding, RULES, lint_paths, lint_source, lint_sources)
from gke_ray_train_tpu.analysis.jaxprcheck import (  # noqa: F401
    RecompileDetector, check_preset, donation_findings, trace_preset,
    unbudgeted_collectives)
from gke_ray_train_tpu.analysis.guards import (  # noqa: F401
    GuardViolation, HloDivergenceError, RecompileLimitExceeded,
    RuntimeGuards, allow_transfers, check_host_hlo_agreement,
    install_recompile_limit, uninstall_recompile_limit)
from gke_ray_train_tpu.analysis.plancheck import (  # noqa: F401
    PlanFinding, check_config, check_config_file, check_paths,
    drift_findings, feasibility_findings, portability_findings)
from gke_ray_train_tpu.analysis.kernelcheck import (  # noqa: F401
    CaseResult, KernelCheckError, KernelFinding, kernel_constraint_findings,
    ledger_findings, lint_traced_fn, numerics_findings, quick_verify,
    run_case, sweep)
