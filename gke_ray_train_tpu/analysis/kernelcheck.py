"""shardlint level 5 — kernelcheck: differential kernel verification,
numerics lint, and static kernel/mesh constraints.

The accelerated ops (Pallas flash attention, ring/a2a context
parallelism, NF4/int8 quantization, MoE dispatch, RoPE, the KV-cache
admit path) were each verified by hand-rolled per-test oracles; nothing
statically related a kernel's grid/BlockSpec tiling to the dims an
:class:`~gke_ray_train_tpu.plan.ExecutionPlan` actually declares, and a
kernel claim ("fp32 online softmax", "bf16 matmuls accumulate in f32")
was a docstring, not a checkable property. kernelcheck makes all three
checkable, accelerator-free:

========  ==========================================================
rule      what it catches
========  ==========================================================
KER001    grid/BlockSpec infeasibility: the flash/ring block sizes
          cannot tile the per-shard sequence length the plan implies
          (seq len after context sharding has no legal Pallas block),
          or head_dim breaks the TPU sublane tile for the compute
          dtype (f32: 8, bf16: 16, int8: 32 — lane is always 128)
KER002    estimated VMEM footprint of one ``pallas_call`` grid step
          (double-buffered I/O blocks + scratch) exceeds the per-core
          VMEM budget of the declared topology's chip
KER003    kernel/mesh contract violation: ``attn_impl="flash"`` with
          a context-sharded plan (the runtime ValueError in
          ``ops/dispatch.py``, hoisted into lint)
KER004    non-finite hazard in traced step code: ``exp``/``log``/
          ``rsqrt`` with no guard (max-subtraction, eps-add, clamp,
          select) anywhere in its bounded ancestry — softmax without
          max-subtraction is the canonical instance
KER005    fp32-accumulation policy: a low-precision ``dot_general``
          without ``preferred_element_type=float32``, or a variance /
          second-moment reduction accumulated below fp32
KER006    an accelerated op required to be registered is missing from
          the kernel registry (``ops/registry.py``) — unregistered
          kernels are unverifiable by construction
KER100    a registered kernel case has no pinned tolerance in the
          ledger (``tests/tolerances/*.json``) — record it
KER101    differential value/grad error beyond the pinned tolerance
          band (precision regression vs the oracle)
KER102    the pinned tolerance is far looser than the observed error
          (silent over-loosening — the two-sided half, à la
          ``perf/budget.py``)
========  ==========================================================

KER001-003 are pure arithmetic per plan (no backend, like plancheck);
KER004-005 walk jaxprs — including the jaxprs *inside* ``pallas_call``
eqns — via ``jax.make_jaxpr`` over abstract args (no devices); the
KER10x differential sweeps run every registered kernel against its
oracle (values AND grads, per dtype, sharded cases via the kernel's own
``shard_map`` wrapper on the canonical fake-8 CPU mesh, Pallas in
interpret mode). ``TOLERANCE_UPDATE=1`` (or ``--record``) re-records
the ledger; review the JSON diff like code — that diff IS the numerics
review.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Any, Dict, List, Mapping, Optional, Tuple

RULES = {
    "KER001": "grid/BlockSpec cannot tile the plan's kernel shapes",
    "KER002": "pallas_call VMEM footprint exceeds the per-core budget",
    "KER003": "kernel/mesh contract violation",
    "KER004": "non-finite hazard in traced step code",
    "KER005": "accumulation below fp32",
    "KER006": "accelerated op missing from the kernel registry",
    "KER100": "kernel case unrecorded in the tolerance ledger",
    "KER101": "differential error beyond the pinned tolerance",
    "KER102": "pinned tolerance over-loose vs observed error",
}

TOLERANCE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "tolerances")

# two-sided band à la perf/budget: observed error may drift at most
# SLACK x past the pin (regression), and the pin may sit at most
# SLACK x above the observed error (over-loosened pin). FLOOR absorbs
# exact-zero cases and denormal noise.
LEDGER_SLACK = 4.0
LEDGER_FLOOR = 1e-9

# registry names that MUST exist — deleting a registration (or breaking
# ops/registry.py import order) fails lint instead of silently
# unverifying the kernel (KER006)
REQUIRED_KERNELS = frozenset({
    "flash_attention", "ring_attention", "a2a_attention",
    "quant_matmul", "moe_dispatch", "rope", "kvcache_insert",
    "fused_norm_rope", "fused_cross_entropy", "hier_psum"})

# TPU tiling: lane is always 128; sublane depends on dtype
SUBLANE = {"float32": 8, "bfloat16": 16, "float16": 16,
           "int8": 32, "fp8": 32}


@dataclasses.dataclass(frozen=True)
class KernelFinding:
    rule: str
    subject: str           # kernel / case / config field / traced label
    message: str
    config: str = ""       # config path or label, when plan-scoped

    def __str__(self) -> str:
        where = f"{self.config}: " if self.config else ""
        return f"{where}{self.rule} [{self.subject}] {self.message}"


# ---------------------------------------------------------------------------
# static layer: KER001-003 (pure arithmetic per plan) + KER006
# ---------------------------------------------------------------------------

def resolve_attn_impl(model_cfg, plan, config: Mapping[str, Any] = ()
                      ) -> str:
    """The attention impl the DECLARED topology would run: the config's
    ATTN_IMPL overrides the model preset; ``auto`` resolves to the
    Pallas kernel on TPU families and the XLA oracle on cpu-N — the
    same policy the runtime applies, evaluated against the plan's
    topology instead of the (possibly dead) attached backend."""
    impl = str(dict(config).get("ATTN_IMPL", model_cfg.attn_impl)).lower()
    if impl == "auto":
        family = plan.topology.split("-", 1)[0]
        return "xla" if family == "cpu" else "flash"
    return impl


def kernel_constraint_findings(plan, model_cfg, label: str = "",
                               config: Mapping[str, Any] = ()
                               ) -> List[KernelFinding]:
    """KER001 + KER002 + KER003 for one plan/model pair."""
    from gke_ray_train_tpu.ops.flash_attention import (
        DEFAULT_BLOCK_KV, DEFAULT_BLOCK_Q, estimate_vmem_bytes, pick_block)
    from gke_ray_train_tpu.perf.costs import CHIP_SPECS

    out: List[KernelFinding] = []
    if model_cfg is None:
        return out
    try:
        sizes = plan.resolved_sizes()
    except ValueError:
        return out          # untileable mesh is PLAN001's finding
    impl = resolve_attn_impl(model_cfg, plan, config)
    ctx = sizes["context"]

    # KER003: the ops/dispatch.py runtime contract, hoisted into lint
    if impl == "flash" and ctx > 1:
        out.append(KernelFinding(
            "KER003", "ATTN_IMPL",
            f"attn_impl='flash' with a context-sharded plan (context="
            f"{ctx}) would silently drop cross-shard attention — the "
            "dispatcher refuses it at runtime; declare attn_impl='ring' "
            "(or 'a2a') for context parallelism", label))

    seq = plan.max_seq_len
    s_local = seq // ctx if ctx > 1 and seq % ctx == 0 else seq
    dtype = str(model_cfg.dtype)
    dbytes = 2 if dtype in ("bfloat16", "float16") else 4
    head_dim = model_cfg.resolved_head_dim

    if impl not in ("flash", "ring", "a2a"):
        # the XLA attention oracle has no grid to tile — but the
        # FUSED_OPS epilogue kernels (norm/rope, cross-entropy) run
        # regardless of the attention impl
        family = plan.topology.split("-", 1)[0]
        out.extend(_fused_kernel_findings(
            plan, model_cfg, CHIP_SPECS.get(family, CHIP_SPECS["cpu"]),
            s_local, dbytes, label))
        return out

    # KER001a: block divisibility against the post-context-sharding
    # sequence — the Pallas grid covers s_local // block blocks, and a
    # non-divisor block silently leaves tail rows unwritten, which is
    # why pick_block hard-fails; lint moves that failure to CI
    blocks: Dict[str, int] = {}
    for name, requested in (("block_q", DEFAULT_BLOCK_Q),
                            ("block_kv", DEFAULT_BLOCK_KV)):
        try:
            blocks[name] = pick_block(requested, s_local)
        except ValueError as e:
            out.append(KernelFinding(
                "KER001", name,
                f"{impl} kernel {name}={requested} cannot tile the "
                f"per-shard sequence {s_local} (= {seq} / context "
                f"{ctx}): {e}", label))

    # KER001b: head_dim vs the dtype's sublane tile (lane = 128)
    sublane = SUBLANE.get(dtype, 8)
    if head_dim % sublane:
        out.append(KernelFinding(
            "KER001", "head_dim",
            f"head_dim={head_dim} is not a multiple of the {dtype} "
            f"sublane tile ({sublane}) — Mosaic cannot tile the "
            "kernel's [block, head_dim] VMEM blocks", label))

    # KER002: VMEM footprint of one grid step vs the chip budget
    family = plan.topology.split("-", 1)[0]
    chip = CHIP_SPECS.get(family, CHIP_SPECS["cpu"])
    if len(blocks) == 2:
        est = estimate_vmem_bytes(blocks["block_q"], blocks["block_kv"],
                                  head_dim, dbytes)
        if est > chip.vmem_bytes:
            out.append(KernelFinding(
                "KER002", "FLASH_BLOCK_*",
                f"estimated VMEM for one {impl} grid step is "
                f"{est / 2**20:.1f} MiB (block_q={blocks['block_q']}, "
                f"block_kv={blocks['block_kv']}, head_dim={head_dim}, "
                f"{dtype}) vs the {chip.name} per-core budget "
                f"{chip.vmem_bytes / 2**20:.0f} MiB — shrink "
                "FLASH_BLOCK_Q/FLASH_BLOCK_KV", label))
    out.extend(_fused_kernel_findings(plan, model_cfg, chip, s_local,
                                      dbytes, label))
    return out


def _fused_kernel_findings(plan, model_cfg, chip, s_local: int,
                           dbytes: int, label: str
                           ) -> List[KernelFinding]:
    """KER001/KER002 for the FUSED_OPS kernels — their tiling routes
    through the SAME pick_block/estimate helpers flash uses
    (ops/fused_norm_rope.py, ops/fused_ce.py), so lint sees the same
    numbers the kernels will actually pick; a plan with fused_ops off
    has no fused grid to lint."""
    if not getattr(plan, "fused_ops", False):
        return []
    from gke_ray_train_tpu.ops import fused_ce, fused_norm_rope
    from gke_ray_train_tpu.ops.flash_attention import pick_block

    out: List[KernelFinding] = []
    d_model = model_cfg.d_model
    vocab = model_cfg.vocab_size
    sizes = plan.resolved_sizes()
    v_local = vocab // sizes["model"] if vocab % sizes["model"] == 0 \
        else vocab
    rows = plan.per_device_batch * s_local

    # fused_norm_rope: rows blocked over the per-shard sequence
    try:
        bs = pick_block(fused_norm_rope.DEFAULT_BLOCK_S, s_local)
    except ValueError as e:
        out.append(KernelFinding(
            "KER001", "FUSED_BLOCK_S",
            f"fused_norm_rope block_s="
            f"{fused_norm_rope.DEFAULT_BLOCK_S} cannot tile the "
            f"per-shard sequence {s_local}: {e}", label))
        bs = None
    if bs is not None:
        est = fused_norm_rope.estimate_vmem_bytes(bs, d_model, dbytes)
        if est > chip.vmem_bytes:
            out.append(KernelFinding(
                "KER002", "FUSED_BLOCK_S",
                f"estimated VMEM for one fused_norm_rope grid step is "
                f"{est / 2**20:.1f} MiB (block_s={bs}, "
                f"d_model={d_model}) vs the {chip.name} per-core "
                f"budget {chip.vmem_bytes / 2**20:.0f} MiB — shrink "
                "FUSED_BLOCK_S", label))

    # fused_cross_entropy: rows = local batch x seq, vocab tiled
    br = bv = None
    try:
        br = pick_block(fused_ce.DEFAULT_BLOCK_R, rows)
    except ValueError as e:
        out.append(KernelFinding(
            "KER001", "FUSED_CE_BLOCK_R",
            f"fused_cross_entropy block_r={fused_ce.DEFAULT_BLOCK_R} "
            f"cannot tile the local row count {rows} "
            f"(= per_device_batch {plan.per_device_batch} x per-shard "
            f"seq {s_local}): {e}", label))
    try:
        bv = pick_block(fused_ce.DEFAULT_BLOCK_V, v_local)
    except ValueError as e:
        out.append(KernelFinding(
            "KER001", "FUSED_CE_BLOCK_V",
            f"fused_cross_entropy block_v={fused_ce.DEFAULT_BLOCK_V} "
            f"cannot tile the per-shard vocab {v_local}: {e}", label))
    if br is not None and bv is not None:
        est = fused_ce.estimate_vmem_bytes(br, bv, d_model, dbytes)
        if est > chip.vmem_bytes:
            out.append(KernelFinding(
                "KER002", "FUSED_CE_BLOCK_*",
                f"estimated VMEM for one fused_cross_entropy grid step "
                f"is {est / 2**20:.1f} MiB (block_r={br}, block_v={bv}, "
                f"d_model={d_model}) vs the {chip.name} per-core "
                f"budget {chip.vmem_bytes / 2**20:.0f} MiB — shrink "
                "FUSED_CE_BLOCK_R/FUSED_CE_BLOCK_V", label))
    return out


def registration_findings() -> List[KernelFinding]:
    """KER006: every required accelerated op is registered."""
    from gke_ray_train_tpu.ops import registry
    have = {s.name for s in registry.all_kernels()}
    return [KernelFinding(
        "KER006", name,
        "required kernel has no entry in ops/registry.py — an "
        "unregistered kernel has no oracle, no domain, and no pinned "
        "tolerance, so nothing can verify it")
        for name in sorted(REQUIRED_KERNELS - have)]


# ---------------------------------------------------------------------------
# numerics lint: KER004/KER005 over jaxprs (no devices)
# ---------------------------------------------------------------------------

_EXP_GUARDS = frozenset({"sub", "min", "minimum", "clamp", "select_n"})
_LOG_GUARDS = frozenset({"add", "max", "maximum", "clamp", "select_n",
                         "exp", "log1p"})
_RSQRT_GUARDS = frozenset({"add", "max", "maximum", "clamp", "select_n"})
_ANCESTRY_DEPTH = 10


def _low_precision(dtype) -> bool:
    return str(dtype) in ("bfloat16", "float16")


def _sub_jaxprs(params: Mapping[str, Any]):
    import jax
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if isinstance(item, jax.core.ClosedJaxpr):
                yield item.jaxpr
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                yield item           # raw Jaxpr (pallas_call)


def _eqn_where(eqn) -> str:
    try:
        frame = eqn.source_info.traceback.frames[0]
        return f" ({os.path.basename(frame.file_name)}:"\
               f"{frame.start_line})"
    except Exception:  # noqa: BLE001 - source info is best-effort
        return ""


def _walk_jaxpr(jaxpr, label: str, top: bool,
                findings: List[KernelFinding]) -> None:
    import jax

    producers = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            producers[ov] = eqn

    def guarded(var, guards) -> bool:
        """True when the bounded producer ancestry of ``var`` contains a
        guarding primitive. Free vars (jaxpr inputs) are benign at
        sub-jaxpr depth — the guard may live in the caller (a Pallas
        backward kernel receives the already-max-subtracted lse via a
        ref) — but raw inputs of the TOP-LEVEL traced body are exactly
        the unguarded case the rule exists for."""
        stack = [(var, 0)]
        seen = set()
        while stack:
            v, d = stack.pop()
            if isinstance(v, jax.core.Literal):
                continue     # a literal operand is a constant, not data
            if id(v) in seen or d > _ANCESTRY_DEPTH:
                continue
            seen.add(id(v))
            eqn = producers.get(v)
            if eqn is None:
                if not top:
                    return True
                continue
            if eqn.primitive.name in guards:
                return True
            stack.extend((iv, d + 1) for iv in eqn.invars)
        return False

    def low_prec_square(var) -> bool:
        """A square (x*x / x**2) in the bounded ancestry whose RESULT
        is low-precision — the second moment rounds to bf16 before it
        is ever accumulated (rms_norm's discipline: cast to f32 FIRST,
        then square, then reduce)."""
        stack = [(var, 0)]
        seen = set()
        while stack:
            v, d = stack.pop()
            if isinstance(v, jax.core.Literal) or id(v) in seen \
                    or d > _ANCESTRY_DEPTH:
                continue
            seen.add(id(v))
            eqn = producers.get(v)
            if eqn is None:
                continue
            name = eqn.primitive.name
            is_square = (
                name == "square"
                or (name == "integer_pow" and eqn.params.get("y") == 2)
                or (name == "mul" and len(eqn.invars) == 2
                    and eqn.invars[0] is eqn.invars[1]))
            if is_square and _low_precision(eqn.outvars[0].aval.dtype):
                return True
            stack.extend((iv, d + 1) for iv in eqn.invars)
        return False

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in ("exp", "exp2") and not guarded(eqn.invars[0],
                                                   _EXP_GUARDS):
            findings.append(KernelFinding(
                "KER004", label,
                f"exp with no max-subtraction/clamp in its ancestry"
                f"{_eqn_where(eqn)} — overflows to inf for large "
                "logits; subtract the row max first (online-softmax "
                "discipline)"))
        elif name == "log" and not guarded(eqn.invars[0], _LOG_GUARDS):
            findings.append(KernelFinding(
                "KER004", label,
                f"log with no eps/max/clamp guard in its ancestry"
                f"{_eqn_where(eqn)} — NaN/-inf at zero; add an eps or "
                "clamp the operand"))
        elif name == "rsqrt" and not guarded(eqn.invars[0],
                                             _RSQRT_GUARDS):
            findings.append(KernelFinding(
                "KER004", label,
                f"rsqrt with no eps-add in its ancestry{_eqn_where(eqn)}"
                " — inf at zero variance; use rsqrt(x + eps)"))
        elif name == "dot_general":
            pref = eqn.params.get("preferred_element_type")
            if _low_precision(eqn.invars[0].aval.dtype) and (
                    pref is None or _low_precision(pref)):
                findings.append(KernelFinding(
                    "KER005", label,
                    "low-precision dot_general without "
                    f"preferred_element_type=float32{_eqn_where(eqn)} — "
                    "the contraction accumulates (and rounds) in "
                    f"{eqn.invars[0].aval.dtype}; declare fp32 "
                    "accumulation and cast the result"))
        elif name == "reduce_sum" and low_prec_square(eqn.invars[0]):
            findings.append(KernelFinding(
                "KER005", label,
                "variance/second-moment computed below fp32"
                f"{_eqn_where(eqn)} — the squares round to bf16/f16 "
                "before accumulation; cast to float32 FIRST, then "
                "square and reduce (rms_norm's discipline)"))
        for sub in _sub_jaxprs(eqn.params):
            _walk_jaxpr(sub, label, False, findings)


def numerics_findings() -> List[KernelFinding]:
    """KER004/KER005 over every registered kernel's traced bodies plus
    the standalone step-code targets (loss, norms, dense attention)."""
    import jax

    from gke_ray_train_tpu.ops import registry

    targets: List[tuple] = []
    for spec in registry.all_kernels():
        if spec.numerics_targets is not None:
            targets.extend(spec.numerics_targets())
    targets.extend(registry.standalone_numerics_targets())

    findings: List[KernelFinding] = []
    for label, fn, abstract_args in targets:
        jaxpr = jax.make_jaxpr(fn)(*abstract_args)
        _walk_jaxpr(jaxpr.jaxpr, label, True, findings)
    return findings


def lint_traced_fn(fn, *abstract_args, label: str = "<fn>"
                   ) -> List[KernelFinding]:
    """KER004/KER005 over one traced body — the test-fixture entry."""
    import jax
    findings: List[KernelFinding] = []
    jaxpr = jax.make_jaxpr(fn)(*abstract_args)
    _walk_jaxpr(jaxpr.jaxpr, label, True, findings)
    return findings


# ---------------------------------------------------------------------------
# differential layer: registry sweeps vs the tolerance ledger
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CaseResult:
    kernel: str
    case: str
    value_err: float
    grad_err: Optional[float] = None
    exact: bool = False

    def metrics(self) -> Dict[str, float]:
        out = {"value": self.value_err}
        if self.grad_err is not None:
            out["grad"] = self.grad_err
        return out


def _case_key(spec_name: str, case_name: str):
    import jax
    return jax.random.key(zlib.crc32(f"{spec_name}/{case_name}".encode()))


def _rel_err(a, b) -> float:
    import numpy as np
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    denom = max(float(np.max(np.abs(b))), 1e-30)
    return float(np.max(np.abs(a - b))) / denom


def _matched_leaves(got, want):
    """Leaf pairs, with the tree structures asserted equal FIRST — a
    kernel/oracle structure mismatch must be a loud error, never a
    zip-truncated partial comparison that reports 'clean' on leaves it
    silently skipped."""
    import jax
    got_s = jax.tree.structure(got)
    want_s = jax.tree.structure(want)
    if got_s != want_s:
        raise KernelCheckError(
            f"kernel and oracle outputs have different tree structures "
            f"({got_s} vs {want_s}) — the differential claim is "
            "ill-formed; fix the registration")
    return list(zip(jax.tree.leaves(got), jax.tree.leaves(want)))


def _tree_err(got, want) -> float:
    return max(_rel_err(g, w) for g, w in _matched_leaves(got, want))


def _tree_exact(got, want) -> bool:
    import numpy as np
    return all(np.array_equal(np.asarray(g), np.asarray(w))
               for g, w in _matched_leaves(got, want))


def _case_mesh(case):
    import jax

    from gke_ray_train_tpu.parallel.mesh import MESH_AXES, MeshConfig, \
        build_mesh
    if case.mesh_axes is None:
        return None
    sizes = {a: 1 for a in MESH_AXES}
    sizes.update(case.mesh_axes)
    n = 1
    for v in sizes.values():
        n *= v
    if n != len(jax.devices()):
        raise RuntimeError(
            f"case {case.name!r} wants a {n}-device mesh but "
            f"{len(jax.devices())} devices are attached — run on the "
            "canonical fake-8 CPU mesh (the CLI re-execs itself there)")
    return build_mesh(MeshConfig(**sizes), jax.devices())


def _probe(tree):
    """Deterministic cotangent for the grad check (cos ramp per leaf)."""
    import jax
    import jax.numpy as jnp

    def one(x):
        flat = jnp.cos(jnp.arange(x.size, dtype=jnp.float32) * 0.7)
        return flat.reshape(x.shape).astype(x.dtype)

    return jax.tree.map(one, tree)


def run_case(spec, case) -> CaseResult:
    """One differential point: values (and grads) of kernel vs oracle."""
    import jax
    import jax.numpy as jnp

    args, diff_argnums = spec.build(case, _case_key(spec.name, case.name))
    mesh = _case_mesh(case)

    out_k = spec.kernel(case, mesh, *args)
    out_o = spec.oracle(case, mesh, *args)
    if case.exact:
        return CaseResult(spec.name, case.name,
                          0.0 if _tree_exact(out_k, out_o) else
                          _tree_err(out_k, out_o), exact=True)
    value_err = _tree_err(out_k, out_o)

    grad_err = None
    if case.grads and diff_argnums:
        probe = _probe(out_k)

        def loss(run):
            def fn(*dargs):
                full = list(args)
                for i, a in zip(diff_argnums, dargs):
                    full[i] = a
                out = run(case, mesh, *full)
                return sum(
                    jnp.sum(o.astype(jnp.float32)
                            * p.astype(jnp.float32))
                    for o, p in zip(jax.tree.leaves(out),
                                    jax.tree.leaves(probe)))
            return fn

        dargs = tuple(args[i] for i in diff_argnums)
        g_k = jax.grad(loss(spec.kernel),
                       argnums=tuple(range(len(dargs))))(*dargs)
        g_o = jax.grad(loss(spec.oracle),
                       argnums=tuple(range(len(dargs))))(*dargs)
        grad_err = _tree_err(g_k, g_o)
    return CaseResult(spec.name, case.name, value_err, grad_err)


def sweep(names: Optional[List[str]] = None) -> List[CaseResult]:
    """Run every registered kernel's full case sweep (or a subset)."""
    from gke_ray_train_tpu.ops import registry
    specs = registry.all_kernels()
    if names:
        unknown = set(names) - {s.name for s in specs}
        if unknown:
            # a typo'd name must not shrink the sweep to nothing and
            # report 'clean' — the gate would pass having verified zero
            raise KernelCheckError(
                f"unknown kernel(s) {sorted(unknown)}; registered: "
                f"{[s.name for s in specs]}")
        specs = [s for s in specs if s.name in set(names)]
    results: List[CaseResult] = []
    for spec in specs:
        for case in spec.cases:
            results.append(run_case(spec, case))
    return results


# -- tolerance ledger --------------------------------------------------------

def ledger_path(kernel: str, ledger_dir: Optional[str] = None) -> str:
    return os.path.join(ledger_dir or TOLERANCE_DIR, f"{kernel}.json")


def load_ledger(kernel: str, ledger_dir: Optional[str] = None
                ) -> Optional[Dict[str, Any]]:
    path = ledger_path(kernel, ledger_dir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def record_ledger(results: List[CaseResult],
                  ledger_dir: Optional[str] = None) -> List[str]:
    """Write one ledger JSON per kernel, pinning the observed errors.
    Values are rounded to 3 significant digits so a bitwise-stable
    re-record survives last-ulp drift in the error measurement."""
    by_kernel: Dict[str, Dict[str, Dict[str, float]]] = {}
    for r in results:
        by_kernel.setdefault(r.kernel, {})[r.case] = {
            k: float(f"{v:.3g}") for k, v in r.metrics().items()}
    written = []
    for kernel in sorted(by_kernel):
        path = ledger_path(kernel, ledger_dir)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        doc = {
            "_kernel": kernel,
            "_note": "observed kernel-vs-oracle error per case, pinned "
                     "two-sided; re-record with TOLERANCE_UPDATE=1 (or "
                     "python -m gke_ray_train_tpu.analysis kernelcheck "
                     "--record) and review the diff like code",
            "cases": by_kernel[kernel],
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        written.append(path)
    return written


def ledger_findings(results: List[CaseResult],
                    ledger_dir: Optional[str] = None
                    ) -> List[KernelFinding]:
    """KER100/101/102: the two-sided comparator. A regression (observed
    error above the pinned band) and an over-loosened pin (pinned error
    far above observed — e.g. a hand-edited ledger hiding a regression
    behind slack) both fail."""
    out: List[KernelFinding] = []
    ledgers: Dict[str, Optional[Dict[str, Any]]] = {}
    for r in results:
        if r.kernel not in ledgers:
            ledgers[r.kernel] = load_ledger(r.kernel, ledger_dir)
        doc = ledgers[r.kernel]
        pinned = (doc or {}).get("cases", {}).get(r.case)
        subject = f"{r.kernel}/{r.case}"
        if pinned is None:
            out.append(KernelFinding(
                "KER100", subject,
                "no pinned tolerance for this case — record the ledger "
                "(TOLERANCE_UPDATE=1) and review the new pin"))
            continue
        for metric, observed in r.metrics().items():
            pin = pinned.get(metric)
            if pin is None:
                out.append(KernelFinding(
                    "KER100", f"{subject}:{metric}",
                    "metric unpinned in the ledger — re-record"))
                continue
            if observed > max(pin * LEDGER_SLACK, LEDGER_FLOOR):
                out.append(KernelFinding(
                    "KER101", f"{subject}:{metric}",
                    f"observed error {observed:.3g} vs pinned "
                    f"{pin:.3g} (> {LEDGER_SLACK:g}x band) — precision "
                    "regression against the oracle; if the change is "
                    "INTENTIONAL, re-record with TOLERANCE_UPDATE=1"))
            elif pin > max(observed * LEDGER_SLACK, LEDGER_FLOOR):
                out.append(KernelFinding(
                    "KER102", f"{subject}:{metric}",
                    f"pinned tolerance {pin:.3g} is > {LEDGER_SLACK:g}x "
                    f"looser than the observed error {observed:.3g} — "
                    "an over-loose pin would hide the next regression; "
                    "re-record to tighten"))
    return out


def quick_verify(log=None) -> List[CaseResult]:
    """The KERNELCHECK=1 worker-startup probe: first (cheapest) case of
    every registered kernel, value-only, against the shipped ledger.
    Raises on any finding — a worker whose kernels disagree with their
    oracles must not train."""
    import jax

    from gke_ray_train_tpu.ops import registry

    def mesh_fits(case) -> bool:
        if case.mesh_axes is None:
            return True
        n = 1
        for v in case.mesh_axes.values():
            n *= v
        return n == len(jax.devices())

    results = []
    for spec in registry.all_kernels():
        # cheapest case whose mesh (if any) the attached pool can form
        # — a worker on a 16-chip pool must not die because a case was
        # written for the canonical fake-8 mesh; mesh-free cases cover
        # the kernel math either way
        case = next((c for c in spec.cases if mesh_fits(c)), None)
        if case is None:
            continue
        results.append(run_case(spec, dataclasses.replace(case,
                                                          grads=False)))
    findings = [f for f in ledger_findings(results)
                if f.rule != "KER102"]   # startup gate: regressions only
    if findings:
        raise KernelCheckError(
            "KERNELCHECK startup verification failed:\n  "
            + "\n  ".join(str(f) for f in findings))
    if log is not None and results:
        log("KERNELCHECK: %d kernel(s) verified against their oracles "
            "(worst value error %.3g)", len(results),
            max(r.value_err for r in results))
    return results


class KernelCheckError(AssertionError):
    """A kernel disagreed with its oracle beyond the pinned tolerance."""


# ---------------------------------------------------------------------------
# CLI body (the `kernelcheck` verb of python -m gke_ray_train_tpu.analysis)
# ---------------------------------------------------------------------------

def static_findings(config_paths: Optional[List[str]] = None
                    ) -> List[KernelFinding]:
    """KER001-006 over the shipped configs (same default set plancheck
    gates) — no backend, no devices."""
    from gke_ray_train_tpu.analysis.plancheck import (
        default_config_paths, model_config_for)
    from gke_ray_train_tpu.plan import ExecutionPlan, PlanError

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = config_paths or default_config_paths(repo_root)
    findings: List[KernelFinding] = []
    for p in paths:
        label = os.path.relpath(p, repo_root) if os.path.isabs(p) else p
        try:
            with open(p) as fh:
                config = json.load(fh)
            plan = ExecutionPlan.from_config(config)
            model_cfg = model_config_for(config, plan)
        except (OSError, json.JSONDecodeError, PlanError, ValueError):
            continue         # plancheck PLAN000 owns unparseable configs
        findings.extend(kernel_constraint_findings(
            plan, model_cfg, label=label, config=config))
    findings.extend(registration_findings())
    findings.extend(numerics_findings())
    return findings


def main_check(names: Optional[List[str]] = None, *,
               static_only: bool = False, diff_only: bool = False,
               record: bool = False,
               ledger_dir: Optional[str] = None,
               config_paths: Optional[List[str]] = None) -> int:
    findings: List[KernelFinding] = []
    if not diff_only:
        findings.extend(static_findings(config_paths))
    results: List[CaseResult] = []
    if not static_only:
        results = sweep(names)
        if record or os.environ.get("TOLERANCE_UPDATE") == "1":
            for path in record_ledger(results, ledger_dir):
                print(f"recorded {path}")
        else:
            findings.extend(ledger_findings(results, ledger_dir))
    for f in findings:
        print(f"FINDING {f}")
    if findings:
        print(f"kernelcheck: {len(findings)} finding(s)")
        return 1
    parts = []
    if not diff_only:
        parts.append("static rules KER001-006 clean")
    if results:
        worst = max(r.value_err for r in results)
        parts.append(f"{len(results)} differential case(s) within the "
                     f"pinned ledger, worst value error {worst:.3g}")
    print("kernelcheck: clean (" + "; ".join(parts) + ")")
    return 0
