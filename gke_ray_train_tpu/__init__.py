"""gke_ray_train_tpu — a TPU-native distributed LLM training framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the
``ericehanley/gke-ray-train`` reference (Ray-on-GKE LLM fine-tuning on
A3/H100 + NCCL), rebuilt TPU-first:

- SPMD over a ``jax.sharding.Mesh`` (axes: data / fsdp / model / context)
  instead of DDP+NCCL (reference: ray-jobs/pytorch_llm_ray.py:362-364).
- GSPMD-sharded params/optimizer state (ZeRO/FSDP as sharding specs, not
  machinery) instead of bitsandbytes paged optimizers.
- Functional pytree models (Llama-3 / Mistral / Gemma-2 / BasicLM) with
  Pallas flash attention and ring attention for long context.
- orbax sharded checkpointing with retention + resume (the reference never
  wires resume — fine_tune_llama_ray.py has no resume_from_checkpoint).
- A Ray Train style ``JaxTrainer`` preserving the reference's
  ``train_loop_per_worker(config)`` API shape (fine_tune_llama_ray.py:198).
"""

__version__ = "0.1.0"

# Sharding-invariant init is a correctness contract here (meshed init ==
# plain init == init on any elastic topology): every init path wraps
# itself in parallel.sharding.sharding_invariant_rng (partitionable
# threefry, scoped — the global flag costs ~15% wall on CPU suites).

from gke_ray_train_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    batch_sharding,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_CONTEXT,
    AXIS_PIPE,
    MESH_AXES,
)
from gke_ray_train_tpu.plan import (  # noqa: F401
    ExecutionPlan,
    compile_step_with_plan,
)
