"""gke_ray_train_tpu — a TPU-native distributed LLM training framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the
``ericehanley/gke-ray-train`` reference (Ray-on-GKE LLM fine-tuning on
A3/H100 + NCCL), rebuilt TPU-first:

- SPMD over a ``jax.sharding.Mesh`` (axes: data / fsdp / model / context)
  instead of DDP+NCCL (reference: ray-jobs/pytorch_llm_ray.py:362-364).
- GSPMD-sharded params/optimizer state (ZeRO/FSDP as sharding specs, not
  machinery) instead of bitsandbytes paged optimizers.
- Functional pytree models (Llama-3 / Mistral / Gemma-2 / BasicLM) with
  Pallas flash attention and ring attention for long context.
- orbax sharded checkpointing with retention + resume (the reference never
  wires resume — fine_tune_llama_ray.py has no resume_from_checkpoint).
- A Ray Train style ``JaxTrainer`` preserving the reference's
  ``train_loop_per_worker(config)`` API shape (fine_tune_llama_ray.py:198).
"""

__version__ = "0.1.0"

# Sharding-invariant init is a correctness contract here (meshed init ==
# plain init == init on any elastic topology): every init path wraps
# itself in parallel.sharding.sharding_invariant_rng (partitionable
# threefry, scoped — the global flag costs ~15% wall on CPU suites).

# The package re-exports are LAZY (PEP 562): parallel.mesh imports jax
# at module level, but the obs/ CLI surface (`python -m
# gke_ray_train_tpu.obs report|diff|schema`) is stdlib-only by
# contract — it must run on a laptop pointed at a GCS-FUSE mount with
# no jax installed, and importing any submodule materializes this
# __init__ first. Attribute access (`gke_ray_train_tpu.MeshConfig`)
# resolves exactly as before.
_LAZY_EXPORTS = {
    "MeshConfig": "parallel.mesh",
    "build_mesh": "parallel.mesh",
    "batch_sharding": "parallel.mesh",
    "AXIS_DATA": "parallel.mesh",
    "AXIS_FSDP": "parallel.mesh",
    "AXIS_MODEL": "parallel.mesh",
    "AXIS_CONTEXT": "parallel.mesh",
    "AXIS_PIPE": "parallel.mesh",
    "MESH_AXES": "parallel.mesh",
    "ExecutionPlan": "plan",
    "compile_step_with_plan": "plan",
}

__all__ = sorted(_LAZY_EXPORTS)


def __getattr__(name):
    mod = _LAZY_EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)
