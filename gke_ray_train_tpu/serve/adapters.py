"""Bounded multi-tenant adapter pool (ISSUE 17 — the S-LoRA cache).

The QLoRA fine-tune is an *adapter factory*: production traffic is many
LoRA tenants over one frozen base. This module owns the device-resident
half of that: one stacked array per LoRA target matmul
(``[n_repeats, A, d_in, r]`` / ``[n_repeats, A, r, d_out]``, adapter
axis 1 — the layout ``ops/lora_batched.py`` gathers from inside the
shared decode executable), with host-side LRU admission/eviction over
``MAX_ADAPTERS`` tenant slots. Mirrors the KV-pool discipline
(serve/engine.py): the pool's SHAPE is fixed at construction so the
compiled decode never changes; tenants churn by overwriting slots.

Slot 0 is the reserved zero adapter (A = B = 0): a request without an
``adapter_id`` routes there and gets the exact base-model output —
adding an exact-zero delta cannot change an argmax, so the no-adapter
tenant stays bitwise the no-LoRA engine.

Pinning: the engine ``acquire``s a tenant at admission and
``release``s at retirement; eviction only ever takes an *unpinned*
slot, so a tenant's weights are never overwritten while one of its
requests is mid-decode in the shared batch.

Counters (hits/misses/evictions) flow through the engine's ``stats()``
into the obs metrics registry (``serve_adapter_*_total``).
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)


class AdapterPoolPinned(RuntimeError):
    """Every resident slot is pinned by an in-flight request — the
    admission path treats this as 'retry next iteration', not a crash."""


def _stack_template(template: Any, n_slots: int) -> Any:
    """Zeroed pool shaped like ``n_slots`` copies of a single-adapter
    tree, stacked at axis 1 (adapter axis; the scanned-block axis stays
    leading — the lora_batched layout contract)."""
    def widen(leaf):
        return jnp.zeros(leaf.shape[:1] + (n_slots,) + leaf.shape[1:],
                         leaf.dtype)
    return jax.tree.map(widen, template)


class AdapterPool:
    """Bounded host+device adapter pool with LRU eviction.

    ``loader(adapter_id) -> single-adapter tree`` backfills misses
    (e.g. :func:`adapter_from_checkpoint`); without one, an unknown id
    raises ``KeyError``. ``template`` is any single-adapter tree of the
    right shape (e.g. the just-trained ``state.lora``, or
    ``train.lora.init_lora`` output) — only its shapes/dtypes are read.
    """

    def __init__(self, template: Any, *, max_adapters: int,
                 loader: Optional[Callable[[str], Any]] = None):
        if max_adapters < 1:
            raise ValueError(f"max_adapters={max_adapters} must be >= 1")
        self.max_adapters = int(max_adapters)
        self.n_slots = self.max_adapters + 1   # + reserved zero slot 0
        tpl = {"blocks": template["blocks"]}
        # device pool; slot 0 stays all-zero forever (the base tenant)
        self.blocks = _stack_template(tpl, self.n_slots)["blocks"]
        self._loader = loader
        self._slots: "OrderedDict[str, int]" = OrderedDict()  # LRU order
        self._free = list(range(1, self.n_slots))
        self._pins: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @classmethod
    def from_template(cls, template: Any, *, max_adapters: int,
                      loader: Optional[Callable[[str], Any]] = None
                      ) -> "AdapterPool":
        return cls(template, max_adapters=max_adapters, loader=loader)

    # -- residency -----------------------------------------------------

    def __contains__(self, adapter_id: str) -> bool:
        return adapter_id in self._slots

    def resident(self) -> Dict[str, int]:
        """adapter_id -> slot, LRU-oldest first (inspection/tests)."""
        return dict(self._slots)

    def slot_of(self, adapter_id: Optional[str]) -> Optional[int]:
        if adapter_id is None:
            return 0
        return self._slots.get(adapter_id)

    def _write(self, slot: int, tree: Any) -> None:
        """Admission-path pool write (one ``.at[:, slot].set`` per leaf
        — never inside the decode loop). Shape/structure mismatches
        (wrong rank r, wrong targets) fail loudly here."""
        self.blocks = jax.tree.map(
            lambda p, leaf: p.at[:, slot].set(leaf.astype(p.dtype)),
            self.blocks, tree["blocks"])

    def register(self, adapter_id: str, tree: Any) -> int:
        """Make ``adapter_id`` resident with the given single-adapter
        tree. Ids are immutable-by-contract (the engine's prefix cache
        keys on them) — re-registering an id raises."""
        if not adapter_id:
            raise ValueError("adapter_id must be a non-empty string")
        if adapter_id in self._slots:
            raise ValueError(
                f"adapter {adapter_id!r} already resident — adapter ids "
                "are immutable (the prefix cache keys on them); use a "
                "new id for new weights")
        slot = self._take_slot()
        self._write(slot, tree)
        self._slots[adapter_id] = slot
        return slot

    def _take_slot(self) -> int:
        if self._free:
            return self._free.pop(0)
        for aid in self._slots:            # LRU-oldest first
            if not self._pins.get(aid):
                slot = self._slots.pop(aid)
                self.evictions += 1
                logger.info("adapter pool: evicted %r from slot %d",
                            aid, slot)
                return slot
        raise AdapterPoolPinned(
            f"all {self.max_adapters} adapter slots are pinned by "
            "in-flight requests — raise MAX_ADAPTERS or drain first")

    def acquire(self, adapter_id: Optional[str]) -> int:
        """Resolve a request's adapter to its pool slot, pinning it for
        the request's lifetime (engine calls at admission; pair with
        :meth:`release` at retirement). ``None`` -> the zero slot,
        never pinned, never evicted."""
        if adapter_id is None:
            return 0
        slot = self._slots.get(adapter_id)
        if slot is not None:
            self.hits += 1
            self._slots.move_to_end(adapter_id)
        else:
            if self._loader is None:
                raise KeyError(
                    f"adapter {adapter_id!r} is not resident and the "
                    "pool has no loader")
            if not self._free and not any(
                    not self._pins.get(a) for a in self._slots):
                # raise BEFORE paying the loader: the engine retries a
                # pinned-out admission every step, and a retry that
                # loads nothing must not re-read a checkpoint or count
                # as a miss
                raise AdapterPoolPinned(
                    f"all {self.max_adapters} adapter slots are pinned "
                    "by in-flight requests — raise MAX_ADAPTERS or "
                    "drain first")
            slot = self.register(adapter_id, self._loader(adapter_id))
            self.misses += 1
        self._pins[adapter_id] = self._pins.get(adapter_id, 0) + 1
        return slot

    def release(self, adapter_id: Optional[str]) -> None:
        if adapter_id is None:
            return
        n = self._pins.get(adapter_id, 0)
        if n <= 1:
            self._pins.pop(adapter_id, None)
        else:
            self._pins[adapter_id] = n - 1

    def stats(self) -> Dict[str, Any]:
        return {"adapter_hits": self.hits,
                "adapter_misses": self.misses,
                "adapter_evictions": self.evictions,
                "adapter_resident": len(self._slots)}


def adapter_from_checkpoint(directory: str, step: Optional[int] = None
                            ) -> Any:
    """Load a trained adapter tree from a TrainState checkpoint — the
    existing artifact path (``ckpt/manager.py``): the trainer saves the
    full state (params/opt/lora) and ``restore_raw`` reads it back
    topology-free, so a serving host with a different mesh (or no mesh)
    can still hydrate tenants. Use as an :class:`AdapterPool` loader:
    ``loader=lambda aid: adapter_from_checkpoint(dirs[aid])``."""
    from gke_ray_train_tpu.ckpt.manager import CheckpointManager
    raw = CheckpointManager(directory).restore_raw(step)
    lora = raw.get("lora") if isinstance(raw, dict) else None
    if lora is None:
        raise ValueError(
            f"checkpoint at {directory} has no 'lora' subtree — was the "
            "run trained with USE_LORA/USE_QLORA?")
    return lora
