"""Prompt bucketing shared by the comparison path and the serve engine.

One compile per length class: a request's working width is rounded up
to a fixed bucket so every prompt of similar length dispatches into the
same compiled prefill/decode pair. 128-multiples matter twice — they
are the flash-prefill tiling gate in ``models/kvcache.py``, and they
make ``greedy_generate_cached``'s internal prefill rounding land on the
full bucket width, which is what keeps the serving engine's full-width
prefill bitwise-comparable to the sequential oracle.

Extracted from ``inference.py`` (which duplicated the rounding and the
buffer form-up inline) so the comparison path and ``serve/engine.py``
cannot drift.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_BUCKET_QUANTUM = 128


def prompt_bucket(n: int, *, bucket: int = DEFAULT_BUCKET_QUANTUM) -> int:
    """Round a width up to a fixed bucket so every prompt of similar
    length shares one compiled decode loop (VERDICT r1 weak #6:
    per-prompt-length recompiles)."""
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


def pick_bucket(prompt_len: int, max_new_tokens: int,
                buckets: Sequence[int],
                max_seq_len: Optional[int] = None) -> int:
    """The smallest declared bucket that fits ``prompt_len + max_new``
    (and the model's ``max_seq_len`` when given). Raises ValueError
    when no bucket fits — the scheduler rejects the request up front
    instead of letting a fixed-shape executable truncate it silently."""
    need = prompt_len + max_new_tokens
    usable = sorted(b for b in buckets
                    if max_seq_len is None or b <= max_seq_len)
    if not usable:
        raise ValueError(
            f"no declared bucket {sorted(buckets)} fits the model's "
            f"max_seq_len={max_seq_len}")
    for b in usable:
        if need <= b:
            return b
    raise ValueError(
        f"request needs {need} slots (prompt {prompt_len} + "
        f"{max_new_tokens} new) but the largest usable bucket is "
        f"{usable[-1]} — truncate the prompt or declare a larger bucket")


def truncate_prompt(ids: np.ndarray, max_prompt: int,
                    *, label: str = "prompt") -> np.ndarray:
    """Keep the LAST ``max_prompt`` tokens (the reference's behavior),
    but loudly: a silently truncated prompt makes the model answer a
    question the user never finished asking."""
    if len(ids) > max_prompt:
        logger.warning(
            "%s of %d tokens exceeds the %d-token budget; truncating "
            "to the last %d tokens (the head of the prompt is DROPPED)",
            label, len(ids), max_prompt, max_prompt)
        return ids[-max_prompt:]
    return ids


def form_prompt_buffer(ids: np.ndarray, width: int
                       ) -> Tuple[np.ndarray, int]:
    """(right-padded [1, width] int32 buffer, prompt_len) — the fixed
    buffer shape prefill compiles against. ``ids`` must already fit
    ``width`` (callers bucket/truncate first)."""
    ids = np.asarray(ids, np.int32)
    if len(ids) > width:
        raise ValueError(f"prompt of {len(ids)} tokens does not fit the "
                         f"{width}-wide buffer — bucket/truncate first")
    buf = np.zeros((1, width), np.int32)
    buf[0, :len(ids)] = ids
    return buf, len(ids)
