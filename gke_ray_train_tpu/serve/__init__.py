"""serve/ — continuous-batching inference on the trained artifacts.

The production half of the north star (`ROADMAP` #2): promote the
one-prompt-at-a-time comparison path (``inference.py`` +
``models/kvcache.py``) into a multi-tenant serving engine —
iteration-level continuous batching (Orca) over a length-bucketed
KV-cache pool, adapted to XLA's static-shape world with fixed
``(max_batch, bucket)`` executables instead of dynamic pages.
"""

from gke_ray_train_tpu.serve.adapters import (  # noqa: F401
    AdapterPool, AdapterPoolPinned, adapter_from_checkpoint)
from gke_ray_train_tpu.serve.bucketing import (  # noqa: F401
    form_prompt_buffer, pick_bucket, prompt_bucket, truncate_prompt)
from gke_ray_train_tpu.serve.engine import (  # noqa: F401
    BatchEngine, Completion, Request, post_train_smoke, serve_plan)
