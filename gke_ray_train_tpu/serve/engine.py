"""Continuous-batching inference engine (ROADMAP #2, Orca-style).

Iteration-level scheduling over bucketed, jitted executables:

- Every request is assigned the smallest declared length bucket that
  fits ``prompt_len + max_new_tokens`` (serve/bucketing.py). Per bucket
  the engine compiles exactly THREE executables — ``prefill_step``
  (``[1, L]``), ``decode_step`` (``[max_batch, 1]``) and
  ``insert_slot`` — so XLA compiles once per bucket, never per request.
- Admission is slot-level: a finished sequence's slot is refilled at
  the next decode iteration (prefill the newcomer at batch 1, then
  ``dynamic_update_slice`` its KV rows into the pooled cache) without
  flushing the batch — the surviving sequences' K/V bytes are
  untouched, which is what makes continuous-batched output bitwise
  identical to sequential ``greedy_generate_cached``.
- The KV pool is ``models/kvcache.py::init_cache`` at
  ``[max_batch, bucket]`` per bucket — the static-shape stand-in for
  vLLM's dynamic pages (XLA cannot page, it CAN bucket).
- Weights optionally serve quantized (``ops/quant.py``: int8/nf4).
- Cold start: executables build through ``compile_step_with_plan``
  (plan.py), so the persistent compile cache applies and — when a
  ``sidecar_dir`` is given — each executable AOT-serializes through
  ``perf/cache.py``; a fresh replica deserializes all three per bucket
  and reaches its first decoded token with zero new compilations.

Sequential-equivalence contract (drilled in tests/test_serve.py): the
per-slot update rule is exactly ``greedy_generate_cached``'s loop body,
attention masking contributes *exact zeros* for other slots' garbage
(ops/attention.py NEG_INF underflows), and prefill runs the full bucket
width — which equals the oracle's internal prefill width whenever the
bucket is a 128-multiple and ``max_new_tokens < 128``.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.kvcache import (
    forward_step, init_cache, insert_cache_slot)
from gke_ray_train_tpu.ops.quant import quantize_for_serving
from gke_ray_train_tpu.plan import ExecutionPlan, compile_step_with_plan
from gke_ray_train_tpu.serve.bucketing import (
    form_prompt_buffer, pick_bucket, truncate_prompt)

logger = logging.getLogger(__name__)


def serve_plan(**overrides: Any) -> ExecutionPlan:
    """The serving ExecutionPlan: env/config resolved like the trainer's
    (MAX_BATCH / DECODE_BUCKETS / SERVE_QUANT et al.), with kwarg
    overrides — so the engine shares the plan fingerprint/budget/
    plancheck machinery instead of growing a fifth knob dialect."""
    return ExecutionPlan.resolve(**overrides)


@dataclasses.dataclass
class Request:
    """One generation request. ``token_ids`` is the already-tokenized
    prompt (the engine is tokenizer-agnostic; rayint/serving.py holds
    the tokenizer)."""
    rid: str
    token_ids: np.ndarray
    max_new_tokens: int = 32
    # multi-tenant serving: which LoRA tenant decodes this request
    # (None = the base model / reserved zero adapter). Requires the
    # engine to carry an AdapterPool (serve/adapters.py).
    adapter_id: Optional[str] = None


@dataclasses.dataclass
class Completion:
    rid: str
    tokens: np.ndarray          # full row buffer [bucket] incl. prompt
    prompt_len: int
    length: int                 # prompt_len + generated count
    bucket: int
    finish_reason: str          # "eos" | "length"
    submit_s: float = 0.0
    first_token_s: float = 0.0  # submit -> first decoded token
    done_s: float = 0.0         # submit -> completion
    adapter_id: Optional[str] = None

    @property
    def generated(self) -> np.ndarray:
        """The generated region (includes the EOS token when one was
        produced, mirroring ``greedy_generate_cached``'s buffer)."""
        return self.tokens[self.prompt_len:self.length]


# ---------------------------------------------------------------------------
# the pure step bodies (named so shardlint treats them as traced code)
# ---------------------------------------------------------------------------

def init_serve_state(cfg: ModelConfig, batch: int, width: int, *,
                     multi_lora: bool = False,
                     draft_cfg: Optional[ModelConfig] = None
                     ) -> Dict[str, Any]:
    """Zeroed per-bucket batch state: token buffer, per-slot cursors and
    the pooled KV cache. ``active`` starts all-False — empty slots run
    the decode step as masked no-ops until admission fills them.

    ``multi_lora`` adds the per-slot adapter index ``aslot`` [B] (slot 0
    = the reserved zero adapter); ``draft_cfg`` adds the draft model's
    own KV pool ``dcache`` for speculative decoding."""
    state = {
        "buf": jnp.zeros((batch, width), jnp.int32),
        "lens": jnp.zeros((batch,), jnp.int32),
        "stop": jnp.zeros((batch,), jnp.int32),
        "active": jnp.zeros((batch,), bool),
        "cur": jnp.zeros((batch,), jnp.int32),
        "cache": init_cache(cfg, batch, width),
    }
    if multi_lora:
        state["aslot"] = jnp.zeros((batch,), jnp.int32)
    if draft_cfg is not None:
        state["dcache"] = init_cache(draft_cfg, batch, width)
    return state


def _resolve_lora(state: Dict[str, Any], lora: Any, pool: bool) -> Any:
    """In pool mode the compiled step's ``lora`` argument is the stacked
    pool blocks; pair them with the state's per-slot adapter indices
    into the {"aslot", "blocks"} dict kvcache.forward_step gathers."""
    if not pool or lora is None:
        return lora
    return {"aslot": state["aslot"], "blocks": lora}


def make_prefill_fn(cfg: ModelConfig, *, lora_scale: float = 1.0,
                    draft_cfg: Optional[ModelConfig] = None) -> Callable:
    """``prefill_step(params, prompt[1, L], prompt_len[1], lora) ->
    (first_tok[1], cache_row)`` — full-bucket-width prefill with lens=0:
    garbage K/V past the prompt sit at positions strictly above every
    query's until decode overwrites them (the kvcache.py invariant).

    With ``draft_cfg`` (speculative decoding) the signature grows a
    draft-params arg and the draft model's cache row rides along:
    ``spec_prefill(params, draft_params, prompt, prompt_len, lora) ->
    (first_tok, cache_row, dcache_row)`` — still ONE executable."""
    def _target_prefill(params, prompt, prompt_len, lora):
        B, L = prompt.shape
        cache = init_cache(cfg, B, L)
        logits, cache = forward_step(
            params, prompt, cfg, cache, jnp.zeros((B,), jnp.int32),
            lora=lora, lora_scale=lora_scale)
        idx = jnp.clip(prompt_len - 1, 0, L - 1)
        first = jnp.argmax(
            jnp.take_along_axis(logits, idx[:, None, None],
                                axis=1)[:, 0, :],
            axis=-1).astype(jnp.int32)
        return first, cache

    if draft_cfg is None:
        def prefill_step(params, prompt, prompt_len, lora):
            return _target_prefill(params, prompt, prompt_len, lora)
        return prefill_step

    def spec_prefill_step(params, draft_params, prompt, prompt_len, lora):
        first, cache = _target_prefill(params, prompt, prompt_len, lora)
        B, L = prompt.shape
        dcache = init_cache(draft_cfg, B, L)
        # the draft never carries adapters — it proposes, the (LoRA'd)
        # target disposes; only its K/V matter here
        _, dcache = forward_step(
            draft_params, prompt, draft_cfg, dcache,
            jnp.zeros((B,), jnp.int32))
        return first, cache, dcache
    return spec_prefill_step


def make_decode_fn(cfg: ModelConfig, eos_ids: Sequence[int], *,
                   lora_scale: float = 1.0, pool: bool = False
                   ) -> Callable:
    """``decode_step(params, state, lora) -> state`` — one iteration for
    the whole slot batch. The per-slot update rule is EXACTLY
    ``greedy_generate_cached``'s loop body (write the pending token,
    forward one position, argmax, advance), with the loop-count bound
    expressed as the per-slot absolute ``stop`` position — so a slot's
    token stream is bit-identical to a batch-1 greedy decode.

    ``pool=True`` (multi-tenant): ``lora`` is the stacked adapter-pool
    blocks and the state carries per-slot ``aslot`` indices — one shared
    executable decodes a mixed-tenant batch (ops/lora_batched.py)."""
    eos_host = np.asarray(list(eos_ids) or [-1], np.int32)

    def decode_step(params, state, lora):
        buf, lens, stop = state["buf"], state["lens"], state["stop"]
        active, cur, cache = state["active"], state["cur"], state["cache"]
        L = buf.shape[1]
        eos = jnp.asarray(eos_host)
        write_pos = jnp.clip(lens, 0, L - 1)
        buf = jnp.where(
            active[:, None] & (jnp.arange(L)[None, :] ==
                               write_pos[:, None]),
            cur[:, None], buf)
        logits, cache = forward_step(
            params, cur[:, None], cfg, cache, lens,
            lora=_resolve_lora(state, lora, pool),
            lora_scale=lora_scale)
        next_tok = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        now_eos = jnp.any(cur[:, None] == eos[None, :], axis=-1)
        new_lens = jnp.where(~active | (lens >= L), lens, lens + 1)
        new_active = active & ~now_eos & (new_lens < stop)
        out = {"buf": buf, "lens": new_lens, "stop": stop,
               "active": new_active, "cur": next_tok, "cache": cache}
        if pool:
            out["aslot"] = state["aslot"]
        return out
    return decode_step


def make_spec_decode_fn(cfg: ModelConfig, draft_cfg: ModelConfig,
                        eos_ids: Sequence[int], spec_k: int, *,
                        lora_scale: float = 1.0, pool: bool = False
                        ) -> Callable:
    """ONE fused speculative iteration (``spec_decode(params,
    draft_params, state, lora) -> state``): the draft proposes
    ``spec_k`` tokens via a scanned T=1 loop on its own cache, the
    target verifies all ``spec_k + 1`` positions in a single batched
    forward, and a vectorized acceptance rule commits the longest
    draft prefix the target agrees with (plus the target's one bonus
    token) — per slot, per iteration.

    Greedy-acceptance equivalence (drilled bitwise in tests): the
    committed stream is EXACTLY what the T=1 rule above would have
    produced, because a draft token is only consumed when it equals the
    target argmax given the identical committed prefix; the first
    disagreement is replaced by the target's own argmax and everything
    after it is discarded (the cache rows it wrote are overwritten by
    the next iteration's ``spec_k + 1``-wide scatter before any query
    can attend to them). The draft model only steers HOW MANY tokens
    commit per iteration — never WHICH.

    Bucket headroom contract: the engine routes speculative requests
    with ``max_new_tokens + spec_k`` (submit()), so every active slot
    satisfies ``stop + spec_k <= width`` and the verify window never
    clamps into committed history."""
    eos_host = np.asarray(list(eos_ids) or [-1], np.int32)
    K = int(spec_k)

    def spec_decode_step(params, draft_params, state, lora):
        buf, lens, stop = state["buf"], state["lens"], state["stop"]
        active, cur = state["active"], state["cur"]
        cache, dcache = state["cache"], state["dcache"]
        B, L = buf.shape
        eos = jnp.asarray(eos_host)

        # -- draft phase: K sequential single-token proposals ----------
        def draft_body(carry, _):
            dc, tok, pos = carry
            lg, dc = forward_step(draft_params, tok[:, None], draft_cfg,
                                  dc, pos)
            nxt = jnp.argmax(lg[:, 0, :], axis=-1).astype(jnp.int32)
            return (dc, nxt, pos + 1), tok
        (dcache, last, _), toks = jax.lax.scan(
            draft_body, (dcache, cur, lens), None, length=K)
        # tokens_in[:, 0] = the committed pending token; 1..K = drafts
        tokens_in = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)  # [B, K+1]

        # -- verify: one batched target forward over all K+1 ----------
        logits, cache = forward_step(
            params, tokens_in, cfg, cache, lens,
            lora=_resolve_lora(state, lora, pool), lora_scale=lora_scale)
        tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]

        # -- vectorized greedy acceptance ------------------------------
        match = (tokens_in[:, 1:] == tgt[:, :-1]).astype(jnp.int32)
        accepted = jnp.cumprod(match, axis=1).sum(axis=1)   # leading run
        consumed = accepted + 1                   # + the pending token
        # the sequential rule deactivates ON the first consumed eos —
        # nothing after it may commit
        is_eos = jnp.any(tokens_in[:, :, None] == eos[None, None, :],
                         axis=-1)
        has_eos = jnp.any(is_eos, axis=1)
        eos_cut = jnp.where(has_eos, jnp.argmax(is_eos, axis=1) + 1,
                            K + 1)
        m = jnp.minimum(jnp.minimum(consumed, eos_cut), stop - lens)
        m = jnp.where(active, jnp.maximum(m, 0), 0)         # [B]

        rel = jnp.arange(L, dtype=jnp.int32)[None, :] - lens[:, None]
        write = (rel >= 0) & (rel < m[:, None]) & active[:, None]
        vals = jnp.take_along_axis(tokens_in, jnp.clip(rel, 0, K), axis=1)
        buf = jnp.where(write, vals, buf)
        new_lens = lens + m
        consumed_eos = has_eos & (jnp.argmax(is_eos, axis=1) < m)
        new_active = active & ~consumed_eos & (new_lens < stop)
        # next pending token = the target's argmax after the last
        # committed token (the "bonus" token on full acceptance)
        nxt = jnp.take_along_axis(
            tgt, jnp.clip(m - 1, 0, K)[:, None], axis=1)[:, 0]
        new_cur = jnp.where(active & (m > 0), nxt, cur)
        out = {"buf": buf, "lens": new_lens, "stop": stop,
               "active": new_active, "cur": new_cur, "cache": cache,
               "dcache": dcache}
        if pool:
            out["aslot"] = state["aslot"]
        return out
    return spec_decode_step


def make_insert_fn(*, multi_lora: bool = False, spec: bool = False
                   ) -> Callable:
    """``insert_slot(state, slot, cache_row, prompt_row, prompt_len,
    stop, first_tok[, dcache_row][, aslot]) -> state`` — admit one
    prefilled request into slot ``slot`` (a traced scalar: one compile
    serves every slot). ``spec`` adds the draft cache row; ``multi_lora``
    adds the request's adapter slot index (both trailing, in that
    order)."""
    def insert_slot(state, slot, cache_row, prompt_row, prompt_len,
                    stop, first_tok, *extra):
        new_state = dict(state)
        new_state["cache"] = insert_cache_slot(state["cache"], slot,
                                               cache_row)
        new_state["buf"] = jax.lax.dynamic_update_slice_in_dim(
            state["buf"], prompt_row, slot, axis=0)
        new_state["lens"] = state["lens"].at[slot].set(prompt_len[0])
        new_state["stop"] = state["stop"].at[slot].set(stop[0])
        new_state["active"] = state["active"].at[slot].set(True)
        new_state["cur"] = state["cur"].at[slot].set(first_tok[0])
        i = 0
        if spec:
            new_state["dcache"] = insert_cache_slot(state["dcache"],
                                                    slot, extra[i])
            i += 1
        if multi_lora:
            new_state["aslot"] = state["aslot"].at[slot].set(extra[i][0])
        return new_state
    return insert_slot


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Slot:
    rid: str
    prompt_len: int
    submit_t: float
    first_token_t: float
    # span bookkeeping (obs/trace.py): host perf_counter stamps the
    # admit path already took — the request's lifecycle spans are
    # emitted retroactively at retirement from these, so tracing adds
    # zero work to the decode loop
    prefill_t0: float = 0.0
    decodes0: int = 0
    adapter_id: Optional[str] = None


class _BucketRuntime:
    """Per-bucket state + slot bookkeeping (the host-side half; every
    device-side transition happens in the three compiled steps)."""

    def __init__(self, width: int, max_batch: int):
        self.width = width
        self.max_batch = max_batch
        self.state: Optional[Dict[str, Any]] = None   # device pytree
        self.slots: List[Optional[_Slot]] = [None] * max_batch
        self.host_active = np.zeros((max_batch,), bool)
        self.decodes = 0            # decode iterations run so far
        # last fetched per-slot lens — the speculative acceptance
        # ledger is pure host arithmetic on the control leaves the
        # step loop already fetches (no extra device traffic)
        self.prev_lens = np.zeros((max_batch,), np.int64)

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def occupied(self) -> int:
        return sum(s is not None for s in self.slots)


class BatchEngine:
    """The in-process continuous-batching engine (the CPU-mesh tests
    and ``BENCH_MODE=serve`` drive this directly; rayint/serving.py
    wraps it in a Ray actor).

    ``params`` may be a plain or quantized tree, optionally mesh-placed;
    ``plan.serve_quant`` quantizes at construction when asked. All
    executables build eagerly on first use of a bucket through
    ``compile_step_with_plan`` — with ``sidecar_dir`` set they
    AOT-serialize there, and a fresh engine pointed at the same dir
    deserializes instead of compiling (cold-start-in-seconds path).
    """

    def __init__(self, params: Any, cfg: ModelConfig, *,
                 plan: Optional[ExecutionPlan] = None,
                 eos_ids: Sequence[int] = (),
                 lora: Optional[Any] = None, lora_scale: float = 1.0,
                 adapters: Optional[Any] = None,
                 draft: Optional[Tuple[Any, ModelConfig]] = None,
                 sidecar_dir: Optional[str] = None,
                 heartbeat_fn: Optional[Callable[[int], None]] = None):
        self.plan = plan if plan is not None else serve_plan()
        self.cfg = cfg
        self.params = quantize_for_serving(params, self.plan.serve_quant)
        if adapters is not None and lora is not None:
            raise ValueError(
                "pass either a single lora= adapter or a multi-tenant "
                "adapters= pool, not both")
        self.lora = lora
        self.pool = adapters
        self._pool_mode = adapters is not None
        self.eos_ids = tuple(int(e) for e in eos_ids)
        self.max_batch = self.plan.max_batch
        # speculative decoding: "self" drafts with the target's own
        # (already quantized) weights — the zero-infrastructure arm
        # whose accept-all behavior witnesses verify/decode equality;
        # "distilled" takes a caller-provided small model
        if self.plan.spec_draft == "self":
            self._draft: Optional[Tuple[Any, ModelConfig]] = (
                self.params, cfg)
        elif self.plan.spec_draft == "distilled":
            if draft is None:
                raise ValueError(
                    "SPEC_DRAFT=distilled needs draft=(draft_params, "
                    "draft_cfg) — train side produces small configs")
            dparams, dcfg = draft
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size} — proposals must be target tokens")
            self._draft = (quantize_for_serving(
                dparams, self.plan.serve_quant), dcfg)
        else:
            self._draft = None
        self.spec_k = int(self.plan.spec_k) if self._draft else 0
        self.buckets = [b for b in self.plan.bucket_list()
                        if b <= cfg.max_seq_len
                        and (self._draft is None
                             or b <= self._draft[1].max_seq_len)]
        if not self.buckets:
            raise ValueError(
                f"no declared bucket {self.plan.bucket_list()} fits "
                f"max_seq_len={cfg.max_seq_len}")
        self.sidecar_dir = sidecar_dir
        self._heartbeat = heartbeat_fn
        dcfg = self._draft[1] if self._draft else None
        self._prefill_fn = make_prefill_fn(cfg, lora_scale=lora_scale,
                                           draft_cfg=dcfg)
        if self._draft is not None:
            self._decode_fn = make_spec_decode_fn(
                cfg, dcfg, self.eos_ids, self.plan.spec_k,
                lora_scale=lora_scale, pool=self._pool_mode)
        else:
            self._decode_fn = make_decode_fn(
                cfg, self.eos_ids, lora_scale=lora_scale,
                pool=self._pool_mode)
        self._insert_fn = make_insert_fn(multi_lora=self._pool_mode,
                                         spec=self._draft is not None)
        # host-side whole-prompt prefix/KV reuse (plan.prefix_cache):
        # (bucket, adapter_id, prompt-token hash) -> the prefill outputs
        # (first token + cache row(s)); bounded LRU. Insert does NOT
        # donate the row, so a memoized row serves any number of slots.
        from collections import OrderedDict
        self._prefix_memo: Any = OrderedDict()
        self.prefix_hits = 0
        self.spec_proposed = 0      # draft tokens offered to the target
        self.spec_accepted = 0      # draft tokens the target agreed with
        self._compiled: Dict[Tuple[str, int], Callable] = {}
        self._runtimes: Dict[int, _BucketRuntime] = {}
        self._pending: List[Request] = []
        self._pending_bucket: Dict[str, int] = {}
        self._completions: Dict[str, Completion] = {}
        self._submit_t: Dict[str, float] = {}
        self.iterations = 0
        self.refills = 0            # admissions into a non-fresh batch
        self.completed_total = 0    # process-lifetime completion count
        # rolling windows (one entry per decode iteration): a replica
        # serving for hours must not grow per iteration; p50/p99 and
        # occupancy reflect the most recent traffic
        from collections import deque
        self._token_latencies: Any = deque(maxlen=10_000)
        self._occupancy: Any = deque(maxlen=10_000)

    # -- executables ---------------------------------------------------

    def _sidecar(self, kind: str, width: int) -> Optional[str]:
        if not self.sidecar_dir:
            return None
        return os.path.join(self.sidecar_dir,
                            f"serve_{kind}_b{width}.bin")

    def _abstract_lora(self):
        """Abstract shape of the decode/prefill ``lora`` argument: the
        single adapter tree, the stacked pool blocks (multi-tenant), or
        None."""
        from gke_ray_train_tpu.perf.cache import abstractify
        if self._pool_mode:
            return abstractify(self.pool.blocks)
        return abstractify(self.lora) if self.lora is not None else None

    def _decode_lora_arg(self):
        """The concrete ``lora`` argument every decode call passes —
        re-read from the pool each call so admission-time tenant churn
        (register/evict) is visible without recompiling."""
        return self.pool.blocks if self._pool_mode else self.lora

    def _get(self, kind: str, width: int) -> Callable:
        key = (kind, width)
        fn = self._compiled.get(key)
        if fn is not None:
            return fn
        from gke_ray_train_tpu.perf.cache import abstractify
        aparams = abstractify(self.params)
        alora = self._abstract_lora()
        spec = self._draft is not None
        adraft = abstractify(self._draft[0]) if spec else None
        dcfg = self._draft[1] if spec else None
        astate = jax.eval_shape(
            partial(init_serve_state, self.cfg, self.max_batch, width,
                    multi_lora=self._pool_mode, draft_cfg=dcfg))
        if kind == "decode":
            args = (aparams, adraft, astate, alora) if spec else \
                (aparams, astate, alora)
            fn = compile_step_with_plan(
                self.plan, None, self._decode_fn, *args,
                donate_argnums=(2,) if spec else (1,),
                sidecar=self._sidecar(kind, width),
                label=f"serve_decode_b{width}",
                surface="serve")
        elif kind == "prefill":
            aprompt = jax.ShapeDtypeStruct((1, width), jnp.int32)
            alen = jax.ShapeDtypeStruct((1,), jnp.int32)
            aplora = alora
            if self._pool_mode:
                # prefill runs at batch 1: its aslot is a length-1 vec
                aplora = {"aslot": jax.ShapeDtypeStruct((1,), jnp.int32),
                          "blocks": alora}
            args = (aparams, adraft, aprompt, alen, aplora) if spec \
                else (aparams, aprompt, alen, aplora)
            fn = compile_step_with_plan(
                self.plan, None, self._prefill_fn, *args,
                donate_argnums=(), sidecar=self._sidecar(kind, width),
                label=f"serve_prefill_b{width}",
                surface="serve")
        else:  # insert
            row_cache = jax.eval_shape(
                partial(init_cache, self.cfg, 1, width))
            scalars = jax.ShapeDtypeStruct((1,), jnp.int32)
            extra = []
            if spec:
                extra.append(jax.eval_shape(
                    partial(init_cache, dcfg, 1, width)))
            if self._pool_mode:
                extra.append(scalars)
            fn = compile_step_with_plan(
                self.plan, None, self._insert_fn,
                astate, jax.ShapeDtypeStruct((), jnp.int32), row_cache,
                jax.ShapeDtypeStruct((1, width), jnp.int32),
                scalars, scalars, scalars, *extra,
                # the batch-1 cache row is NOT donated: its [1, L] rows
                # cannot alias into the pooled [B, L] buffer, and jax
                # warns on every unusable donation (this is also what
                # lets the prefix cache reuse a memoized row)
                donate_argnums=(0,), sidecar=self._sidecar(kind, width),
                label=f"serve_insert_b{width}",
                surface="serve")
        self._compiled[key] = fn
        return fn

    def set_heartbeat(self, fn: Optional[Callable[[int], None]]) -> None:
        """(Re)wire the per-iteration liveness beat — the deployment
        (rayint/serving.py) points this at a Supervisor actor after the
        engine is built, so a replica wedged mid-decode is detected by
        the same board shape that watches training ranks."""
        self._heartbeat = fn

    def executable_info(self) -> Dict[str, Dict[str, Any]]:
        """Build provenance per compiled executable ("deserialized" |
        "compiled" | absent for plain-jit) — the warm-start tests'
        witness that a fresh replica recompiled nothing."""
        return {f"{k}_b{w}": dict(getattr(fn, "info", {}))
                for (k, w), fn in self._compiled.items()}

    def decode_cost_report(self, width: Optional[int] = None):
        """StepCostReport of the decode executable (perf/costs.py) —
        None when the executable cannot be introspected (plain jit or a
        deserialized blob without analyses)."""
        from gke_ray_train_tpu.perf.costs import step_cost_report
        width = width or self.buckets[0]
        fn = self._get("decode", width)
        compiled = getattr(fn, "_compiled", None)
        if compiled is None:
            return None
        try:
            return step_cost_report(compiled,
                                    tokens_per_step=self.max_batch)
        except Exception as e:  # noqa: BLE001 - introspection best-effort
            logger.debug("decode cost report unavailable: %s", e)
            return None

    def warm_up(self, widths: Optional[Sequence[int]] = None) -> None:
        """Build (or deserialize) every executable for the given buckets
        up front — the replica cold-start path, so the first request
        pays dispatch latency, not compile latency."""
        for w in widths or self.buckets:
            for kind in ("prefill", "decode", "insert"):
                self._get(kind, w)
        # obs: record the cold-start provenance (deserialized vs
        # compiled, per executable) on the run's event stream
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        obs_runtime.emit("serve_start",
                         executables=self.executable_info())

    # -- request intake ------------------------------------------------

    def submit(self, request: Request) -> int:
        """Queue a request; returns the bucket it will run in. Raises
        ValueError when no declared bucket fits (reject up front — a
        fixed-shape executable must never truncate silently)."""
        # every per-request structure is keyed by rid alone: a
        # duplicate (e.g. a client retry racing its original) would
        # overwrite the first request's routing and double-pop its
        # completion — reject it while the first is still in flight
        # (_pending_bucket spans submit→retire) or unretrieved
        if request.rid in self._pending_bucket \
                or request.rid in self._completions:
            raise ValueError(f"request {request.rid}: rid already in "
                             "flight or unretrieved — rids must be "
                             "unique per engine")
        ids = np.asarray(request.token_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError(f"request {request.rid}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(f"request {request.rid}: max_new_tokens="
                             f"{request.max_new_tokens} must be >= 1")
        if request.adapter_id is not None and not self._pool_mode:
            raise ValueError(
                f"request {request.rid}: adapter_id="
                f"{request.adapter_id!r} but the engine has no adapter "
                "pool — construct BatchEngine(adapters=AdapterPool(...))")
        # speculative headroom: the verify window writes spec_k + 1
        # cache positions from ``lens``, so the bucket must hold
        # stop + spec_k — route (and reject/truncate) as if the request
        # asked for max_new_tokens + spec_k
        budget = request.max_new_tokens + self.spec_k
        # reject BEFORE truncating: even a 1-token prompt cannot fit —
        # truncate_prompt would log a misleading head-DROPPED warning
        # for a request that is rejected anyway
        if budget + 1 > self.buckets[-1]:
            raise ValueError(
                f"request {request.rid}: max_new_tokens="
                f"{request.max_new_tokens}"
                + (f" + spec_k={self.spec_k}" if self.spec_k else "")
                + f" + a 1-token prompt needs {budget + 1} slots but "
                f"the largest usable bucket is {self.buckets[-1]} — "
                "lower max_new_tokens or declare a larger bucket")
        max_prompt = max(self.buckets[-1] - budget, 1)
        ids = truncate_prompt(ids, max_prompt,
                              label=f"request {request.rid} prompt")
        bucket = pick_bucket(len(ids), budget,
                             self.buckets, self.cfg.max_seq_len)
        # obs: the admitted request's total length (post-truncation
        # prompt + decode budget) into the shared metrics registry —
        # the workload-shape histogram bucket declarations are tuned
        # against. No-op when obs is off.
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        if obs_runtime.active() is not None:
            try:
                obs_runtime.registry().histogram("request_len").observe(
                    float(len(ids) + request.max_new_tokens))
            except Exception:  # noqa: BLE001 - telemetry must not reject
                pass
        request = dataclasses.replace(request, token_ids=ids)
        self._pending.append(request)
        self._pending_bucket[request.rid] = bucket
        self._submit_t[request.rid] = time.perf_counter()
        return bucket

    # -- the iteration loop --------------------------------------------

    def _admit(self) -> None:
        """Slot-level admission: fill every free slot whose bucket has a
        pending request — prefill at batch 1, insert into the pool."""
        still_pending: List[Request] = []
        for req in self._pending:
            width = self._pending_bucket[req.rid]
            rt = self._runtimes.get(width)
            if rt is None:
                rt = self._runtimes[width] = _BucketRuntime(
                    width, self.max_batch)
            free = rt.free_slots()
            if not free:
                still_pending.append(req)
                continue
            slot = free[0]
            if self._pool_mode:
                # resolve (and pin) the tenant BEFORE any state work —
                # a pool with every slot pinned is a transient
                # condition (requests retire), not an error: keep the
                # request queued and retry next iteration
                from gke_ray_train_tpu.serve.adapters import (
                    AdapterPoolPinned)
                try:
                    aslot_idx = self.pool.acquire(req.adapter_id)
                except AdapterPoolPinned:
                    still_pending.append(req)
                    continue
            if rt.state is None:
                rt.state = init_serve_state(
                    self.cfg, self.max_batch, width,
                    multi_lora=self._pool_mode,
                    draft_cfg=self._draft[1] if self._draft else None)
            elif rt.occupied() > 0 and rt.decodes > 0:
                # a TRUE mid-batch refill: decode already ran for this
                # batch and other sequences are live (the initial
                # fill-up wave before the first decode is not a refill)
                self.refills += 1
            buf, plen = form_prompt_buffer(req.token_ids, width)
            stop = min(plen + req.max_new_tokens, width)
            t_prefill0 = time.perf_counter()
            out = self._prefill_outputs(req, width, buf, plen)
            first = out[0]
            # the first decoded token exists only once prefill
            # materializes — on an async backend stamping at dispatch
            # would measure enqueue latency, not time-to-first-token
            jax.block_until_ready(first)
            extra = list(out[2:])   # dcache_row when speculative
            if self._pool_mode:
                extra.append(jnp.asarray([aslot_idx], jnp.int32))
            rt.state = self._get("insert", width)(
                rt.state, jnp.asarray(slot, jnp.int32), out[1],
                jnp.asarray(buf), jnp.asarray([plen], jnp.int32),
                jnp.asarray([stop], jnp.int32), first, *extra)
            now = time.perf_counter()
            rt.slots[slot] = _Slot(req.rid, plen,
                                   self._submit_t[req.rid], now,
                                   prefill_t0=t_prefill0,
                                   decodes0=rt.decodes,
                                   adapter_id=req.adapter_id)
            rt.host_active[slot] = True
            rt.prev_lens[slot] = plen
        self._pending = still_pending

    _PREFIX_MEMO_MAX = 64

    def _prefill_outputs(self, req: Request, width: int,
                         buf: np.ndarray, plen: int) -> tuple:
        """Run (or reuse) the batch-1 prefill for one admission:
        ``(first_tok, cache_row[, dcache_row])``.

        Prefix/KV reuse (plan.prefix_cache) memoizes WHOLE post-
        truncation prompts by token hash, per (bucket, tenant): the
        common shared-system-prompt traffic pattern re-admits the same
        prefix verbatim, and replaying the memoized cache row through
        the (non-donating) insert executable is bitwise the cold
        prefill by construction — the same buffers go in. Partial-
        prefix splicing is deliberately out of scope: reusing a strict
        prefix would change the prefill width and break the bitwise
        contract."""
        key = None
        if self.plan.prefix_cache:
            import hashlib
            digest = hashlib.sha1(
                np.ascontiguousarray(buf).tobytes()).hexdigest()
            # plen rides in the key: a prompt that genuinely ends in
            # token id 0 pads to the same buffer as a shorter one
            key = (width, req.adapter_id or "", int(plen), digest)
            hit = self._prefix_memo.get(key)
            if hit is not None:
                self._prefix_memo.move_to_end(key)
                self.prefix_hits += 1
                return hit
        lora_arg = self.lora
        if self._pool_mode:
            lora_arg = {
                "aslot": jnp.asarray(
                    [self.pool.slot_of(req.adapter_id)], jnp.int32),
                "blocks": self.pool.blocks}
        args = (self.params, jnp.asarray(buf),
                jnp.asarray([plen], jnp.int32), lora_arg)
        if self._draft is not None:
            args = (args[0], self._draft[0]) + args[1:]
        out = self._get("prefill", width)(*args)
        if key is not None:
            self._prefix_memo[key] = out
            while len(self._prefix_memo) > self._PREFIX_MEMO_MAX:
                self._prefix_memo.popitem(last=False)
        return out

    def _collect(self, rt: _BucketRuntime, active: np.ndarray,
                 lens: np.ndarray, buf: Optional[np.ndarray]) -> None:
        """Retire slots that went inactive this iteration."""
        now = time.perf_counter()
        for i, slot in enumerate(rt.slots):
            if slot is None or active[i]:
                continue
            # np.array COPIES: device_get can return a zero-copy view of
            # the device buffer (CPU backend), and the state is DONATED —
            # without the copy, a later admit/decode reuses that buffer
            # and the retired completion's tokens mutate under it
            row = np.array(buf[i])
            length = int(lens[i])
            gen = row[slot.prompt_len:length]
            reason = ("eos" if self.eos_ids and len(gen)
                      and int(gen[-1]) in self.eos_ids else "length")
            self._completions[slot.rid] = Completion(
                rid=slot.rid, tokens=row, prompt_len=slot.prompt_len,
                length=length, bucket=rt.width, finish_reason=reason,
                submit_s=slot.submit_t,
                first_token_s=slot.first_token_t - slot.submit_t,
                done_s=now - slot.submit_t,
                adapter_id=slot.adapter_id)
            self._trace_request(rt, slot, now, length, reason)
            if self._pool_mode:
                # unpin the tenant — its slot becomes evictable once no
                # in-flight request decodes against it
                self.pool.release(slot.adapter_id)
            rt.slots[i] = None
            rt.host_active[i] = False
            self.completed_total += 1
            # pre-completion bookkeeping dies with the request — a
            # long-lived replica must not grow per served request
            self._submit_t.pop(slot.rid, None)
            self._pending_bucket.pop(slot.rid, None)

    def _trace_request(self, rt: _BucketRuntime, slot: _Slot,
                       now: float, length: int, reason: str) -> None:
        """Emit the request's lifecycle spans (obs/trace.py) at
        retirement — the "where did my p99 go" decomposition: enqueue
        (submit → prefill dispatch), prefill (dispatch → first token
        materialized), decode (admission → retire, with the iteration
        count it shared with the continuous batch). Everything here is
        host floats the engine already stamped; emission is once per
        COMPLETED request, never per decode iteration, so the one-
        ``device_get``-per-iteration hot-path contract holds. No-op
        when obs/tracing is off."""
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        if not obs_runtime.tracing():
            return
        anchor = time.time()      # map perf_counter diffs to wall ts

        def t1_of(pc: float) -> float:
            return anchor - (now - pc)

        req_id = obs_runtime.span_add(
            "serve_request", now - slot.submit_t, t1=anchor,
            rid=slot.rid, bucket=rt.width, prompt_len=slot.prompt_len,
            generated=int(length - slot.prompt_len),
            finish_reason=reason)
        if req_id is None:
            # the parent write failed (IO): children with parent=None
            # would re-parent under the attempt span and read as
            # attempt-level path leaves — a lossy trace must stay
            # consistent, so drop the orphans with their parent
            return
        obs_runtime.span_add(
            "serve_enqueue", slot.prefill_t0 - slot.submit_t,
            t1=t1_of(slot.prefill_t0), parent_id=req_id, rid=slot.rid)
        obs_runtime.span_add(
            "serve_prefill", slot.first_token_t - slot.prefill_t0,
            t1=t1_of(slot.first_token_t), parent_id=req_id,
            rid=slot.rid)
        obs_runtime.span_add(
            "serve_decode", now - slot.first_token_t, t1=anchor,
            parent_id=req_id, rid=slot.rid,
            iterations=int(rt.decodes - slot.decodes0))

    def step(self) -> int:
        """One engine iteration: admit into free slots, then run ONE
        decode step per live bucket. Returns the number of slots still
        active across buckets (0 = drained)."""
        self._admit()
        total_active = 0
        for rt in self._runtimes.values():
            if rt.occupied() == 0:
                continue
            t0 = time.perf_counter()
            fn = self._get("decode", rt.width)
            if self._draft is not None:
                rt.state = fn(self.params, self._draft[0], rt.state,
                              self._decode_lora_arg())
            else:
                rt.state = fn(self.params, rt.state,
                              self._decode_lora_arg())
            rt.decodes += 1
            # ONE batched fetch of the small control leaves per
            # iteration (shardlint TPU001: never per-slot round-trips);
            # buf rides along only when a slot may have finished
            active, lens = jax.device_get(
                (rt.state["active"], rt.state["lens"]))
            dt = time.perf_counter() - t0
            n_act = int(np.sum(rt.host_active))
            if self.spec_k:
                # acceptance ledger from the lens deltas the fetch
                # above already paid for: each previously-active slot
                # was offered spec_k drafts and committed (delta - 1)
                # of them (the +1 being the target's own bonus token)
                was = rt.host_active
                deltas = np.asarray(lens, np.int64)[was] \
                    - rt.prev_lens[was]
                self.spec_proposed += self.spec_k * n_act
                self.spec_accepted += int(
                    np.clip(deltas - 1, 0, self.spec_k).sum())
            rt.prev_lens = np.asarray(lens, np.int64).copy()
            self._token_latencies.append(dt)
            self._occupancy.append(n_act / self.max_batch)
            total_active += int(np.sum(active))
            if bool(np.any(rt.host_active & ~active)):
                buf = jax.device_get(rt.state["buf"])
                self._collect(rt, active, lens, buf)
        self.iterations += 1
        if self._heartbeat is not None:
            try:
                self._heartbeat(self.iterations)
            except Exception as e:  # noqa: BLE001 - liveness best-effort
                logger.debug("serve heartbeat dropped: %s", e)
        return total_active + len(self._pending)

    def run_until_drained(self, requests: Sequence[Request] = ()
                          ) -> List[Completion]:
        """Submit ``requests`` and iterate until every queued request
        completed; returns completions in submit order. Returned
        completions are RELEASED from the engine (a long-lived replica
        calls this per request batch and must not accumulate every
        buffer it ever served) — use :meth:`completion` + manual
        :meth:`step` when you need them retained."""
        for r in requests:
            self.submit(r)
        want = [r.rid for r in requests]
        while self.step() > 0:
            pass
        # obs: serving latency/occupancy into the shared metrics
        # registry + one `serve_drained` event (off the decode loop —
        # once per drain, never per iteration; no-op when obs is off)
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        if obs_runtime.active() is not None:
            obs_runtime.active().note_serve(self.stats())
        if want:
            return [self._completions.pop(rid) for rid in want]
        out = list(self._completions.values())
        self._completions.clear()
        return out

    def completion(self, rid: str) -> Optional[Completion]:
        return self._completions.get(rid)

    # -- reporting -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Serving statistics: iteration count, batch occupancy and the
        per-token latency distribution (one decode iteration produces
        one token per active slot, so the iteration latency IS the
        per-token latency)."""
        lat = sorted(self._token_latencies)

        def pct(p):
            if not lat:
                return 0.0
            return lat[min(int(p / 100.0 * len(lat)), len(lat) - 1)]

        out = {
            "iterations": self.iterations,
            "refills": self.refills,
            "completed": self.completed_total,
            "pending": len(self._pending),
            "batch_occupancy": (float(np.mean(self._occupancy))
                                if self._occupancy else 0.0),
            "p50_token_latency_s": pct(50),
            "p99_token_latency_s": pct(99),
            "plan_fingerprint": self.plan.fingerprint(),
        }
        # multi-tenant / reuse / speculation telemetry, present exactly
        # when the feature is on (obs export_serve_stats maps what it
        # finds; absent keys stay out of the metrics registry)
        if self.plan.prefix_cache:
            out["prefix_hits"] = self.prefix_hits
        if self.spec_k:
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
        if self._pool_mode:
            out.update(self.pool.stats())
        return out


def post_train_smoke(params: Any, cfg: ModelConfig,
                     plan: ExecutionPlan,
                     prompt_ids: Sequence[np.ndarray], *,
                     eos_ids: Sequence[int] = (),
                     lora: Optional[Any] = None, lora_scale: float = 1.0,
                     adapter_ids: Optional[Sequence[Optional[str]]] = None,
                     max_new_tokens: int = 32
                     ) -> Optional[Tuple[List[Completion], Dict[str, Any]]]:
    """The ``SERVE_AFTER_TRAIN`` hook both ray-jobs entries call after
    training: run the given already-tokenized prompts through a fresh
    continuous-batching engine on the just-trained weights (train →
    serve on the same process, ROADMAP #2's loop closed end to end).
    Returns (completions, stats), or None — with a loud warning — when
    no declared bucket fits the model or no prompt is usable; a smoke
    must degrade, not kill a finished training run."""
    usable = [b for b in plan.bucket_list() if b <= cfg.max_seq_len]
    if not usable:
        logger.warning(
            "SERVE_AFTER_TRAIN skipped: no declared bucket %s fits "
            "max_seq_len=%d (set DECODE_BUCKETS)", plan.bucket_list(),
            cfg.max_seq_len)
        return None
    prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompt_ids]
    prompts = [p for p in prompts if p.size]
    if not prompts:
        logger.warning("SERVE_AFTER_TRAIN skipped: no non-empty prompts")
        return None
    # one-shot in-process smoke: AOT off — the executables would build
    # from abstract UNSHARDED args while the just-trained params are
    # mesh-placed, so every AOT call would be rejected into the jit
    # fallback anyway (a wasted build + a noisy guard log per bucket)
    plan = dataclasses.replace(plan, aot_train_step=False)
    # the budget must fit the declared buckets (a tight DECODE_BUCKETS
    # would otherwise reject every request at submit) — a smoke clamps
    # rather than crash
    max_new_tokens = min(max_new_tokens, max(usable[-1] - 1, 1))
    # adapter_id-tagged smoke (ISSUE 17): when the run trained LoRA and
    # the caller tags requests, serve through a real AdapterPool so the
    # batched multi-tenant path is what the smoke exercises end to end
    # — every unique id maps to the just-trained adapter tree
    tags: List[Optional[str]] = list(adapter_ids or [])
    tags += [None] * (len(prompts) - len(tags))
    lora_kw: Dict[str, Any] = {"lora": lora, "lora_scale": lora_scale}
    if lora is not None and any(t is not None for t in tags):
        from gke_ray_train_tpu.serve.adapters import AdapterPool
        pool = AdapterPool.from_template(
            lora, max_adapters=max(plan.max_adapters,
                                   len({t for t in tags if t})))
        for aid in sorted({t for t in tags if t}):
            pool.register(aid, lora)
        lora_kw = {"adapters": pool, "lora_scale": lora_scale}
    elif lora is None:
        tags = [None] * len(prompts)
    t0 = time.perf_counter()
    try:
        engine = BatchEngine(params, cfg, plan=plan, eos_ids=eos_ids,
                             **lora_kw)
        comps = engine.run_until_drained([
            Request(rid=f"smoke{i}", token_ids=p,
                    max_new_tokens=max_new_tokens, adapter_id=tags[i])
            for i, p in enumerate(prompts)])
    except Exception:  # noqa: BLE001 - the degrade contract below
        # the whole point of this hook is "degrade, not kill": the
        # training run already SUCCEEDED — a serving-smoke failure is
        # loud telemetry, never a job failure
        logger.warning("SERVE_AFTER_TRAIN failed; training output is "
                       "unaffected", exc_info=True)
        return None
    stats = engine.stats()
    stats["wall_s"] = round(time.perf_counter() - t0, 3)
    stats["generated_tokens"] = int(
        sum(c.length - c.prompt_len for c in comps))
    stats["adapter_requests"] = sum(1 for t in tags if t is not None)
    logger.info(
        "SERVE_AFTER_TRAIN: %d request(s) -> %d tokens in %.2fs "
        "(occupancy %.2f, p50 %.1fms/token, plan %s)",
        len(comps), stats["generated_tokens"], stats["wall_s"],
        stats["batch_occupancy"], stats["p50_token_latency_s"] * 1e3,
        stats["plan_fingerprint"])
    return comps, stats
