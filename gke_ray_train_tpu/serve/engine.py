"""Continuous-batching inference engine (ROADMAP #2, Orca-style).

Iteration-level scheduling over bucketed, jitted executables:

- Every request is assigned the smallest declared length bucket that
  fits ``prompt_len + max_new_tokens`` (serve/bucketing.py). Per bucket
  the engine compiles exactly THREE executables — ``prefill_step``
  (``[1, L]``), ``decode_step`` (``[max_batch, 1]``) and
  ``insert_slot`` — so XLA compiles once per bucket, never per request.
- Admission is slot-level: a finished sequence's slot is refilled at
  the next decode iteration (prefill the newcomer at batch 1, then
  ``dynamic_update_slice`` its KV rows into the pooled cache) without
  flushing the batch — the surviving sequences' K/V bytes are
  untouched, which is what makes continuous-batched output bitwise
  identical to sequential ``greedy_generate_cached``.
- The KV pool is ``models/kvcache.py::init_cache`` at
  ``[max_batch, bucket]`` per bucket — the static-shape stand-in for
  vLLM's dynamic pages (XLA cannot page, it CAN bucket).
- Weights optionally serve quantized (``ops/quant.py``: int8/nf4).
- Cold start: executables build through ``compile_step_with_plan``
  (plan.py), so the persistent compile cache applies and — when a
  ``sidecar_dir`` is given — each executable AOT-serializes through
  ``perf/cache.py``; a fresh replica deserializes all three per bucket
  and reaches its first decoded token with zero new compilations.

Sequential-equivalence contract (drilled in tests/test_serve.py): the
per-slot update rule is exactly ``greedy_generate_cached``'s loop body,
attention masking contributes *exact zeros* for other slots' garbage
(ops/attention.py NEG_INF underflows), and prefill runs the full bucket
width — which equals the oracle's internal prefill width whenever the
bucket is a 128-multiple and ``max_new_tokens < 128``.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.kvcache import (
    forward_step, init_cache, insert_cache_slot)
from gke_ray_train_tpu.ops.quant import quantize_for_serving
from gke_ray_train_tpu.plan import ExecutionPlan, compile_step_with_plan
from gke_ray_train_tpu.serve.bucketing import (
    form_prompt_buffer, pick_bucket, truncate_prompt)

logger = logging.getLogger(__name__)


def serve_plan(**overrides: Any) -> ExecutionPlan:
    """The serving ExecutionPlan: env/config resolved like the trainer's
    (MAX_BATCH / DECODE_BUCKETS / SERVE_QUANT et al.), with kwarg
    overrides — so the engine shares the plan fingerprint/budget/
    plancheck machinery instead of growing a fifth knob dialect."""
    return ExecutionPlan.resolve(**overrides)


@dataclasses.dataclass
class Request:
    """One generation request. ``token_ids`` is the already-tokenized
    prompt (the engine is tokenizer-agnostic; rayint/serving.py holds
    the tokenizer)."""
    rid: str
    token_ids: np.ndarray
    max_new_tokens: int = 32


@dataclasses.dataclass
class Completion:
    rid: str
    tokens: np.ndarray          # full row buffer [bucket] incl. prompt
    prompt_len: int
    length: int                 # prompt_len + generated count
    bucket: int
    finish_reason: str          # "eos" | "length"
    submit_s: float = 0.0
    first_token_s: float = 0.0  # submit -> first decoded token
    done_s: float = 0.0         # submit -> completion

    @property
    def generated(self) -> np.ndarray:
        """The generated region (includes the EOS token when one was
        produced, mirroring ``greedy_generate_cached``'s buffer)."""
        return self.tokens[self.prompt_len:self.length]


# ---------------------------------------------------------------------------
# the pure step bodies (named so shardlint treats them as traced code)
# ---------------------------------------------------------------------------

def init_serve_state(cfg: ModelConfig, batch: int, width: int
                     ) -> Dict[str, Any]:
    """Zeroed per-bucket batch state: token buffer, per-slot cursors and
    the pooled KV cache. ``active`` starts all-False — empty slots run
    the decode step as masked no-ops until admission fills them."""
    return {
        "buf": jnp.zeros((batch, width), jnp.int32),
        "lens": jnp.zeros((batch,), jnp.int32),
        "stop": jnp.zeros((batch,), jnp.int32),
        "active": jnp.zeros((batch,), bool),
        "cur": jnp.zeros((batch,), jnp.int32),
        "cache": init_cache(cfg, batch, width),
    }


def make_prefill_fn(cfg: ModelConfig, *, lora_scale: float = 1.0
                    ) -> Callable:
    """``prefill_step(params, prompt[1, L], prompt_len[1], lora) ->
    (first_tok[1], cache_row)`` — full-bucket-width prefill with lens=0:
    garbage K/V past the prompt sit at positions strictly above every
    query's until decode overwrites them (the kvcache.py invariant)."""
    def prefill_step(params, prompt, prompt_len, lora):
        B, L = prompt.shape
        cache = init_cache(cfg, B, L)
        logits, cache = forward_step(
            params, prompt, cfg, cache, jnp.zeros((B,), jnp.int32),
            lora=lora, lora_scale=lora_scale)
        idx = jnp.clip(prompt_len - 1, 0, L - 1)
        first = jnp.argmax(
            jnp.take_along_axis(logits, idx[:, None, None],
                                axis=1)[:, 0, :],
            axis=-1).astype(jnp.int32)
        return first, cache
    return prefill_step


def make_decode_fn(cfg: ModelConfig, eos_ids: Sequence[int], *,
                   lora_scale: float = 1.0) -> Callable:
    """``decode_step(params, state, lora) -> state`` — one iteration for
    the whole slot batch. The per-slot update rule is EXACTLY
    ``greedy_generate_cached``'s loop body (write the pending token,
    forward one position, argmax, advance), with the loop-count bound
    expressed as the per-slot absolute ``stop`` position — so a slot's
    token stream is bit-identical to a batch-1 greedy decode."""
    eos_host = np.asarray(list(eos_ids) or [-1], np.int32)

    def decode_step(params, state, lora):
        buf, lens, stop = state["buf"], state["lens"], state["stop"]
        active, cur, cache = state["active"], state["cur"], state["cache"]
        L = buf.shape[1]
        eos = jnp.asarray(eos_host)
        write_pos = jnp.clip(lens, 0, L - 1)
        buf = jnp.where(
            active[:, None] & (jnp.arange(L)[None, :] ==
                               write_pos[:, None]),
            cur[:, None], buf)
        logits, cache = forward_step(
            params, cur[:, None], cfg, cache, lens,
            lora=lora, lora_scale=lora_scale)
        next_tok = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        now_eos = jnp.any(cur[:, None] == eos[None, :], axis=-1)
        new_lens = jnp.where(~active | (lens >= L), lens, lens + 1)
        new_active = active & ~now_eos & (new_lens < stop)
        return {"buf": buf, "lens": new_lens, "stop": stop,
                "active": new_active, "cur": next_tok, "cache": cache}
    return decode_step


def make_insert_fn() -> Callable:
    """``insert_slot(state, slot, cache_row, prompt_row, prompt_len,
    stop, first_tok) -> state`` — admit one prefilled request into slot
    ``slot`` (a traced scalar: one compile serves every slot)."""
    def insert_slot(state, slot, cache_row, prompt_row, prompt_len,
                    stop, first_tok):
        new_state = dict(state)
        new_state["cache"] = insert_cache_slot(state["cache"], slot,
                                               cache_row)
        new_state["buf"] = jax.lax.dynamic_update_slice_in_dim(
            state["buf"], prompt_row, slot, axis=0)
        new_state["lens"] = state["lens"].at[slot].set(prompt_len[0])
        new_state["stop"] = state["stop"].at[slot].set(stop[0])
        new_state["active"] = state["active"].at[slot].set(True)
        new_state["cur"] = state["cur"].at[slot].set(first_tok[0])
        return new_state
    return insert_slot


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Slot:
    rid: str
    prompt_len: int
    submit_t: float
    first_token_t: float
    # span bookkeeping (obs/trace.py): host perf_counter stamps the
    # admit path already took — the request's lifecycle spans are
    # emitted retroactively at retirement from these, so tracing adds
    # zero work to the decode loop
    prefill_t0: float = 0.0
    decodes0: int = 0


class _BucketRuntime:
    """Per-bucket state + slot bookkeeping (the host-side half; every
    device-side transition happens in the three compiled steps)."""

    def __init__(self, width: int, max_batch: int):
        self.width = width
        self.max_batch = max_batch
        self.state: Optional[Dict[str, Any]] = None   # device pytree
        self.slots: List[Optional[_Slot]] = [None] * max_batch
        self.host_active = np.zeros((max_batch,), bool)
        self.decodes = 0            # decode iterations run so far

    def free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def occupied(self) -> int:
        return sum(s is not None for s in self.slots)


class BatchEngine:
    """The in-process continuous-batching engine (the CPU-mesh tests
    and ``BENCH_MODE=serve`` drive this directly; rayint/serving.py
    wraps it in a Ray actor).

    ``params`` may be a plain or quantized tree, optionally mesh-placed;
    ``plan.serve_quant`` quantizes at construction when asked. All
    executables build eagerly on first use of a bucket through
    ``compile_step_with_plan`` — with ``sidecar_dir`` set they
    AOT-serialize there, and a fresh engine pointed at the same dir
    deserializes instead of compiling (cold-start-in-seconds path).
    """

    def __init__(self, params: Any, cfg: ModelConfig, *,
                 plan: Optional[ExecutionPlan] = None,
                 eos_ids: Sequence[int] = (),
                 lora: Optional[Any] = None, lora_scale: float = 1.0,
                 sidecar_dir: Optional[str] = None,
                 heartbeat_fn: Optional[Callable[[int], None]] = None):
        self.plan = plan if plan is not None else serve_plan()
        self.cfg = cfg
        self.params = quantize_for_serving(params, self.plan.serve_quant)
        self.lora = lora
        self.eos_ids = tuple(int(e) for e in eos_ids)
        self.max_batch = self.plan.max_batch
        self.buckets = [b for b in self.plan.bucket_list()
                        if b <= cfg.max_seq_len]
        if not self.buckets:
            raise ValueError(
                f"no declared bucket {self.plan.bucket_list()} fits "
                f"max_seq_len={cfg.max_seq_len}")
        self.sidecar_dir = sidecar_dir
        self._heartbeat = heartbeat_fn
        self._prefill_fn = make_prefill_fn(cfg, lora_scale=lora_scale)
        self._decode_fn = make_decode_fn(cfg, self.eos_ids,
                                         lora_scale=lora_scale)
        self._insert_fn = make_insert_fn()
        self._compiled: Dict[Tuple[str, int], Callable] = {}
        self._runtimes: Dict[int, _BucketRuntime] = {}
        self._pending: List[Request] = []
        self._pending_bucket: Dict[str, int] = {}
        self._completions: Dict[str, Completion] = {}
        self._submit_t: Dict[str, float] = {}
        self.iterations = 0
        self.refills = 0            # admissions into a non-fresh batch
        self.completed_total = 0    # process-lifetime completion count
        # rolling windows (one entry per decode iteration): a replica
        # serving for hours must not grow per iteration; p50/p99 and
        # occupancy reflect the most recent traffic
        from collections import deque
        self._token_latencies: Any = deque(maxlen=10_000)
        self._occupancy: Any = deque(maxlen=10_000)

    # -- executables ---------------------------------------------------

    def _sidecar(self, kind: str, width: int) -> Optional[str]:
        if not self.sidecar_dir:
            return None
        return os.path.join(self.sidecar_dir,
                            f"serve_{kind}_b{width}.bin")

    def _abstract_lora(self):
        from gke_ray_train_tpu.perf.cache import abstractify
        return abstractify(self.lora) if self.lora is not None else None

    def _get(self, kind: str, width: int) -> Callable:
        key = (kind, width)
        fn = self._compiled.get(key)
        if fn is not None:
            return fn
        from gke_ray_train_tpu.perf.cache import abstractify
        aparams = abstractify(self.params)
        alora = self._abstract_lora()
        astate = jax.eval_shape(
            partial(init_serve_state, self.cfg, self.max_batch, width))
        if kind == "decode":
            fn = compile_step_with_plan(
                self.plan, None, self._decode_fn,
                aparams, astate, alora,
                donate_argnums=(1,), sidecar=self._sidecar(kind, width),
                label=f"serve_decode_b{width}",
                surface="serve")
        elif kind == "prefill":
            aprompt = jax.ShapeDtypeStruct((1, width), jnp.int32)
            alen = jax.ShapeDtypeStruct((1,), jnp.int32)
            fn = compile_step_with_plan(
                self.plan, None, self._prefill_fn,
                aparams, aprompt, alen, alora,
                donate_argnums=(), sidecar=self._sidecar(kind, width),
                label=f"serve_prefill_b{width}",
                surface="serve")
        else:  # insert
            row_cache = jax.eval_shape(
                partial(init_cache, self.cfg, 1, width))
            scalars = jax.ShapeDtypeStruct((1,), jnp.int32)
            fn = compile_step_with_plan(
                self.plan, None, self._insert_fn,
                astate, jax.ShapeDtypeStruct((), jnp.int32), row_cache,
                jax.ShapeDtypeStruct((1, width), jnp.int32),
                scalars, scalars, scalars,
                # the batch-1 cache row is NOT donated: its [1, L] rows
                # cannot alias into the pooled [B, L] buffer, and jax
                # warns on every unusable donation
                donate_argnums=(0,), sidecar=self._sidecar(kind, width),
                label=f"serve_insert_b{width}",
                surface="serve")
        self._compiled[key] = fn
        return fn

    def set_heartbeat(self, fn: Optional[Callable[[int], None]]) -> None:
        """(Re)wire the per-iteration liveness beat — the deployment
        (rayint/serving.py) points this at a Supervisor actor after the
        engine is built, so a replica wedged mid-decode is detected by
        the same board shape that watches training ranks."""
        self._heartbeat = fn

    def executable_info(self) -> Dict[str, Dict[str, Any]]:
        """Build provenance per compiled executable ("deserialized" |
        "compiled" | absent for plain-jit) — the warm-start tests'
        witness that a fresh replica recompiled nothing."""
        return {f"{k}_b{w}": dict(getattr(fn, "info", {}))
                for (k, w), fn in self._compiled.items()}

    def decode_cost_report(self, width: Optional[int] = None):
        """StepCostReport of the decode executable (perf/costs.py) —
        None when the executable cannot be introspected (plain jit or a
        deserialized blob without analyses)."""
        from gke_ray_train_tpu.perf.costs import step_cost_report
        width = width or self.buckets[0]
        fn = self._get("decode", width)
        compiled = getattr(fn, "_compiled", None)
        if compiled is None:
            return None
        try:
            return step_cost_report(compiled,
                                    tokens_per_step=self.max_batch)
        except Exception as e:  # noqa: BLE001 - introspection best-effort
            logger.debug("decode cost report unavailable: %s", e)
            return None

    def warm_up(self, widths: Optional[Sequence[int]] = None) -> None:
        """Build (or deserialize) every executable for the given buckets
        up front — the replica cold-start path, so the first request
        pays dispatch latency, not compile latency."""
        for w in widths or self.buckets:
            for kind in ("prefill", "decode", "insert"):
                self._get(kind, w)
        # obs: record the cold-start provenance (deserialized vs
        # compiled, per executable) on the run's event stream
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        obs_runtime.emit("serve_start",
                         executables=self.executable_info())

    # -- request intake ------------------------------------------------

    def submit(self, request: Request) -> int:
        """Queue a request; returns the bucket it will run in. Raises
        ValueError when no declared bucket fits (reject up front — a
        fixed-shape executable must never truncate silently)."""
        # every per-request structure is keyed by rid alone: a
        # duplicate (e.g. a client retry racing its original) would
        # overwrite the first request's routing and double-pop its
        # completion — reject it while the first is still in flight
        # (_pending_bucket spans submit→retire) or unretrieved
        if request.rid in self._pending_bucket \
                or request.rid in self._completions:
            raise ValueError(f"request {request.rid}: rid already in "
                             "flight or unretrieved — rids must be "
                             "unique per engine")
        ids = np.asarray(request.token_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError(f"request {request.rid}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(f"request {request.rid}: max_new_tokens="
                             f"{request.max_new_tokens} must be >= 1")
        # reject BEFORE truncating: even a 1-token prompt cannot fit —
        # truncate_prompt would log a misleading head-DROPPED warning
        # for a request that is rejected anyway
        if request.max_new_tokens + 1 > self.buckets[-1]:
            raise ValueError(
                f"request {request.rid}: max_new_tokens="
                f"{request.max_new_tokens} + a 1-token prompt needs "
                f"{request.max_new_tokens + 1} slots but the largest "
                f"usable bucket is {self.buckets[-1]} — lower "
                "max_new_tokens or declare a larger bucket")
        max_prompt = max(self.buckets[-1] - request.max_new_tokens, 1)
        ids = truncate_prompt(ids, max_prompt,
                              label=f"request {request.rid} prompt")
        bucket = pick_bucket(len(ids), request.max_new_tokens,
                             self.buckets, self.cfg.max_seq_len)
        # obs: the admitted request's total length (post-truncation
        # prompt + decode budget) into the shared metrics registry —
        # the workload-shape histogram bucket declarations are tuned
        # against. No-op when obs is off.
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        if obs_runtime.active() is not None:
            try:
                obs_runtime.registry().histogram("request_len").observe(
                    float(len(ids) + request.max_new_tokens))
            except Exception:  # noqa: BLE001 - telemetry must not reject
                pass
        request = dataclasses.replace(request, token_ids=ids)
        self._pending.append(request)
        self._pending_bucket[request.rid] = bucket
        self._submit_t[request.rid] = time.perf_counter()
        return bucket

    # -- the iteration loop --------------------------------------------

    def _admit(self) -> None:
        """Slot-level admission: fill every free slot whose bucket has a
        pending request — prefill at batch 1, insert into the pool."""
        still_pending: List[Request] = []
        for req in self._pending:
            width = self._pending_bucket[req.rid]
            rt = self._runtimes.get(width)
            if rt is None:
                rt = self._runtimes[width] = _BucketRuntime(
                    width, self.max_batch)
            free = rt.free_slots()
            if not free:
                still_pending.append(req)
                continue
            slot = free[0]
            if rt.state is None:
                rt.state = init_serve_state(self.cfg, self.max_batch,
                                            width)
            elif rt.occupied() > 0 and rt.decodes > 0:
                # a TRUE mid-batch refill: decode already ran for this
                # batch and other sequences are live (the initial
                # fill-up wave before the first decode is not a refill)
                self.refills += 1
            buf, plen = form_prompt_buffer(req.token_ids, width)
            stop = min(plen + req.max_new_tokens, width)
            t_prefill0 = time.perf_counter()
            first, cache_row = self._get("prefill", width)(
                self.params, jnp.asarray(buf),
                jnp.asarray([plen], jnp.int32), self.lora)
            # the first decoded token exists only once prefill
            # materializes — on an async backend stamping at dispatch
            # would measure enqueue latency, not time-to-first-token
            jax.block_until_ready(first)
            rt.state = self._get("insert", width)(
                rt.state, jnp.asarray(slot, jnp.int32), cache_row,
                jnp.asarray(buf), jnp.asarray([plen], jnp.int32),
                jnp.asarray([stop], jnp.int32), first)
            now = time.perf_counter()
            rt.slots[slot] = _Slot(req.rid, plen,
                                   self._submit_t[req.rid], now,
                                   prefill_t0=t_prefill0,
                                   decodes0=rt.decodes)
            rt.host_active[slot] = True
        self._pending = still_pending

    def _collect(self, rt: _BucketRuntime, active: np.ndarray,
                 lens: np.ndarray, buf: Optional[np.ndarray]) -> None:
        """Retire slots that went inactive this iteration."""
        now = time.perf_counter()
        for i, slot in enumerate(rt.slots):
            if slot is None or active[i]:
                continue
            # np.array COPIES: device_get can return a zero-copy view of
            # the device buffer (CPU backend), and the state is DONATED —
            # without the copy, a later admit/decode reuses that buffer
            # and the retired completion's tokens mutate under it
            row = np.array(buf[i])
            length = int(lens[i])
            gen = row[slot.prompt_len:length]
            reason = ("eos" if self.eos_ids and len(gen)
                      and int(gen[-1]) in self.eos_ids else "length")
            self._completions[slot.rid] = Completion(
                rid=slot.rid, tokens=row, prompt_len=slot.prompt_len,
                length=length, bucket=rt.width, finish_reason=reason,
                submit_s=slot.submit_t,
                first_token_s=slot.first_token_t - slot.submit_t,
                done_s=now - slot.submit_t)
            self._trace_request(rt, slot, now, length, reason)
            rt.slots[i] = None
            rt.host_active[i] = False
            self.completed_total += 1
            # pre-completion bookkeeping dies with the request — a
            # long-lived replica must not grow per served request
            self._submit_t.pop(slot.rid, None)
            self._pending_bucket.pop(slot.rid, None)

    def _trace_request(self, rt: _BucketRuntime, slot: _Slot,
                       now: float, length: int, reason: str) -> None:
        """Emit the request's lifecycle spans (obs/trace.py) at
        retirement — the "where did my p99 go" decomposition: enqueue
        (submit → prefill dispatch), prefill (dispatch → first token
        materialized), decode (admission → retire, with the iteration
        count it shared with the continuous batch). Everything here is
        host floats the engine already stamped; emission is once per
        COMPLETED request, never per decode iteration, so the one-
        ``device_get``-per-iteration hot-path contract holds. No-op
        when obs/tracing is off."""
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        if not obs_runtime.tracing():
            return
        anchor = time.time()      # map perf_counter diffs to wall ts

        def t1_of(pc: float) -> float:
            return anchor - (now - pc)

        req_id = obs_runtime.span_add(
            "serve_request", now - slot.submit_t, t1=anchor,
            rid=slot.rid, bucket=rt.width, prompt_len=slot.prompt_len,
            generated=int(length - slot.prompt_len),
            finish_reason=reason)
        if req_id is None:
            # the parent write failed (IO): children with parent=None
            # would re-parent under the attempt span and read as
            # attempt-level path leaves — a lossy trace must stay
            # consistent, so drop the orphans with their parent
            return
        obs_runtime.span_add(
            "serve_enqueue", slot.prefill_t0 - slot.submit_t,
            t1=t1_of(slot.prefill_t0), parent_id=req_id, rid=slot.rid)
        obs_runtime.span_add(
            "serve_prefill", slot.first_token_t - slot.prefill_t0,
            t1=t1_of(slot.first_token_t), parent_id=req_id,
            rid=slot.rid)
        obs_runtime.span_add(
            "serve_decode", now - slot.first_token_t, t1=anchor,
            parent_id=req_id, rid=slot.rid,
            iterations=int(rt.decodes - slot.decodes0))

    def step(self) -> int:
        """One engine iteration: admit into free slots, then run ONE
        decode step per live bucket. Returns the number of slots still
        active across buckets (0 = drained)."""
        self._admit()
        total_active = 0
        for rt in self._runtimes.values():
            if rt.occupied() == 0:
                continue
            t0 = time.perf_counter()
            rt.state = self._get("decode", rt.width)(
                self.params, rt.state, self.lora)
            rt.decodes += 1
            # ONE batched fetch of the small control leaves per
            # iteration (shardlint TPU001: never per-slot round-trips);
            # buf rides along only when a slot may have finished
            active, lens = jax.device_get(
                (rt.state["active"], rt.state["lens"]))
            dt = time.perf_counter() - t0
            n_act = int(np.sum(rt.host_active))
            self._token_latencies.append(dt)
            self._occupancy.append(n_act / self.max_batch)
            total_active += int(np.sum(active))
            if bool(np.any(rt.host_active & ~active)):
                buf = jax.device_get(rt.state["buf"])
                self._collect(rt, active, lens, buf)
        self.iterations += 1
        if self._heartbeat is not None:
            try:
                self._heartbeat(self.iterations)
            except Exception as e:  # noqa: BLE001 - liveness best-effort
                logger.debug("serve heartbeat dropped: %s", e)
        return total_active + len(self._pending)

    def run_until_drained(self, requests: Sequence[Request] = ()
                          ) -> List[Completion]:
        """Submit ``requests`` and iterate until every queued request
        completed; returns completions in submit order. Returned
        completions are RELEASED from the engine (a long-lived replica
        calls this per request batch and must not accumulate every
        buffer it ever served) — use :meth:`completion` + manual
        :meth:`step` when you need them retained."""
        for r in requests:
            self.submit(r)
        want = [r.rid for r in requests]
        while self.step() > 0:
            pass
        # obs: serving latency/occupancy into the shared metrics
        # registry + one `serve_drained` event (off the decode loop —
        # once per drain, never per iteration; no-op when obs is off)
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        if obs_runtime.active() is not None:
            obs_runtime.active().note_serve(self.stats())
        if want:
            return [self._completions.pop(rid) for rid in want]
        out = list(self._completions.values())
        self._completions.clear()
        return out

    def completion(self, rid: str) -> Optional[Completion]:
        return self._completions.get(rid)

    # -- reporting -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Serving statistics: iteration count, batch occupancy and the
        per-token latency distribution (one decode iteration produces
        one token per active slot, so the iteration latency IS the
        per-token latency)."""
        lat = sorted(self._token_latencies)

        def pct(p):
            if not lat:
                return 0.0
            return lat[min(int(p / 100.0 * len(lat)), len(lat) - 1)]

        return {
            "iterations": self.iterations,
            "refills": self.refills,
            "completed": self.completed_total,
            "pending": len(self._pending),
            "batch_occupancy": (float(np.mean(self._occupancy))
                                if self._occupancy else 0.0),
            "p50_token_latency_s": pct(50),
            "p99_token_latency_s": pct(99),
            "plan_fingerprint": self.plan.fingerprint(),
        }


def post_train_smoke(params: Any, cfg: ModelConfig,
                     plan: ExecutionPlan,
                     prompt_ids: Sequence[np.ndarray], *,
                     eos_ids: Sequence[int] = (),
                     lora: Optional[Any] = None, lora_scale: float = 1.0,
                     max_new_tokens: int = 32
                     ) -> Optional[Tuple[List[Completion], Dict[str, Any]]]:
    """The ``SERVE_AFTER_TRAIN`` hook both ray-jobs entries call after
    training: run the given already-tokenized prompts through a fresh
    continuous-batching engine on the just-trained weights (train →
    serve on the same process, ROADMAP #2's loop closed end to end).
    Returns (completions, stats), or None — with a loud warning — when
    no declared bucket fits the model or no prompt is usable; a smoke
    must degrade, not kill a finished training run."""
    usable = [b for b in plan.bucket_list() if b <= cfg.max_seq_len]
    if not usable:
        logger.warning(
            "SERVE_AFTER_TRAIN skipped: no declared bucket %s fits "
            "max_seq_len=%d (set DECODE_BUCKETS)", plan.bucket_list(),
            cfg.max_seq_len)
        return None
    prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompt_ids]
    prompts = [p for p in prompts if p.size]
    if not prompts:
        logger.warning("SERVE_AFTER_TRAIN skipped: no non-empty prompts")
        return None
    # one-shot in-process smoke: AOT off — the executables would build
    # from abstract UNSHARDED args while the just-trained params are
    # mesh-placed, so every AOT call would be rejected into the jit
    # fallback anyway (a wasted build + a noisy guard log per bucket)
    plan = dataclasses.replace(plan, aot_train_step=False)
    # the budget must fit the declared buckets (a tight DECODE_BUCKETS
    # would otherwise reject every request at submit) — a smoke clamps
    # rather than crash
    max_new_tokens = min(max_new_tokens, max(usable[-1] - 1, 1))
    t0 = time.perf_counter()
    try:
        engine = BatchEngine(params, cfg, plan=plan, eos_ids=eos_ids,
                             lora=lora, lora_scale=lora_scale)
        comps = engine.run_until_drained([
            Request(rid=f"smoke{i}", token_ids=p,
                    max_new_tokens=max_new_tokens)
            for i, p in enumerate(prompts)])
    except Exception:  # noqa: BLE001 - the degrade contract below
        # the whole point of this hook is "degrade, not kill": the
        # training run already SUCCEEDED — a serving-smoke failure is
        # loud telemetry, never a job failure
        logger.warning("SERVE_AFTER_TRAIN failed; training output is "
                       "unaffected", exc_info=True)
        return None
    stats = engine.stats()
    stats["wall_s"] = round(time.perf_counter() - t0, 3)
    stats["generated_tokens"] = int(
        sum(c.length - c.prompt_len for c in comps))
    logger.info(
        "SERVE_AFTER_TRAIN: %d request(s) -> %d tokens in %.2fs "
        "(occupancy %.2f, p50 %.1fms/token, plan %s)",
        len(comps), stats["generated_tokens"], stats["wall_s"],
        stats["batch_occupancy"], stats["p50_token_latency_s"] * 1e3,
        stats["plan_fingerprint"])
    return comps, stats
