"""Side-by-side base-vs-tuned inference comparison (SURVEY.md §3.4).

Capability parity with run_inference_comparison
(ray-jobs/fine_tune_llama_ray.py:22-194): post-training; filter test
rows, greedy-generate from both the original and the fine-tuned weights
with a shared prompt template, print and accumulate side-by-side
results, JSON-dump to shared storage. TPU redesign: both models generate
through one jitted KV-cached prefill+step loop (models/kvcache.py;
models/decode.py is the full-forward oracle it is tested against),
prompts bucketed to 128-multiples so similar lengths share a compile; no
device cache juggling (the reference's del model +
torch.cuda.empty_cache() dance at :191-194 has no XLA equivalent — arrays
free when references drop).

Multi-host semantics (the one place this deliberately diverges from the
reference's rank-0-only harness, :22-194): the reference can generate on
rank 0 alone because DDP replicates weights; here the weights are
mesh-sharded global arrays, so EVERY host must enter the generate —
running it on host 0 only would diverge the SPMD program and deadlock.
``is_host0`` therefore gates only printing and file IO, exactly like
train/loop.py. Pass ``mesh`` whenever params are sharded over one: the
prompt buffers are formed up as globally-replicated arrays (every host
feeds identical bytes — callers must pass identical ``test_rows``, which
holds for the seeded downsample/synthetic paths) and the generated
buffer is read back from an addressable replica shard.
"""

from __future__ import annotations

import json
import logging
import os
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gke_ray_train_tpu.data.sft import format_gretel_sql_example, render_chat
from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.kvcache import greedy_generate_cached
from gke_ray_train_tpu.models.transformer import Params
from gke_ray_train_tpu.serve.bucketing import (
    form_prompt_buffer, prompt_bucket, truncate_prompt)

logger = logging.getLogger(__name__)

# jitted replicated-generate executables keyed on (mesh identity, cfg,
# decode shape). NOT an lru_cache: every entry closes over a
# NamedSharding that pins its Mesh — and through it the device buffers
# of every array the jit ever touched — so an unbounded/function-scoped
# cache kept torn-down meshes alive for the life of the process. The
# id(mesh) key is stable exactly because the entry pins the mesh (no id
# reuse while the entry lives); eviction and clear_generate_cache()
# are what release it.
_GENERATE_CACHE: "OrderedDict[Tuple, Callable]" = OrderedDict()
_GENERATE_CACHE_MAX = 32


def clear_generate_cache() -> int:
    """Drop every cached replicated-generate executable — call on mesh
    teardown (``rayint/trainer.py`` does, after every worker attempt):
    the cache is the only thing keeping a dead mesh's device buffers
    live. Returns the number of entries dropped."""
    n = len(_GENERATE_CACHE)
    _GENERATE_CACHE.clear()
    return n


def _replicated_generate(mesh: Mesh, cfg: ModelConfig,
                         max_new_tokens: int, eos_ids: Tuple[int, ...],
                         lora_scale: float):
    """One jitted generate per (mesh, cfg, decode-shape) with the output
    pinned to a replicated sharding, so every host can read its full
    value from any addressable shard. The inner call traces through the
    already-jitted greedy_generate_cached."""
    key = (id(mesh), cfg, max_new_tokens, eos_ids, lora_scale)
    fn = _GENERATE_CACHE.get(key)
    if fn is not None:
        _GENERATE_CACHE.move_to_end(key)
        return fn
    out_sharding = NamedSharding(mesh, P())

    def f(params, prompt, prompt_len, lora):
        return greedy_generate_cached(
            params, prompt, prompt_len, cfg,
            max_new_tokens=max_new_tokens, eos_ids=eos_ids,
            lora=lora, lora_scale=lora_scale)
    fn = jax.jit(f, out_shardings=out_sharding)
    _GENERATE_CACHE[key] = fn
    while len(_GENERATE_CACHE) > _GENERATE_CACHE_MAX:
        _GENERATE_CACHE.popitem(last=False)
    return fn


def _place_replicated(mesh: Mesh, arr: np.ndarray) -> jax.Array:
    """Host-local numpy (identical on every host) → globally-replicated
    jax.Array over the mesh (the form-up greedy decode needs once params
    are sharded; single-host this is a plain device_put)."""
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P()), arr, arr.shape)


def generate_answer(params: Params, cfg: ModelConfig, tokenizer,
                    prompt_text: str, *, max_new_tokens: int = 300,
                    lora: Optional[Params] = None,
                    lora_scale: float = 1.0,
                    mesh: Optional[Mesh] = None) -> str:
    ids = np.asarray(
        tokenizer(prompt_text, add_special_tokens=False)["input_ids"],
        np.int32)
    # bucketed fixed-size buffer: prompt region rounded up to a 128
    # multiple + generation room — compiles once per bucket, not per
    # prompt length. Bucketing/truncation/form-up are shared with the
    # serving engine (serve/bucketing.py) so the two paths cannot drift;
    # an over-long prompt is truncated LOUDLY (the head is dropped).
    max_prompt = max(cfg.max_seq_len - max_new_tokens, 1)
    ids = truncate_prompt(ids, max_prompt, label="generate_answer prompt")
    # buffer width rounded to a 128 multiple: the KV-cache flash prefill
    # gates on the CACHE width tiling too (models/kvcache.py) — an
    # unaligned width would silently fall back to the dense
    # O(T*max_len) prefill at exactly the long-prompt sizes where it
    # hurts. One bucket call keeps compile-sharing per length class.
    L = min(prompt_bucket(len(ids) + max_new_tokens), cfg.max_seq_len)
    buf, _ = form_prompt_buffer(ids, L)
    eos_ids = []
    if getattr(tokenizer, "eos_token_id", None) is not None:
        eos_ids.append(int(tokenizer.eos_token_id))
    if mesh is not None:
        gen_fn = _replicated_generate(mesh, cfg, max_new_tokens,
                                      tuple(eos_ids), lora_scale)
        out = gen_fn(params, _place_replicated(mesh, buf),
                     _place_replicated(
                         mesh, np.asarray([len(ids)], np.int32)),
                     lora)
        # replicated sharding: any addressable shard IS the full array
        # (np.asarray on the global array would require every device to
        # be addressable, which fails under multi-process)
        out = np.asarray(out.addressable_data(0)[0])
    else:
        out = greedy_generate_cached(
            params, jnp.asarray(buf), jnp.asarray([len(ids)], jnp.int32),
            cfg, max_new_tokens=max_new_tokens, eos_ids=tuple(eos_ids),
            lora=lora, lora_scale=lora_scale)
        out = np.asarray(out[0])
    gen = out[len(ids):]
    # trim at the first EOS; otherwise strip only TRAILING zeros (the
    # unwritten buffer tail). Filtering every zero would also delete a
    # legitimately generated token id 0 (e.g. "!" in Llama-3's vocab)
    # from the middle of the answer.
    stops = np.where(np.isin(gen, eos_ids))[0] if eos_ids else []
    if len(stops):
        gen = gen[: stops[0]]
    else:
        nz = np.nonzero(gen)[0]
        gen = gen[: nz[-1] + 1] if len(nz) else gen[:0]
    return tokenizer.decode(gen)


def run_inference_comparison(
        base_params: Params, tuned_params: Params, cfg: ModelConfig,
        tokenizer, test_rows: List[Dict], *,
        num_samples: int = 2, max_new_tokens: int = 300,
        output_path: Optional[str] = None,
        row_filter: Optional[Callable[[Dict], bool]] = None,
        format_example: Callable = format_gretel_sql_example,
        mesh: Optional[Mesh] = None,
        is_host0: bool = True,
        tuned_lora: Optional[Params] = None,
        lora_scale: float = 1.0) -> List[Dict]:
    """Returns the accumulated comparison records; writes JSON when
    ``output_path`` is given (reference behavior: filter on
    sql_complexity == 'window functions', :87-96; JSON dump :182-187).

    COLLECTIVE once ``mesh`` is given and params are sharded: every host
    must call this with identical ``test_rows`` (see module docstring);
    ``is_host0`` gates only the log lines and the JSON write.

    ``tuned_lora``: when given, the tuned model is ``tuned_params`` +
    adapters applied at decode time — (Q)LoRA runs never materialize a
    merged tree on device (an 8B NF4 base dequantized to a merged copy
    does not fit one 16 GB chip).
    """
    if row_filter is not None:
        test_rows = [r for r in test_rows if row_filter(r)]
    test_rows = test_rows[:num_samples]
    results = []
    for i, row in enumerate(test_rows):
        msgs = format_example(row)
        prompt = render_chat(tokenizer, msgs, add_generation_prompt=True)
        record = {
            "index": i,
            "question": msgs["user"],
            "reference_answer": msgs["assistant"],
            "base_model_answer": generate_answer(
                base_params, cfg, tokenizer, prompt,
                max_new_tokens=max_new_tokens, mesh=mesh),
            "finetuned_model_answer": generate_answer(
                tuned_params, cfg, tokenizer, prompt,
                max_new_tokens=max_new_tokens, mesh=mesh,
                lora=tuned_lora, lora_scale=lora_scale),
        }
        if is_host0:
            logger.info("sample %d\n  Q: %s\n  base: %s\n  tuned: %s", i,
                        record["question"], record["base_model_answer"],
                        record["finetuned_model_answer"])
        results.append(record)
    if output_path and is_host0:
        os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
        with open(output_path, "w") as f:
            json.dump(results, f, indent=2)
        logger.info("wrote %d comparison records to %s", len(results),
                    output_path)
    return results
