"""Ray-actor serving deployment — replica-per-host-group continuous
batching over the trained artifacts (ROADMAP #2, the "millions of
users" half).

One :class:`ServeReplica` actor per TPU host-group, each owning one
:class:`~gke_ray_train_tpu.serve.engine.BatchEngine` (the replica's
whole device set runs the bucketed prefill/decode executables; a JAX
process drives all its local chips, exactly like a training worker).
The driver-side :class:`ServeDeployment` scatters request batches
round-robin across replicas and gathers completions; each replica
continuously batches its share at iteration granularity.

Liveness rides the existing supervisor heartbeat shape
(``rayint/supervisor.py``): every engine iteration beats
``(replica_rank, iteration)`` to a Supervisor actor (Ray path) or an
in-process HeartbeatBoard (local path), so a replica wedged mid-decode
is detected — and NAMED — by the same board that watches training
ranks. Cold start reuses the AOT sidecar dir (``perf/cache.py`` via the
engine): point every replica at shared storage and a fresh process
deserializes its prefill/decode executables instead of compiling.

Ray is optional at import time (the trainer's pattern): with no Ray
installed or ``use_ray=False`` the deployment degrades to in-process
replicas — that is also the unit-test path; the fake-ray harness in
``tests/test_rayint_cluster.py`` drives the actor path.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

try:  # pragma: no cover - exercised only with Ray installed
    import ray
    _HAS_RAY = True
except ImportError:
    ray = None
    _HAS_RAY = False


def _completion_payload(c) -> Dict[str, Any]:
    """A Completion as a plain dict — actor results must cross process
    boundaries without importing serve/ on the driver."""
    return {
        "rid": c.rid,
        "tokens": np.asarray(c.tokens).tolist(),
        "generated": np.asarray(c.generated).tolist(),
        "prompt_len": int(c.prompt_len),
        "length": int(c.length),
        "bucket": int(c.bucket),
        "finish_reason": c.finish_reason,
        "first_token_s": float(c.first_token_s),
        "done_s": float(c.done_s),
        "adapter_id": c.adapter_id,
    }


class ServeReplica:
    """Actor body: ``ray.remote(ServeReplica)`` at deploy time (the
    ``rayint/supervisor.py::Supervisor`` pattern — decorating here
    would make Ray an import-time dependency). Zero-arg constructible;
    :meth:`build` does the heavy lifting so actor creation stays cheap
    and the engine factory travels as a task argument."""

    def __init__(self):
        self._engine = None
        self._rank = 0

    def build(self, engine_factory: Callable[[], Any], *, rank: int = 0,
              supervisor=None, warm: bool = True) -> Dict[str, Any]:
        """Construct (and by default warm up) this replica's engine.
        ``engine_factory() -> BatchEngine`` loads weights and plan on
        the replica's own process; with a ``supervisor`` handle every
        engine iteration beats ``(rank, iteration)`` to it. Returns
        ``executable_info()`` — the cold-start witness (every entry
        ``"deserialized"`` on a warm sidecar dir)."""
        self._rank = int(rank)
        # obs session for this replica process (no-op without OBS_DIR):
        # the engine's serve_start/serve_drained events and the
        # latency/occupancy metrics export land per replica rank, in
        # the same correlated stream a training run writes
        import os
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        if obs_runtime.active() is None and os.environ.get("OBS_DIR"):
            obs_runtime.start_attempt(rank=self._rank)
        self._engine = engine_factory()
        if supervisor is not None:
            if hasattr(supervisor, "beat") and hasattr(
                    getattr(supervisor, "beat"), "remote"):
                self._engine.set_heartbeat(
                    lambda it: supervisor.beat.remote(self._rank, it))
            else:  # local path: a HeartbeatBoard
                self._engine.set_heartbeat(
                    lambda it: supervisor.beat(self._rank, it))
        if warm:
            self._engine.warm_up()
        return self._engine.executable_info()

    def serve(self, requests: Sequence[Dict[str, Any]]
              ) -> List[Dict[str, Any]]:
        """Continuously batch ``requests`` (dicts: rid / token_ids /
        max_new_tokens / optional adapter_id for multi-tenant engines)
        to completion; returns completion payloads in submit order."""
        from gke_ray_train_tpu.serve.engine import Request
        reqs = [Request(rid=str(r["rid"]),
                        token_ids=np.asarray(r["token_ids"], np.int32),
                        max_new_tokens=int(r.get("max_new_tokens", 32)),
                        adapter_id=r.get("adapter_id"))
                for r in requests]
        return [_completion_payload(c)
                for c in self._engine.run_until_drained(reqs)]

    def stats(self) -> Dict[str, Any]:
        return self._engine.stats()

    def executable_info(self) -> Dict[str, Any]:
        return self._engine.executable_info()


class ServeDeployment:
    """Driver-side deployment: N replicas + one heartbeat sink.

    ``engine_factory`` must be self-contained (load checkpoint, build
    the plan, construct the BatchEngine) — on the Ray path it executes
    inside each replica actor's process. ``resources_per_replica``
    follows the trainer's host-group convention (e.g. ``{"TPU": 4}``).
    """

    def __init__(self, engine_factory: Callable[[], Any], *,
                 num_replicas: int = 1,
                 resources_per_replica: Optional[Dict[str, float]] = None,
                 use_ray: Optional[bool] = None):
        self.engine_factory = engine_factory
        self.num_replicas = int(num_replicas)
        self.resources = resources_per_replica or {}
        self.use_ray = _HAS_RAY if use_ray is None else use_ray
        self._replicas: List[Any] = []
        self._supervisor = None
        self._board = None
        self._rr = 0

    # -- lifecycle -----------------------------------------------------

    def start(self, *, warm: bool = True) -> List[Dict[str, Any]]:
        """Create supervisor + replicas and build every engine. Returns
        one ``executable_info()`` dict per replica."""
        if self.use_ray:
            from gke_ray_train_tpu.rayint.supervisor import Supervisor
            if not ray.is_initialized():  # pragma: no cover - cluster
                ray.init()
            self._supervisor = ray.remote(Supervisor).options(
                num_cpus=0).remote()
            actor_cls = ray.remote(ServeReplica)
            opts = {"resources": self.resources} if self.resources else {}
            self._replicas = [actor_cls.options(**opts).remote()
                              for _ in range(self.num_replicas)]
            infos = ray.get([
                r.build.remote(self.engine_factory, rank=i,
                               supervisor=self._supervisor, warm=warm)
                for i, r in enumerate(self._replicas)])
        else:
            from gke_ray_train_tpu.rayint.supervisor import HeartbeatBoard
            self._board = HeartbeatBoard()
            self._replicas = [ServeReplica()
                              for _ in range(self.num_replicas)]
            infos = [r.build(self.engine_factory, rank=i,
                             supervisor=self._board, warm=warm)
                     for i, r in enumerate(self._replicas)]
        logger.info("serve deployment up: %d replica(s), %s",
                    self.num_replicas,
                    "ray actors" if self.use_ray else "in-process")
        return infos

    def shutdown(self) -> None:
        if self.use_ray:
            for actor in self._replicas + (
                    [self._supervisor] if self._supervisor is not None
                    else []):
                try:
                    ray.kill(actor)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
        self._replicas = []
        self._supervisor = None
        self._board = None

    # -- request path --------------------------------------------------

    def serve(self, requests: Sequence[Dict[str, Any]]
              ) -> List[Dict[str, Any]]:
        """Scatter a request batch round-robin across replicas, gather
        completions back in the callers' order. Each replica
        continuously batches its share; replicas run concurrently on
        the Ray path (one in-flight ``serve`` task per replica)."""
        if not self._replicas:
            raise RuntimeError("deployment not started — call start()")
        # duplicate rids must fail HERE: scattered onto different
        # replicas they would dodge the engine's per-rid guard and the
        # order map below would silently drop one completion
        rids = [str(r["rid"]) for r in requests]
        if len(set(rids)) != len(rids):
            dupes = sorted({r for r in rids if rids.count(r) > 1})
            raise ValueError(f"duplicate request rids {dupes} — rids "
                             "must be unique per batch")
        shares: List[List[Dict[str, Any]]] = [
            [] for _ in self._replicas]
        order: Dict[str, int] = {}
        for i, req in enumerate(requests):
            shares[(self._rr + i) % len(self._replicas)].append(req)
            order[str(req["rid"])] = i
        self._rr = (self._rr + len(requests)) % len(self._replicas)
        if self.use_ray:
            futs = [r.serve.remote(share)
                    for r, share in zip(self._replicas, shares) if share]
            batches = ray.get(futs)
        else:
            batches = [r.serve(share)
                       for r, share in zip(self._replicas, shares)
                       if share]
        out: List[Optional[Dict[str, Any]]] = [None] * len(requests)
        for batch in batches:
            for payload in batch:
                out[order[payload["rid"]]] = payload
        return [p for p in out if p is not None]

    # -- health --------------------------------------------------------

    def stalled(self, timeout_s: float):
        """Replicas with no engine-iteration progress for ``timeout_s``
        — same StallInfo shape the training watchdog reports."""
        if self.use_ray:
            return ray.get(self._supervisor.stalled.remote(timeout_s)) \
                if self._supervisor is not None else []
        return self._board.stalled(timeout_s) if self._board else []

    def stats(self) -> List[Dict[str, Any]]:
        if self.use_ray:
            return ray.get([r.stats.remote() for r in self._replicas])
        return [r.stats() for r in self._replicas]
