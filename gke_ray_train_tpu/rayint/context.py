"""Worker train context — the ``ray.train.get_context()`` /
``train.report`` surface the reference's worker fns rely on
(ray-jobs/fine_tune_llama_ray.py:201-202, pytorch_llm_ray.py:125-128,
:309-310), reimplemented so the same worker-fn shape runs under Ray
actors, plain multi-process SPMD, or a single local process.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)


class TrainContext:
    def __init__(self):
        self.last_reported: Optional[dict] = None
        # step the current attempt resumed from (None = fresh start);
        # set by the train loop, read into Result.attempt_log
        self.resumed_step: Optional[int] = None
        # the attempt's goodput ledger (train/metrics.py LEDGER_TERMS),
        # set by the loop's exit path on success AND failure — on the
        # local trainer path it survives a crashed attempt where the
        # worker payload does not
        self.goodput: Optional[dict] = None
        # fingerprint of the ExecutionPlan the attempt ran under (set
        # by _run_worker after resolve/replan) — attempt_log provenance
        self.plan_fingerprint: Optional[str] = None
        # heartbeat sink wired by the trainer: callable(rank, step, done)
        # forwarding to the supervisor actor (Ray) or the local board
        self._heartbeat = None

    def get_world_size(self) -> int:
        return int(os.environ.get("NUM_PROCESSES", "1"))

    def get_world_rank(self) -> int:
        return int(os.environ.get("PROCESS_ID", "0"))

    def get_local_rank(self) -> int:
        return 0  # one JAX process per host owns all local chips

    def is_host0(self) -> bool:
        return self.get_world_rank() == 0

    def set_heartbeat_sink(self, fn) -> None:
        self._heartbeat = fn

    def heartbeat(self, step: int, done: bool = False) -> None:
        """Report step progress to the supervisor (rayint/supervisor.py).
        Best-effort: liveness reporting must never kill a live worker."""
        if self._heartbeat is None:
            return
        try:
            self._heartbeat(self.get_world_rank(), int(step), done)
        except Exception as e:  # noqa: BLE001
            logger.debug("heartbeat dropped: %s", e)

    def heartbeat_done(self) -> None:
        """Mark this rank finished — a done worker is never 'stalled'."""
        self.heartbeat(-1, done=True)

    def note_resume(self, step: Optional[int]) -> None:
        self.resumed_step = step

    def note_goodput(self, ledger: Optional[dict]) -> None:
        self.goodput = dict(ledger) if ledger is not None else None

    def report(self, metrics: dict, checkpoint_path: Optional[str] = None):
        """train.report parity: metrics become the trainer Result. Unlike
        Ray Train this is not a barrier — collective synchronization
        belongs to the collectives themselves (orbax save / psum), not to
        the metrics channel."""
        self.last_reported = dict(metrics)
        if checkpoint_path:
            self.last_reported["checkpoint_path"] = checkpoint_path
        if self.is_host0():
            logger.info("report: %s", self.last_reported)


_context = TrainContext()


def get_context() -> TrainContext:
    return _context


def report(metrics: dict, checkpoint_path: Optional[str] = None) -> None:
    _context.report(metrics, checkpoint_path)
