"""Worker train context — the ``ray.train.get_context()`` /
``train.report`` surface the reference's worker fns rely on
(ray-jobs/fine_tune_llama_ray.py:201-202, pytorch_llm_ray.py:125-128,
:309-310), reimplemented so the same worker-fn shape runs under Ray
actors, plain multi-process SPMD, or a single local process.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)


class TrainContext:
    def __init__(self):
        self.last_reported: Optional[dict] = None

    def get_world_size(self) -> int:
        return int(os.environ.get("NUM_PROCESSES", "1"))

    def get_world_rank(self) -> int:
        return int(os.environ.get("PROCESS_ID", "0"))

    def get_local_rank(self) -> int:
        return 0  # one JAX process per host owns all local chips

    def is_host0(self) -> bool:
        return self.get_world_rank() == 0

    def report(self, metrics: dict, checkpoint_path: Optional[str] = None):
        """train.report parity: metrics become the trainer Result. Unlike
        Ray Train this is not a barrier — collective synchronization
        belongs to the collectives themselves (orbax save / psum), not to
        the metrics channel."""
        self.last_reported = dict(metrics)
        if checkpoint_path:
            self.last_reported["checkpoint_path"] = checkpoint_path
        if self.is_host0():
            logger.info("report: %s", self.last_reported)


_context = TrainContext()


def get_context() -> TrainContext:
    return _context


def report(metrics: dict, checkpoint_path: Optional[str] = None) -> None:
    _context.report(metrics, checkpoint_path)
