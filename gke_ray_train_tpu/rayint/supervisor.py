"""Heartbeat supervision — step-granular liveness for training workers.

``RunConfig.worker_timeout_s`` bounds a whole attempt with one wall
clock, which forces the operator to guess total run time. This module
detects the actual failure signature of a wedged collective or dead TPU
host instead: *no step progress for N seconds* (``HEARTBEAT_TIMEOUT_S``).

Workers report ``(rank, step)`` after every completed step through
``rayint/context.py`` (``ctx.heartbeat``); the sink is wired by the
trainer — a :class:`Supervisor` actor on Ray clusters, an in-process
:class:`HeartbeatBoard` + :class:`Watchdog` thread in the local path.
The driver polls for stalls and kills the attempt with an error that
NAMES the stalled rank, so the operator learns which host to drain.

Arrival times are stamped by the receiving board (driver/actor clock) —
worker clocks are never trusted across machines. A rank is tracked only
once it has beaten (model build/compile before the first step is not a
stall; ``worker_timeout_s`` still bounds that phase if set) and is
exempt once it reports done (a finished or failed worker is not a
stalled one).

Stdlib-only by design: importable by the driver-side trainer and the
Ray actor runtime without jax.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# (rank, last_step, seconds_since_last_progress)
StallInfo = Tuple[int, int, float]


class HeartbeatTimeout(RuntimeError):
    """An attempt killed because a named rank stopped making step
    progress. Retryable: workers resume from the latest checkpoint.

    ``slice_map`` (rank → slice index, per the ``slice_index`` contract
    in ``parallel/mesh.py``) scopes the failure domain: when EVERY
    stalled rank belongs to one slice (``uniform_slice``), the
    signature is a slice eviction/loss — the trainer classifies it as a
    *shrink* event (elastic re-form on the survivors) instead of a
    whole-job failure burning ``max_failures``."""

    def __init__(self, stalled: List[StallInfo], timeout_s: float,
                 slice_map: Optional[Dict[int, int]] = None):
        self.stalled = list(stalled)
        self.timeout_s = timeout_s
        self.slice_map = dict(slice_map or {})
        ranks = ", ".join(
            f"rank {r} (last step {s}, {age:.1f}s ago"
            + (f", slice {self.slice_map[r]}" if r in self.slice_map
               else "") + ")"
            for r, s, age in self.stalled)
        msg = (f"heartbeat timeout: no step progress for {timeout_s:g}s "
               f"from {ranks}; killed all workers for retry-with-resume")
        u = self.uniform_slice
        if u is not None:
            msg += (f" [every stalled rank is on slice {u} — "
                    "slice-loss signature]")
        super().__init__(msg)

    @property
    def uniform_slice(self) -> Optional[int]:
        """The single slice every stalled rank belongs to, or None when
        the stall spans slices (or no slice identity is known)."""
        if not self.stalled or not self.slice_map:
            return None
        slices = {self.slice_map.get(r) for r, _, _ in self.stalled}
        if len(slices) == 1 and None not in slices:
            return slices.pop()
        return None


def slice_shrink_pool(evicted_slice: int, slice_map: Dict[int, int],
                      chips_per_worker: float) -> int:
    """Surviving chip count after one slice's workers are written off —
    the pool the elastic trainer re-forms on when a stall has the
    slice-loss signature (every rank of ``slice_map`` is a worker)."""
    survivors = sum(1 for s in slice_map.values() if s != evicted_slice)
    return int(survivors * chips_per_worker)


class HeartbeatBoard:
    """Thread-safe rank → (step, arrival_time-of-last-PROGRESS) board.

    A beat only refreshes the clock when the step advanced — a worker
    re-reporting the same step is as stalled as one reporting nothing.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._last = {}      # rank -> (step, monotonic_time)
        self._done = set()
        self._slices = {}    # rank -> slice index (slice_index contract)

    def set_slices(self, mapping: Dict[int, int]) -> None:
        """Teach the board slice identity (rank → slice index) so stall
        reports carry the failure domain, not just the rank."""
        with self._lock:
            self._slices.update({int(r): int(s)
                                 for r, s in mapping.items()})

    def slice_map(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._slices)

    def beat(self, rank: int, step: int, done: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if done:
                self._done.add(rank)
                return
            prev = self._last.get(rank)
            if prev is None or step > prev[0]:
                self._last[rank] = (int(step), now)

    def stalled(self, timeout_s: float,
                now: Optional[float] = None) -> List[StallInfo]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return sorted(
                (rank, step, now - t)
                for rank, (step, t) in self._last.items()
                if rank not in self._done and now - t > timeout_s)

    def snapshot(self) -> dict:
        with self._lock:
            return {rank: {"step": step, "age_s": time.monotonic() - t,
                           "done": rank in self._done,
                           **({"slice": self._slices[rank]}
                              if rank in self._slices else {})}
                    for rank, (step, t) in self._last.items()}

    def metrics_view(self, timeout_s: Optional[float] = None) -> dict:
        """Serializable export view (obs satellite): the per-rank
        last-beat age/step/slice snapshot plus — when a timeout is
        given — the ranks currently past it, BY NAME. Consumed by the
        driver-side obs exporter (``<obs_dir>/supervisor.json``) and by
        ``obs report``; JSON-safe by construction (string rank keys)."""
        snap = self.snapshot()
        view = {"ranks": {str(r): {k: (round(v, 3)
                                       if isinstance(v, float) else v)
                                   for k, v in info.items()}
                          for r, info in snap.items()}}
        if timeout_s is not None:
            view["timeout_s"] = float(timeout_s)
            view["stalled"] = [
                {"rank": r, "last_step": s, "age_s": round(age, 3)}
                for r, s, age in self.stalled(timeout_s)]
        return view


class Supervisor:
    """Actor body for the Ray path: ``ray.remote(Supervisor)`` in the
    trainer (decorating here would make Ray an import-time dependency).
    Workers fire-and-forget ``beat``; the driver polls ``stalled``."""

    def __init__(self):
        self._board = HeartbeatBoard()

    def beat(self, rank: int, step: int, done: bool = False) -> None:
        self._board.beat(rank, step, done=done)

    def set_slices(self, mapping: Dict[int, int]) -> None:
        self._board.set_slices(mapping)

    def stalled(self, timeout_s: float) -> List[StallInfo]:
        return self._board.stalled(timeout_s)

    def snapshot(self) -> dict:
        return self._board.snapshot()

    def metrics_view(self, timeout_s: Optional[float] = None) -> dict:
        return self._board.metrics_view(timeout_s)


class Watchdog:
    """Local-path supervision: a daemon thread polling a board.

    On stall it records ``stalled_info`` and interrupts the main thread
    (the worker shares our process — a wedged collective ignores
    everything short of an interrupt); ``JaxTrainer._fit_local``
    converts that KeyboardInterrupt into :class:`HeartbeatTimeout`.
    """

    def __init__(self, board: HeartbeatBoard, timeout_s: float,
                 poll_s: Optional[float] = None,
                 on_stall: Optional[Callable] = None,
                 pre_interrupt: Optional[Callable] = None):
        self.board = board
        self.timeout_s = timeout_s
        self.poll_s = poll_s if poll_s is not None else max(
            0.01, min(timeout_s / 4.0, 5.0))
        self.stalled_info: Optional[List[StallInfo]] = None
        self._on_stall = on_stall
        # best-effort hook fired with the stall list BEFORE the
        # interrupt: the obs stalled-rank capture runs here — the main
        # thread is wedged but the device may still be executing, and
        # jax.profiler is process-global so this thread can trace it
        self._pre_interrupt = pre_interrupt
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="heartbeat-watchdog")

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            stalled = self.board.stalled(self.timeout_s)
            if stalled:
                # confirmation read: a worker finishing exactly at the
                # detection boundary marks itself done in between — a
                # completed attempt must not be interrupted and retried
                time.sleep(min(0.05, self.poll_s))
                stalled = self.board.stalled(self.timeout_s)
            # re-check stop right before acting: a worker that completed
            # while we computed the stall must not be interrupted
            if stalled and not self._stop.is_set():
                self.stalled_info = stalled
                logger.error("%s", HeartbeatTimeout(stalled, self.timeout_s))
                if self._pre_interrupt is not None:
                    try:
                        self._pre_interrupt(stalled)
                    except Exception as e:  # noqa: BLE001 - never
                        logger.warning(    # block the kill on telemetry
                            "pre-interrupt hook failed: %s", e)
                if self._on_stall is not None:
                    self._on_stall(stalled)
                else:
                    # a real SIGINT to the process: unlike
                    # _thread.interrupt_main(), it EINTRs a main thread
                    # blocked in C (time.sleep, a dead collective's
                    # syscall) instead of waiting for its next bytecode
                    import os
                    import signal
                    os.kill(os.getpid(), signal.SIGINT)
                return
