"""JaxTrainer — Ray Train style orchestration for JAX-on-TPU workers.

API parity with the reference's driver blocks
(ray-jobs/fine_tune_llama_ray.py:445-457, pytorch_llm_ray.py:346-376):
``JaxTrainer(train_loop_per_worker, train_loop_config, scaling_config,
run_config).fit() → Result(metrics)``. Differences, by design
(SURVEY.md row D1):

- One worker per TPU *host* (``resources_per_worker={"TPU": chips}``),
  not per accelerator: a single JAX process drives all local chips.
- Instead of MASTER_ADDR/PORT + NCCL process groups, the trainer elects
  worker 0's node as the JAX coordinator and injects
  COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID; workers then call
  ``parallel.mesh.distributed_init`` (SURVEY.md row D2/§5.8).
- ``FailureConfig(max_failures=N)`` is actually wired (the reference
  never configures it, §5.3); retried workers resume from the latest
  orbax checkpoint because every entry script restores-if-present.

Ray is optional at import time: with no Ray installed (or
``use_ray=False``) the trainer degrades to a single in-process worker —
that is also the unit-test path.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

try:  # pragma: no cover - exercised only on clusters with Ray installed
    import ray
    _HAS_RAY = True
except ImportError:
    ray = None
    _HAS_RAY = False

DEFAULT_COORDINATOR_PORT = 8476  # fallback when port discovery fails


@dataclasses.dataclass
class ScalingConfig:
    """ScalingConfig parity (fine_tune_llama_ray.py:445-449) with TPU
    resources instead of {"GPU": 1}."""
    num_workers: int = 1
    resources_per_worker: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"TPU": 4})
    placement_strategy: str = "SPREAD"

    @staticmethod
    def from_env() -> "ScalingConfig":
        """World shape from env — NUM_HOSTS/CHIPS_PER_HOST, the TPU
        analogues of NUM_NODES/NUM_GPUS_PER_NODE
        (fine_tune_llama_ray.py:439-441, SURVEY.md §5.6)."""
        hosts = int(os.environ.get("NUM_HOSTS",
                                   os.environ.get("NUM_NODES", "1")))
        chips = int(os.environ.get("CHIPS_PER_HOST",
                                   os.environ.get("NUM_GPUS_PER_NODE", "4")))
        return ScalingConfig(num_workers=hosts,
                             resources_per_worker={"TPU": chips})


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: str = "jax-train"
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    # Hang detection (SURVEY.md §5.3): with no bound, one wedged worker
    # (deadlocked collective, dead TPU host) blocks ray.get forever and
    # FailureConfig never gets its chance. When set, an attempt that
    # exceeds this wall-clock kills every worker and counts as a
    # failure, so retry-with-resume proceeds. None = wait forever (the
    # default: legitimate training runs have no universal time bound).
    worker_timeout_s: Optional[float] = None


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    error: Optional[str] = None
    # per-worker metrics (worker 0 first); `metrics` is worker 0's view,
    # matching Ray Train's rank-0 convention, but nothing is dropped
    worker_metrics: Optional[list] = None


def _run_worker(fn: Callable, config: dict, env: Dict[str, str]):
    os.environ.update(env)
    from gke_ray_train_tpu.rayint.context import get_context
    ret = fn(config)
    reported = get_context().last_reported
    return ret if ret is not None else (reported or {})


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 use_ray: Optional[bool] = None):
        self.fn = train_loop_per_worker
        # copied: env-derived injections below must not leak into the
        # caller's dict (it may be reused or serialized as a job spec)
        self.config = dict(train_loop_config or {})
        # input-pipeline knob threaded through config so `ray job submit
        # --env PREFETCH_BATCHES=N` tunes the async prefetch depth
        # (data/prefetch.py) without editing the job JSON; an explicit
        # config value always wins over the driver env
        if "PREFETCH_BATCHES" in os.environ and \
                "PREFETCH_BATCHES" not in self.config:
            self.config["PREFETCH_BATCHES"] = \
                int(os.environ["PREFETCH_BATCHES"])
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.use_ray = (_HAS_RAY and self.scaling.num_workers >= 1
                        if use_ray is None else use_ray)

    # -- local ---------------------------------------------------------
    def _fit_local(self) -> Result:
        env = {"NUM_PROCESSES": "1", "PROCESS_ID": "0"}
        metrics = _run_worker(self.fn, self.config, env)
        return Result(metrics=metrics)

    # -- ray ----------------------------------------------------------
    def _fit_ray(self) -> Result:
        if not ray.is_initialized():
            ray.init(address=os.environ.get("RAY_ADDRESS", "auto"))
        n = self.scaling.num_workers
        resources = dict(self.scaling.resources_per_worker)
        num_cpus = resources.pop("CPU", 1)

        @ray.remote(max_restarts=0)
        class Worker:
            def node_ip(self):
                return ray.util.get_node_ip_address()

            def free_port(self):
                # a port that is free NOW on the coordinator node; the
                # coordinator binds it moments later (standard
                # bind-0-release discovery, replaces the fixed 8476 that
                # collides on shared nodes)
                import socket
                s = socket.socket()
                s.bind(("", 0))
                port = s.getsockname()[1]
                s.close()
                return port

            def run(self, fn, config, env):
                return _run_worker(fn, config, env)

        # honor placement_strategy: one bundle per worker, SPREAD puts
        # each TPU worker on its own host (the declared-but-unused
        # strategy from round 1)
        pg = ray.util.placement_group(
            [dict(resources, CPU=num_cpus) for _ in range(n)],
            strategy=self.scaling.placement_strategy)
        try:
            ray.get(pg.ready())
            try:
                from ray.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy)

                def sched(i):
                    return PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=i)
            except ImportError:  # very old ray: best-effort scheduling
                def sched(i):
                    return None

            workers = [
                Worker.options(resources=resources, num_cpus=num_cpus,
                               scheduling_strategy=sched(i)).remote()
                for i in range(n)]
            coord_ip = ray.get(workers[0].node_ip.remote())
            coord_port = None
            for _ in range(3):   # transient RPC/bind failures retry
                try:
                    coord_port = int(ray.get(workers[0].free_port.remote()))
                    break
                except Exception:  # noqa: BLE001
                    continue
            if coord_port is None:
                coord_port = DEFAULT_COORDINATOR_PORT
            env_base = {
                "COORDINATOR_ADDRESS": f"{coord_ip}:{coord_port}",
                "NUM_PROCESSES": str(n),
            }
            futures = [
                w.run.remote(self.fn, self.config,
                             {**env_base, "PROCESS_ID": str(i)})
                for i, w in enumerate(workers)]
            timeout = self.run_config.worker_timeout_s
            if timeout is not None:
                # hang detection: a worker stuck in a dead collective
                # never returns, so ray.get alone would block fit()
                # forever and FailureConfig.max_failures would never
                # trigger. Bound the attempt, surface WHICH workers
                # stalled, kill everything, and raise into the retry
                # loop (workers resume from the latest checkpoint).
                done, pending = ray.wait(futures,
                                         num_returns=len(futures),
                                         timeout=timeout)
                if pending:
                    stalled = sorted(i for i, f in enumerate(futures)
                                     if f in pending)
                    for w in workers:
                        try:
                            ray.kill(w)
                        except Exception:  # noqa: BLE001
                            pass
                    raise TimeoutError(
                        f"worker(s) {stalled} still running after "
                        f"{timeout}s (others done: {len(done)}/{n}); "
                        "killed all workers for retry-with-resume")
            results = ray.get(futures)
        finally:
            # PGs outlive their Python handles; without removal a retry
            # attempt would create a second PG against resources the
            # first still reserves and deadlock in pg.ready()
            try:
                ray.util.remove_placement_group(pg)
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                pass
        return Result(metrics=results[0] if results else {},
                      worker_metrics=list(results))

    def fit(self) -> Result:
        attempts = self.run_config.failure_config.max_failures + 1
        last_err: Optional[Exception] = None
        for attempt in range(attempts):
            try:
                if self.use_ray:
                    return self._fit_ray()
                return self._fit_local()
            except Exception as e:  # noqa: BLE001 - retry-with-resume path
                last_err = e
                logger.exception(
                    "training attempt %d/%d failed", attempt + 1, attempts)
        return Result(metrics={}, error=str(last_err))
