"""JaxTrainer — Ray Train style orchestration for JAX-on-TPU workers.

API parity with the reference's driver blocks
(ray-jobs/fine_tune_llama_ray.py:445-457, pytorch_llm_ray.py:346-376):
``JaxTrainer(train_loop_per_worker, train_loop_config, scaling_config,
run_config).fit() → Result(metrics)``. Differences, by design
(SURVEY.md row D1):

- One worker per TPU *host* (``resources_per_worker={"TPU": chips}``),
  not per accelerator: a single JAX process drives all local chips.
- Instead of MASTER_ADDR/PORT + NCCL process groups, the trainer elects
  worker 0's node as the JAX coordinator and injects
  COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID; workers then call
  ``parallel.mesh.distributed_init`` (SURVEY.md row D2/§5.8).
- ``FailureConfig(max_failures=N)`` is actually wired (the reference
  never configures it, §5.3); retried workers resume from the latest
  orbax checkpoint because every entry script restores-if-present.

Fault-tolerance model (one PR, three failure classes):

- **Genuine failures** (crash, InjectedKill, heartbeat/worker timeout)
  consume the ``max_failures`` budget and retry with exponential
  backoff + jitter.
- **Preemptions** (SIGTERM → ``train/preempt.py`` → the loop
  checkpoints and raises ``Preempted``) do NOT consume ``max_failures``
  — a spot eviction is not the job's fault — and are bounded by
  ``FailureConfig.max_preemptions`` instead.
- **Non-retryable errors** (KeyError/ValueError/TypeError/... — a
  config typo fails identically every attempt) fail fast on the first
  attempt with the original traceback in the log.

Liveness is supervised at step granularity when
``RunConfig.heartbeat_timeout_s`` is set (``rayint/supervisor.py``):
workers report per-step heartbeats, and a rank with no step progress
for that long is killed BY NAME — versus ``worker_timeout_s``, which
only bounds the whole attempt's wall clock.

Ray is optional at import time: with no Ray installed (or
``use_ray=False``) the trainer degrades to a single in-process worker —
that is also the unit-test path.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import time
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

try:  # pragma: no cover - exercised only on clusters with Ray installed
    import ray
    _HAS_RAY = True
except ImportError:
    ray = None
    _HAS_RAY = False

DEFAULT_COORDINATOR_PORT = 8476  # fallback when port discovery fails

# deterministic errors: retrying replays the identical failure N times
# and buries the real traceback under repetition. Matched by type AND
# name (Ray's serialized task errors may rebuild exception instances).
NONRETRYABLE_TYPES = (KeyError, ValueError, TypeError, AttributeError,
                      ImportError, NotImplementedError)
_NONRETRYABLE_NAMES = frozenset(t.__name__ for t in NONRETRYABLE_TYPES) | {
    "ModuleNotFoundError",
    # shardlint runtime-guard violations (analysis/guards.py) are
    # deterministic by construction — divergent traces and shape-churn
    # recompiles replay identically every attempt, and the guards'
    # contract is FAIL FAST with the diagnosis on top, not buried
    # under max_failures retries
    "GuardViolation", "HloDivergenceError", "RecompileLimitExceeded"}
# explicitly-retryable markers override the type match: a collective
# checkpoint-restore failure is often a ValueError underneath
# (orbax/tensorstore), but a fresh attempt re-reads storage
_RETRYABLE_NAMES = frozenset({"CheckpointRestoreError"})


@dataclasses.dataclass
class ScalingConfig:
    """ScalingConfig parity (fine_tune_llama_ray.py:445-449) with TPU
    resources instead of {"GPU": 1}."""
    num_workers: int = 1
    resources_per_worker: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"TPU": 4})
    placement_strategy: str = "SPREAD"

    @staticmethod
    def from_env() -> "ScalingConfig":
        """World shape from env — NUM_HOSTS/CHIPS_PER_HOST, the TPU
        analogues of NUM_NODES/NUM_GPUS_PER_NODE
        (fine_tune_llama_ray.py:439-441, SURVEY.md §5.6)."""
        hosts = int(os.environ.get("NUM_HOSTS",
                                   os.environ.get("NUM_NODES", "1")))
        chips = int(os.environ.get("CHIPS_PER_HOST",
                                   os.environ.get("NUM_GPUS_PER_NODE", "4")))
        return ScalingConfig(num_workers=hosts,
                             resources_per_worker={"TPU": chips})


@dataclasses.dataclass
class FailureConfig:
    # genuine-failure retry budget (crashes, hangs, timeouts)
    max_failures: int = 0
    # separate budget for spot/preemptible evictions: a preemption
    # checkpoints within its grace window and resumes, so it must not
    # burn a max_failures slot — but unbounded preemption churn on a
    # doomed node pool still needs a stop
    max_preemptions: int = 8


@dataclasses.dataclass
class RunConfig:
    name: str = "jax-train"
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    # elastic mesh re-formation (rayint/elastic.py): when a preemption
    # or failure's post-mortem shows the device pool changed (slice
    # eviction, spot shrink, node return), the next attempt re-resolves
    # the ExecutionPlan on the survivors (plan.replan), re-forms the
    # mesh, and restores resharded — instead of burning the retry
    # budget waiting for the old topology. None = $ELASTIC (default
    # off: a non-elastic job keeps the wait-for-identical behavior).
    elastic: Optional[bool] = None
    # the smallest pool worth re-forming on; below it the run fails
    # with a clear error instead of limping. None = $MIN_DEVICES or 1.
    min_devices: Optional[int] = None
    # Hang detection (SURVEY.md §5.3): with no bound, one wedged worker
    # (deadlocked collective, dead TPU host) blocks ray.get forever and
    # FailureConfig never gets its chance. When set, an attempt that
    # exceeds this wall-clock kills every worker and counts as a
    # failure, so retry-with-resume proceeds. None = wait forever (the
    # default: legitimate training runs have no universal time bound).
    worker_timeout_s: Optional[float] = None
    # Step-granular liveness (rayint/supervisor.py): kill the attempt —
    # naming the stalled rank — when a worker reports no step progress
    # for this long. Orthogonal to worker_timeout_s: this bounds the
    # gap BETWEEN steps, not the run. None = no heartbeat supervision.
    heartbeat_timeout_s: Optional[float] = None
    # base of the exponential backoff between genuine-failure retries
    # (delay = base * 2^(failures-1), capped at 60s, x jitter in
    # [0.5, 1.5)). None = $RETRY_BACKOFF_S or 1.0. Preemptions resume
    # immediately — their checkpoint is already durable.
    retry_backoff_s: Optional[float] = None


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    error: Optional[str] = None
    # per-worker metrics (worker 0 first); `metrics` is worker 0's view,
    # matching Ray Train's rank-0 convention, but nothing is dropped
    worker_metrics: Optional[list] = None
    # attempt metadata: "ok" | "failed" | "preempted" (budget exhausted)
    status: str = "ok"
    attempts: int = 1
    preemptions: int = 0
    # one dict per attempt: {"status", "error"?, "step"?, "resumed_step"?,
    # "ckpt_save_s"?, "nonretryable"?, "goodput" (the per-attempt
    # ledger, train/metrics.py LEDGER_TERMS + wall_s), "event"?
    # ("shrink"|"grow" on elastic pool changes), "pool"? (surviving
    # device count), "plan_fingerprint"?}
    attempt_log: list = dataclasses.field(default_factory=list)
    # summed goodput ledger across every attempt: LEDGER_TERMS +
    # "wall_s" + the headline "goodput_frac" (= step_s / wall_s) —
    # terms reconcile to wall-clock by construction (tests assert it)
    goodput: dict = dataclasses.field(default_factory=dict)


def _cause_chain(e: BaseException):
    """Walk explicit causes only (ray's .cause / raise-from __cause__) —
    __context__ drags in unrelated already-handled exceptions."""
    seen = set()
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        yield e
        e = getattr(e, "cause", None) or e.__cause__


def _find_preempted(e: BaseException):
    from gke_ray_train_tpu.train.preempt import Preempted
    for x in _cause_chain(e):
        if isinstance(x, Preempted) or type(x).__name__ == "Preempted":
            return x
    return None


def _is_nonretryable(e: BaseException) -> bool:
    for x in _cause_chain(e):
        # walked outermost-in: a retryable wrapper vouches for whatever
        # deterministic-looking cause sits beneath it
        if type(x).__name__ in _RETRYABLE_NAMES:
            return False
        if isinstance(x, NONRETRYABLE_TYPES) \
                or type(x).__name__ in _NONRETRYABLE_NAMES:
            return True
    return False


def _maybe_ingest_observed(obs, plan, config: dict) -> None:
    """Attempt-end feedback hook (autotune/registry.py): rank 0 of an
    AUTOTUNE=1 + obs-active attempt ingests its own observed rows back
    into the tuned-plan registry, so calibration data and drift alarms
    accumulate from real runs without separate tooling. Never fatal —
    a broken registry must not turn a finished attempt into a failure
    — and each row is refused on fingerprint/chip/backend drift
    exactly like ``apply``. AUTOTUNE_INGEST=0 opts out."""
    if obs is None or plan is None:
        return
    if not (getattr(plan, "autotune", False)
            and getattr(plan, "autotune_ingest", True)):
        return
    if str(getattr(obs, "rank", None)) != "0":
        return                     # one writer per attempt, like apply
    try:
        from gke_ray_train_tpu.autotune.registry import (
            entry_key, ingest_observed, model_digest, registry_dir)
        # map THIS attempt's runtime fingerprint onto its registry arm:
        # the runtime plan fingerprint covers operational fields the
        # search-time base/winner fingerprints don't, so the entry's
        # own arm map would never match it
        arms = {}
        key = getattr(plan, "_tuned_key", None)
        arm = "tuned"
        if key is None:
            arm = "base"
            from gke_ray_train_tpu.analysis.plancheck import (
                model_config_for)
            model_cfg = model_config_for(dict(config or {}), plan)
            if model_cfg is not None:
                key = entry_key(model_digest(model_cfg), plan.topology,
                                "train")
        if key is not None:
            arms[plan.fingerprint()] = (key, arm)
        summary = ingest_observed(
            obs.obs_dir, directory=registry_dir(config), config=config,
            runtime_arms=arms, log=logger)
        if summary["matched"] or summary["refusals"] or summary["drift"]:
            logger.info(
                "autotune ingest: %d observed row(s) matched, "
                "%d refusal(s), %d drift verdict(s) under %s",
                summary["matched"], len(summary["refusals"]),
                len(summary["drift"]), summary["directory"])
    except Exception as e:  # noqa: BLE001 - feedback must never be fatal
        logger.warning("autotune ingest hook skipped: %s", e)


def _run_worker(fn: Callable, config: dict, env: Dict[str, str],
                beat_fn: Optional[Callable] = None) -> dict:
    """Returns {"metrics", "resumed_step", "goodput",
    "plan_fingerprint"} — attempt metadata rides the payload because on
    the Ray path the worker context lives in another process and the
    driver could not read it otherwise."""
    os.environ.update(env)
    from gke_ray_train_tpu.analysis.guards import (
        install_recompile_limit, uninstall_recompile_limit)
    from gke_ray_train_tpu.obs import runtime as obs_runtime
    from gke_ray_train_tpu.perf.cache import (
        enable_persistent_cache, log_cache_summary)
    from gke_ray_train_tpu.plan import ExecutionPlan, PlanError
    from gke_ray_train_tpu.rayint.context import get_context
    from gke_ray_train_tpu.train import preempt
    # the worker's declarative ExecutionPlan (plan.py): resolved from
    # the same config+env the loop fn will read, logged up front so
    # every attempt states the plan identity it runs under. Purely
    # static — no backend is touched before distributed_init. Under an
    # elastic pool override the plan is re-resolved on the survivors
    # (the entry/worker fn does the same via rayint/elastic.py), so the
    # logged identity — and the compile-cache namespace — match what
    # the attempt actually compiles.
    # snapshot the tuned-overlay env keys BEFORE maybe_apply below can
    # export an entry's flash blocks — the finally must restore the
    # PRE-attempt values, or a dropped overlay's env leaks into a later
    # in-process attempt that runs untuned (attempt-scoped, like the
    # KERNELCHECK export further down)
    from gke_ray_train_tpu.autotune.space import ENV_OVERRIDE_KEYS
    prev_overrides = {k: os.environ.get(k) for k in ENV_OVERRIDE_KEYS}
    plan = None
    try:
        plan = ExecutionPlan.resolve(config)
        pool = os.environ.get("ELASTIC_N_DEVICES")
        try:
            pool_n = int(pool) if pool else None
        except ValueError:
            # same degrade as elastic_devices(): a malformed override
            # must not kill the attempt (and burn a failure slot)
            logger.warning("ELASTIC_N_DEVICES=%r is not an int; "
                           "ignoring the pool override", pool)
            pool_n = None
        if pool_n and pool_n != plan.chips:
            from gke_ray_train_tpu.plan import replan
            plan = replan(plan, pool_n)
        # tuned-plan overlay (autotune/registry.py): AFTER the replan,
        # so the registry lookup keys on the topology this attempt
        # actually runs — a reshard re-keys (usually a miss) instead of
        # a stale 8-device tune riding a 4-device attempt. Loud apply,
        # loud refusal; the cache enable below then namespaces by the
        # TUNED plan's compile fingerprint.
        if plan.autotune:
            from gke_ray_train_tpu.autotune.registry import maybe_apply
            plan, _ = maybe_apply(plan, config=config, log=logger)
        logger.info("execution plan %s (topology %s)",
                    plan.fingerprint(), plan.topology)
    except PlanError as e:
        # a config in a non-flat dialect (the pretrain driver refines
        # its plan in the entry) must not kill the attempt here
        logger.warning("worker-level plan resolution failed (%s); the "
                       "entry's own plan still applies", e)
    # attempt-scoped obs session (obs/runtime.py): per-rank event
    # stream + metrics registry + anomaly captures into the run's obs
    # dir, and the run_id/attempt/rank prefix on every text log line.
    # No-op (None) when obs is off or no dir resolves — the bare test
    # path stays telemetry-free.
    obs = obs_runtime.start_attempt(plan=plan, config=config)
    if obs is not None:
        obs.emit("attempt_start",
                 topology=plan.topology if plan is not None else None,
                 n_devices=plan.chips if plan is not None else None,
                 pool=os.environ.get("ELASTIC_N_DEVICES"))
    # compile-once across restarts: every attempt (and every retry of a
    # preempted worker) reuses the persistent XLA cache instead of
    # paying a full recompile. Config-only here — the backend must not
    # initialize before distributed_init; the entry scripts re-enable
    # after it so the cache dir gains the real topology fingerprint.
    enable_persistent_cache(plan=plan)
    # KERNELCHECK (config key wins over env, like every knob): export
    # the resolved value so run_training's attempt-start probe sees it
    # — the probe itself runs THERE, after distributed_init, because
    # verifying a kernel computes and the backend must not initialize
    # here in a multi-host worker. Scoped to the attempt (restored in
    # the finally below): in-process fits must not inherit a previous
    # config's setting through the process env.
    prev_kernelcheck = os.environ.get("KERNELCHECK")
    if "KERNELCHECK" in config:
        os.environ["KERNELCHECK"] = str(config["KERNELCHECK"])
    ctx = get_context()
    ctx.resumed_step = None      # fresh attempt, fresh metadata
    ctx.goodput = None
    ctx.plan_fingerprint = plan.fingerprint() if plan is not None else None
    ctx.set_heartbeat_sink(beat_fn)
    preempt.reset()              # a retry must not inherit the previous
    preempt.install()            # attempt's preemption flag
    try:
        # RECOMPILE_LIMIT teeth (analysis/guards.py): armed per attempt
        # so the count starts fresh on every retry — shape/dtype/
        # sharding churn past the limit raises from the compile path,
        # naming the function and the signature diff. Armed INSIDE the
        # try: the finally below must disarm it on every failure path,
        # or a raising log handler outlives the attempt
        install_recompile_limit(config=config)
        ret = fn(config)
        reported = ctx.last_reported
        return {"metrics": ret if ret is not None else (reported or {}),
                "resumed_step": ctx.resumed_step,
                "goodput": ctx.goodput,
                "plan_fingerprint": ctx.plan_fingerprint}
    finally:
        # seal the attempt's obs session on every path: worker_exit
        # event (with the ledger the loop parked on the context), final
        # metric export, stream closed — BEFORE the mesh teardown below
        import sys as _sys
        _exc = _sys.exc_info()[1]
        obs_runtime.end_attempt(
            "ok" if _exc is None else
            ("preempted" if _find_preempted(_exc) is not None
             else "failed"))
        # the sealed obs dir now holds this attempt's measured rows —
        # feed them back to the registry (rank 0, AUTOTUNE=1, never
        # fatal; AUTOTUNE_INGEST=0 opts out)
        _maybe_ingest_observed(obs, plan, config)
        # one line of compile-cache health per attempt: a warm restart
        # should show hits ≈ compile count and seconds saved
        log_cache_summary(logger)
        # a finished (or failed — its error surfaces via the future)
        # worker must never be reported as stalled
        ctx.heartbeat_done()
        uninstall_recompile_limit()
        # mesh teardown: the attempt's mesh dies with the attempt, and
        # the replicated-generate cache is the one thing that would
        # keep its device buffers alive across retries. sys.modules
        # guard, NOT an import: the cache can only be non-empty if
        # inference was already imported, and a fresh import inside
        # this finally could raise over the attempt's REAL error
        import sys
        inf_mod = sys.modules.get("gke_ray_train_tpu.inference")
        if inf_mod is not None:
            inf_mod.clear_generate_cache()
        # restore the default SIGTERM disposition: outside an attempt
        # nothing reads the preemption flag, and a long-lived driver
        # process must not silently swallow termination
        preempt.uninstall()
        # the attempt-scoped KERNELCHECK export (above) must not leak
        # into a later in-process fit whose config omits the key
        if "KERNELCHECK" in config:
            if prev_kernelcheck is None:
                os.environ.pop("KERNELCHECK", None)
            else:
                os.environ["KERNELCHECK"] = prev_kernelcheck
        for k, prev in prev_overrides.items():
            if prev is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = prev


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 use_ray: Optional[bool] = None):
        self.fn = train_loop_per_worker
        # copied: env-derived injections below must not leak into the
        # caller's dict (it may be reused or serialized as a job spec)
        self.config = dict(train_loop_config or {})
        # input-pipeline knob threaded through config so `ray job submit
        # --env PREFETCH_BATCHES=N` tunes the async prefetch depth
        # (data/prefetch.py) without editing the job JSON; an explicit
        # config value always wins over the driver env
        if "PREFETCH_BATCHES" in os.environ and \
                "PREFETCH_BATCHES" not in self.config:
            self.config["PREFETCH_BATCHES"] = \
                int(os.environ["PREFETCH_BATCHES"])
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.use_ray = (_HAS_RAY and self.scaling.num_workers >= 1
                        if use_ray is None else use_ray)
        # surviving device count of the last elastic pool change; when
        # set, every subsequent attempt's workers see it as
        # ELASTIC_N_DEVICES and re-form their mesh on it
        self._pool_override: Optional[int] = None
        # obs identity: fit() mints one OBS_RUN_ID per run and stamps
        # OBS_ATTEMPT per attempt into every worker's env, so all
        # ranks of all attempts correlate into one stream
        self._attempt = 0
        self._obs = None

    # -- elastic knobs -------------------------------------------------
    def _elastic(self) -> bool:
        if self.run_config.elastic is not None:
            return bool(self.run_config.elastic)
        from gke_ray_train_tpu.rayint.elastic import elastic_enabled
        return elastic_enabled(self.config)

    def _min_devices(self) -> int:
        if self.run_config.min_devices is not None:
            return max(int(self.run_config.min_devices), 1)
        from gke_ray_train_tpu.rayint.elastic import min_devices
        return min_devices(self.config)

    def _pool_env(self) -> Dict[str, str]:
        """Per-attempt worker env: the elastic pool override plus the
        obs run/attempt identity stamps."""
        env: Dict[str, str] = {}
        if self._obs is not None:
            # both worker paths route through _run_worker, whose first
            # action is os.environ.update(env) — one write site
            env["OBS_RUN_ID"] = self._obs.run_id
            env["OBS_ATTEMPT"] = str(self._attempt or 1)
            if self._obs.attempt_span_id is not None:
                # trace context (obs/trace.py): the driver's attempt
                # span is the causal parent of every worker attempt
                # span this attempt spawns
                from gke_ray_train_tpu.obs.runtime import PARENT_SPAN_ENV
                env[PARENT_SPAN_ENV] = self._obs.attempt_span_id
        if self._obs is None or self._obs.attempt_span_id is None:
            # local path shares os.environ across fits — a stale parent
            # from a previous traced fit must not adopt this attempt
            from gke_ray_train_tpu.obs.runtime import PARENT_SPAN_ENV
            os.environ.pop(PARENT_SPAN_ENV, None)
        # a RunConfig(elastic=True) opt-in must reach the worker-side
        # gate too (rayint/elastic.py reads config/env only) — else the
        # driver arms the override and the workers refuse to replan
        if self.run_config.elastic:
            env["ELASTIC"] = "1"
        if self._pool_override is not None:
            env["ELASTIC_N_DEVICES"] = str(self._pool_override)
            return env
        # local path shares os.environ across attempts — a cleared
        # override must not leave a stale pool behind
        os.environ.pop("ELASTIC_N_DEVICES", None)
        return env

    def _probe_pool(self) -> Optional[int]:
        """Post-mortem device-pool probe for failures whose exception
        carried no pool notice: the fault registry's emulated pool
        in-process (the CPU drill), best-effort and None elsewhere —
        a graceful pool change always arrives on Preempted.pool."""
        try:
            from gke_ray_train_tpu.testing.faults import current_pool
            return current_pool()
        except Exception:  # noqa: BLE001 - probe is best-effort
            return None

    # -- local ---------------------------------------------------------
    def _fit_local(self) -> tuple:
        from gke_ray_train_tpu.rayint.context import get_context
        from gke_ray_train_tpu.rayint.supervisor import (
            HeartbeatBoard, HeartbeatTimeout, Watchdog)
        env = {"NUM_PROCESSES": "1", "PROCESS_ID": "0",
               **self._pool_env()}
        hb = self.run_config.heartbeat_timeout_s
        board = HeartbeatBoard() if hb else None

        def _stall_capture(stalled):
            # obs stalled-rank anomaly (obs/capture.py): a best-effort
            # trace of whatever the device is doing RIGHT NOW, taken on
            # the watchdog thread before the wedged main thread is
            # interrupted — the only moment that trace can exist
            from gke_ray_train_tpu.obs import runtime as obs_runtime
            run = obs_runtime.active()
            if run is not None and run.capture is not None:
                run.capture.note_stalled_rank(
                    {"stalled": [list(s) for s in stalled],
                     "step": max((s[1] for s in stalled), default=-1)})

        wd = Watchdog(board, hb,
                      pre_interrupt=_stall_capture).start() if hb else None
        # the outer try also covers the cleanup and the return: a
        # watchdog SIGINT raised while the finally runs (worker finished
        # in the detection race window) must still be translated, not
        # escape fit() as a raw KeyboardInterrupt
        try:
            try:
                out = _run_worker(self.fn, self.config, env,
                                  beat_fn=board.beat if board else None)
            finally:
                if wd is not None:
                    wd.stop()
                if board is not None and self._obs is not None:
                    self._obs.export_supervisor(board.metrics_view(hb))
                get_context().set_heartbeat_sink(None)
            return Result(metrics=out["metrics"]), out
        except KeyboardInterrupt:
            # the watchdog interrupts the main thread on stall (the only
            # way to pry a single process out of a wedged collective);
            # translate it — a real Ctrl-C (no stall recorded) re-raises
            if wd is not None and wd.stalled_info:
                raise HeartbeatTimeout(wd.stalled_info, hb) from None
            raise

    # -- ray ----------------------------------------------------------
    @staticmethod
    def _kill_workers(workers) -> None:
        for w in workers:
            try:
                ray.kill(w)
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _get_result(future, rank: int, ips: list):
        """ray.get with per-rank error attribution: a worker exception
        re-raises naming the failing rank and its node IP ("a worker
        died" is undebuggable on a slice); Preempted passes through
        untouched for fit()'s classification."""
        try:
            return ray.get(future)
        except Exception as e:  # noqa: BLE001
            if _find_preempted(e) is not None:
                raise
            cause = getattr(e, "cause", None) or e.__cause__ or e
            raise RuntimeError(
                f"worker rank {rank} (node {ips[rank]}) failed: "
                f"{type(cause).__name__}: {cause}") from e

    def _fit_ray(self) -> tuple:
        if not ray.is_initialized():
            ray.init(address=os.environ.get("RAY_ADDRESS", "auto"))
        n = self.scaling.num_workers
        resources = dict(self.scaling.resources_per_worker)
        num_cpus = resources.pop("CPU", 1)

        @ray.remote(max_restarts=0)
        class Worker:
            def node_ip(self):
                return ray.util.get_node_ip_address()

            def free_port(self):
                # a port that is free NOW on the coordinator node; the
                # coordinator binds it moments later (standard
                # bind-0-release discovery, replaces the fixed 8476 that
                # collides on shared nodes)
                import socket
                s = socket.socket()
                s.bind(("", 0))
                port = s.getsockname()[1]
                s.close()
                return port

            def run(self, fn, config, env, supervisor=None):
                beat = None
                if supervisor is not None:
                    def beat(rank, step, done):
                        # fire-and-forget: the worker never blocks on
                        # its own liveness report
                        supervisor.beat.remote(rank, step, done)
                return _run_worker(fn, config, env, beat_fn=beat)

        hb_timeout = self.run_config.heartbeat_timeout_s
        # slice identity (rank → slice, the slice_index contract): with
        # NUM_SLICES declared, contiguous worker blocks form slices —
        # the same layout parallel/mesh.py emulates — so a stall/loss
        # confined to one slice is reported (and classified) as a
        # slice-scoped event, not an anonymous whole-job failure
        try:
            num_slices = int(self.config.get(
                "NUM_SLICES", os.environ.get("NUM_SLICES", "1")))
        except (TypeError, ValueError):
            num_slices = 1
        # rank → slice through the ONE contract function (its non-
        # tiling fallback collapses to a single domain, which carries
        # no slice-scoping information — treat it as no slice identity)
        from gke_ray_train_tpu.parallel.mesh import slice_assignments
        assign = slice_assignments(list(range(n)), num_slices)
        slice_map = (dict(enumerate(assign))
                     if len(set(assign)) > 1 else None)
        supervisor = None
        if hb_timeout:
            from gke_ray_train_tpu.rayint.supervisor import (
                HeartbeatTimeout, Supervisor)
            # tiny bookkeeping actor; released with its handle at return
            supervisor = ray.remote(Supervisor).options(num_cpus=0).remote()
            if slice_map:
                supervisor.set_slices.remote(slice_map)

        # honor placement_strategy: one bundle per worker, SPREAD puts
        # each TPU worker on its own host (the declared-but-unused
        # strategy from round 1)
        pg = ray.util.placement_group(
            [dict(resources, CPU=num_cpus) for _ in range(n)],
            strategy=self.scaling.placement_strategy)
        try:
            ray.get(pg.ready())
            try:
                from ray.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy)

                def sched(i):
                    return PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=i)
            except ImportError:  # very old ray: best-effort scheduling
                def sched(i):
                    return None

            workers = [
                Worker.options(resources=resources, num_cpus=num_cpus,
                               scheduling_strategy=sched(i)).remote()
                for i in range(n)]
            # all node IPs up front: worker 0's elects the coordinator,
            # the rest name the failing host in errors
            ips = ray.get([w.node_ip.remote() for w in workers])
            coord_ip = ips[0]
            coord_port = None
            for port_try in range(3):
                try:
                    coord_port = int(ray.get(workers[0].free_port.remote()))
                    break
                except Exception as e:  # noqa: BLE001 - transient RPC/bind
                    logger.warning(
                        "coordinator port discovery attempt %d/3 failed "
                        "(%s: %s); retrying", port_try + 1,
                        type(e).__name__, e)
                    time.sleep(0.2 * (2 ** port_try))
            if coord_port is None:
                coord_port = DEFAULT_COORDINATOR_PORT
                logger.error(
                    "coordinator port discovery failed after 3 attempts; "
                    "FALLING BACK to fixed port %d — this COLLIDES when "
                    "another job's coordinator shares the node",
                    DEFAULT_COORDINATOR_PORT)
            env_base = {
                "COORDINATOR_ADDRESS": f"{coord_ip}:{coord_port}",
                "NUM_PROCESSES": str(n),
            }
            # plan-scoped knobs ride to the workers explicitly — a
            # driver-side `env COMPILE_CACHE_DIR=...` or `env
            # TRANSFER_GUARD=disallow` must shape the workers even
            # without a Ray runtime-env entry. The key list is DERIVED
            # from the ExecutionPlan's config-key mapping (plan.py), so
            # a renamed knob cannot silently stop being forwarded.
            from gke_ray_train_tpu.plan import ENV_FORWARD_KEYS
            env_base.update({k: os.environ[k] for k in ENV_FORWARD_KEYS
                             if k in os.environ})
            # elastic + autotune-registry knobs + the per-attempt pool
            # override ride to the workers the same way (AUTOTUNE
            # itself is plan-scoped and already in ENV_FORWARD_KEYS;
            # the registry DIR is operational like KERNELCHECK)
            env_base.update({k: os.environ[k]
                             for k in ("ELASTIC", "MIN_DEVICES",
                                       "NUM_SLICES", "KERNELCHECK",
                                       "AUTOTUNE_DIR",
                                       "AUTOTUNE_DRIFT_BAND",
                                       "ASYNC_CKPT", "PEER_REPLICATION",
                                       "CKPT_COMMIT_TIMEOUT_S",
                                       "CKPT_STORAGE_DELAY_S")
                             if k in os.environ})
            env_base.update(self._pool_env())
            futures = [
                w.run.remote(self.fn, self.config,
                             {**env_base, "PROCESS_ID": str(i)}, supervisor)
                for i, w in enumerate(workers)]
            timeout = self.run_config.worker_timeout_s
            if timeout is not None or supervisor is not None:
                # supervised wait: poll for completion while checking
                # (a) step-granular heartbeat stalls — a wedged
                # collective or dead host is caught HEARTBEAT_TIMEOUT_S
                # after its last step, named by rank — and (b) the
                # whole-attempt wall-clock bound. Either kills every
                # worker and raises into the retry loop (workers resume
                # from the latest checkpoint).
                deadline = (time.monotonic() + timeout
                            if timeout is not None else None)
                # timeout=0 means "expire immediately", not "no bound" —
                # it must not reach min() as an empty candidate set
                slices = [t / 4.0 for t in (timeout, hb_timeout)
                          if t is not None and t > 0]
                poll_s = max(0.005, min(min(slices, default=5.0), 5.0))
                while True:
                    done, pending = ray.wait(futures,
                                             num_returns=len(futures),
                                             timeout=poll_s)
                    if not pending:
                        break
                    # a crashed rank completes-with-error while its
                    # collective partners wedge (and, pre-first-step,
                    # never even arm supervision) — the crash is the
                    # ROOT CAUSE and must surface NOW, on every poll,
                    # or a heartbeat-only config hangs forever hiding
                    # it. Preempted completions are NOT raised here:
                    # the other ranks are mid-grace-window-save and
                    # must be allowed to finish before collection.
                    for i, f in enumerate(futures):
                        if f not in done:
                            continue
                        try:
                            ray.get(f)
                        except Exception as e:  # noqa: BLE001
                            if _find_preempted(e) is not None:
                                continue
                            self._kill_workers(workers)
                            self._get_result(f, i, ips)  # raises wrapped
                    if supervisor is not None:
                        stalled = ray.get(
                            supervisor.stalled.remote(hb_timeout))
                        if stalled:
                            self._kill_workers(workers)
                            raise HeartbeatTimeout(stalled, hb_timeout,
                                                   slice_map=slice_map)
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        stalled_idx = sorted(
                            i for i, f in enumerate(futures)
                            if f in pending)
                        self._kill_workers(workers)
                        raise TimeoutError(
                            f"worker(s) {stalled_idx} still running after "
                            f"{timeout}s (others done: {len(done)}/{n}); "
                            "killed all workers for retry-with-resume")
            results = [self._get_result(f, i, ips)
                       for i, f in enumerate(futures)]
        finally:
            # obs supervisor export (driver side, best-effort): the
            # per-rank last-beat view — on a stall it NAMES the dead
            # rank in <obs_dir>/supervisor.json for `obs report`
            if supervisor is not None and self._obs is not None:
                try:
                    self._obs.export_supervisor(ray.get(
                        supervisor.metrics_view.remote(hb_timeout)))
                except Exception:  # noqa: BLE001 - telemetry only
                    pass
            # PGs outlive their Python handles; without removal a retry
            # attempt would create a second PG against resources the
            # first still reserves and deadlock in pg.ready()
            try:
                ray.util.remove_placement_group(pg)
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                pass
        return Result(
            metrics=results[0]["metrics"] if results else {},
            worker_metrics=[r["metrics"] for r in results]), \
            (results[0] if results else {})

    def _local_attempt_note(self, p) -> tuple:
        """(ledger, plan_fingerprint) of a failed/preempted attempt:
        Preempted carries its ledger across process boundaries; on the
        local path the loop's finally parked both on the context even
        when the attempt crashed."""
        led = getattr(p, "ledger", None) if p is not None else None
        fp = None
        if not self.use_ray:
            try:
                from gke_ray_train_tpu.rayint.context import get_context
                ctx = get_context()
                led = led if led is not None else ctx.goodput
                fp = ctx.plan_fingerprint
            except Exception:  # noqa: BLE001 - metadata is best-effort
                pass
        return led, fp

    def fit(self) -> Result:
        from gke_ray_train_tpu.obs import runtime as obs_runtime
        from gke_ray_train_tpu.train.metrics import (
            finish_ledger, sum_ledgers)
        fc = self.run_config.failure_config
        backoff_base = self.run_config.retry_backoff_s
        if backoff_base is None:
            backoff_base = float(os.environ.get("RETRY_BACKOFF_S", "1.0"))
        elastic = self._elastic()
        min_dev = self._min_devices()
        failures = 0
        preemptions = 0
        attempt = 0
        attempt_log: list = []
        # driver-side obs stream (obs/runtime.py): mints the shared
        # OBS_RUN_ID, then records one `attempt_end` per attempt — the
        # FINISHED ledger (lost_s = attempt-wall residual, so terms sum
        # to wall exactly) that `obs report` reconciles against — plus
        # the final `run_end`. None when obs is off / no dir resolves.
        self._obs = obs_runtime.start_driver(config=self.config)

        def finalize(result: Result) -> Result:
            result.attempts = attempt
            result.preemptions = preemptions
            result.attempt_log = attempt_log
            result.goodput = sum_ledgers(
                [e["goodput"] for e in attempt_log if "goodput" in e])
            if self._obs is not None:
                self._obs.note_run_end(result)
                self._obs.close()
                self._obs = None
            return result

        def note_attempt(entry: dict) -> None:
            if self._obs is not None:
                self._obs.note_attempt(attempt, entry)

        def classify_pool(p, entry, exc=None) -> Optional[Result]:
            """Elastic post-mortem: did the device pool change? Reads
            the pool off the preemption notice, the fault registry's
            emulated pool, or — for a heartbeat stall whose stalled
            ranks all sit on ONE slice — the slice-loss arithmetic.
            Records the shrink/grow event on the attempt entry, arms
            the override for the next attempt's workers, and returns a
            terminal Result when the survivors are below MIN_DEVICES."""
            pool = getattr(p, "pool", None) if p is not None else None
            if pool is None:
                pool = self._probe_pool()
            if pool is None and exc is not None:
                from gke_ray_train_tpu.rayint.supervisor import (
                    HeartbeatTimeout, slice_shrink_pool)
                for x in _cause_chain(exc):
                    if isinstance(x, HeartbeatTimeout) \
                            and x.uniform_slice is not None:
                        entry["slice"] = x.uniform_slice
                        per = float(self.scaling.resources_per_worker
                                    .get("TPU", 0))
                        if per > 0:
                            pool = slice_shrink_pool(
                                x.uniform_slice, x.slice_map, per)
                        break
            if pool is None or pool == self._pool_override or not elastic:
                return None
            prev = self._pool_override
            event = "shrink" if prev is None or pool < prev else "grow"
            entry["event"] = event
            entry["pool"] = int(pool)
            if pool < min_dev:
                msg = (f"device pool shrank to {pool} (< MIN_DEVICES="
                       f"{min_dev}); refusing to re-form — raise the "
                       "floor knowingly or wait for capacity")
                logger.error("%s", msg)
                entry["status"] = "failed"
                entry["error"] = msg
                # the terminal attempt must be noted BEFORE finalize
                # emits run_end and closes the driver stream — the
                # caller's note_attempt would hit a closed session
                note_attempt(entry)
                return finalize(Result(metrics={}, error=msg,
                                       status="failed"))
            self._pool_override = int(pool)
            logger.warning(
                "elastic %s event: next attempt re-forms the mesh on "
                "%d devices (restore reshards from the logical spec)",
                event, pool)
            return None

        while True:
            attempt += 1
            self._attempt = attempt       # stamped into worker env
            if self._obs is not None:
                # mint the attempt span id BEFORE the workers launch —
                # _pool_env forwards it as their causal parent; the
                # span itself lands at note_attempt with the verdict
                self._obs.begin_attempt(attempt)
            t_attempt = time.perf_counter()
            try:
                result, out = self._fit_ray() if self.use_ray \
                    else self._fit_local()
                entry = {
                    "status": "ok",
                    "resumed_step": out.get("resumed_step"),
                    "goodput": finish_ledger(
                        out.get("goodput"),
                        time.perf_counter() - t_attempt)}
                if out.get("plan_fingerprint"):
                    entry["plan_fingerprint"] = out["plan_fingerprint"]
                if self._pool_override is not None:
                    entry["pool"] = self._pool_override
                attempt_log.append(entry)
                note_attempt(entry)
                return finalize(result)
            except Exception as e:  # noqa: BLE001 - classified below
                wall = time.perf_counter() - t_attempt
                p = _find_preempted(e)
                led, fp = self._local_attempt_note(p)
                goodput = finish_ledger(led, wall)
                if self._obs is not None:
                    from gke_ray_train_tpu.rayint.supervisor import (
                        HeartbeatTimeout)
                    for x in _cause_chain(e):
                        if isinstance(x, HeartbeatTimeout):
                            self._obs.note_stall(x.stalled, x.timeout_s,
                                                 attempt=attempt)
                            break
                if p is not None:
                    # preempted: checkpointed within the grace window and
                    # exited cleanly — not a failure, does NOT consume
                    # max_failures; bounded by its own budget
                    preemptions += 1
                    entry = {
                        "status": "preempted",
                        "step": getattr(p, "step", None),
                        "resumed_step": getattr(p, "resumed_step", None),
                        "ckpt_save_s": getattr(p, "save_s", None),
                        "goodput": goodput}
                    if fp:
                        entry["plan_fingerprint"] = fp
                    attempt_log.append(entry)
                    stop = classify_pool(p, entry)
                    if stop is not None:
                        return stop      # classify noted the attempt
                    note_attempt(entry)
                    if preemptions > fc.max_preemptions:
                        logger.error(
                            "preemption budget exhausted "
                            "(max_preemptions=%d): %s",
                            fc.max_preemptions, e)
                        return finalize(Result(metrics={}, error=str(e),
                                               status="preempted"))
                    logger.warning(
                        "attempt %d preempted (%s); resuming from the "
                        "saved checkpoint (preemption %d/%d; max_failures "
                        "budget untouched)", attempt, e, preemptions,
                        fc.max_preemptions)
                    continue  # immediate: the checkpoint is durable
                if _is_nonretryable(e):
                    logger.exception(
                        "attempt %d failed with non-retryable %s; NOT "
                        "retrying (a deterministic error fails "
                        "identically every attempt)", attempt,
                        type(e).__name__)
                    entry = {"status": "failed", "error": str(e),
                             "nonretryable": True, "goodput": goodput}
                    if fp:
                        entry["plan_fingerprint"] = fp
                    attempt_log.append(entry)
                    note_attempt(entry)
                    return finalize(Result(metrics={}, error=str(e),
                                           status="failed"))
                # a failure whose post-mortem shows the pool changed
                # (slice eviction without grace, heartbeat stall with
                # the slice-loss signature) is a SHRINK event, not a
                # max_failures burn — the hardware leaving is not the
                # job's fault any more than a polite SIGTERM is
                entry = {"status": "failed", "error": str(e),
                         "goodput": goodput}
                if fp:
                    entry["plan_fingerprint"] = fp
                attempt_log.append(entry)
                stop = classify_pool(None, entry, exc=e)
                if stop is not None:
                    return stop          # classify noted the attempt
                if entry.get("event"):
                    entry["status"] = "preempted"
                    preemptions += 1
                    note_attempt(entry)
                    if preemptions > fc.max_preemptions:
                        logger.error(
                            "preemption budget exhausted "
                            "(max_preemptions=%d): %s",
                            fc.max_preemptions, e)
                        return finalize(Result(metrics={}, error=str(e),
                                               status="preempted"))
                    logger.warning(
                        "attempt %d lost to a pool change (%s); "
                        "re-forming on %d devices (preemption %d/%d; "
                        "max_failures budget untouched)", attempt, e,
                        entry["pool"], preemptions, fc.max_preemptions)
                    continue
                note_attempt(entry)
                failures += 1
                logger.exception(
                    "training attempt %d failed (failure %d/%d)",
                    attempt, failures, fc.max_failures)
                if failures > fc.max_failures:
                    return finalize(Result(metrics={}, error=str(e),
                                           status="failed"))
                # exponential backoff + jitter: a mass restart (whole
                # slice lost) must not thundering-herd the coordinator
                delay = min(backoff_base * (2 ** (failures - 1)), 60.0)
                delay *= 0.5 + random.random()
                if delay > 0:
                    logger.info("retrying in %.1fs (backoff + jitter)",
                                delay)
                    time.sleep(delay)
