from gke_ray_train_tpu.rayint.trainer import (  # noqa: F401
    JaxTrainer, ScalingConfig, RunConfig, FailureConfig, Result)
from gke_ray_train_tpu.rayint.context import (  # noqa: F401
    get_context, report)
from gke_ray_train_tpu.rayint.supervisor import (  # noqa: F401
    HeartbeatBoard, HeartbeatTimeout, Supervisor, Watchdog)
from gke_ray_train_tpu.rayint.serving import (  # noqa: F401
    ServeDeployment, ServeReplica)
