"""Elastic mesh re-formation — the worker-side half (ROADMAP #1/#4).

The trainer's retry loop (``rayint/trainer.py``) classifies a pool
change (slice eviction, spot shrink, node return) post-mortem and
injects the surviving device count into the next attempt's worker env
(``ELASTIC_N_DEVICES``). This module is what the worker/entry side
does with it:

- :func:`elastic_devices` — the devices this attempt may use. On real
  hardware an evicted slice's devices simply are not in
  ``jax.devices()``; on the fake/CPU drill the pool is emulated by
  truncating the device list, which — per the ``slice_index`` contract
  (``parallel/mesh.py::slice_assignments``, contiguous blocks) — is
  exactly "the last slice(s) were evicted".
- :func:`maybe_replan` — re-resolve the declared :class:`ExecutionPlan`
  against the surviving pool via ``plan.replan`` (data/fsdp reflowed,
  structural axes kept, global batch preserved, budget pin dropped) and
  log the re-formation. A no-op when the pool matches the plan or
  elasticity is off — a non-elastic job keeps today's behavior of
  waiting for its original topology.

Knobs (env and/or flat config, audited in ``config.py`` KNOWN_KEYS):

- ``ELASTIC=1`` opts a job into mesh re-formation (default off).
- ``MIN_DEVICES=N`` floors the pool the trainer will re-form on —
  below it the run fails instead of limping (default 1).

Stdlib-only until a device list is actually needed — importable by the
driver-side trainer without jax.
"""

from __future__ import annotations

import logging
import os
from typing import Any, List, Optional, Tuple

logger = logging.getLogger(__name__)

# per-attempt worker env the trainer injects after a pool change
POOL_ENV = "ELASTIC_N_DEVICES"


def _knob(name: str, config=None) -> Optional[str]:
    if config is not None and name in config:
        return str(config[name])
    return os.environ.get(name)


def elastic_enabled(config=None) -> bool:
    v = _knob("ELASTIC", config)
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def min_devices(config=None) -> int:
    v = _knob("MIN_DEVICES", config)
    try:
        return max(int(v), 1) if v is not None else 1
    except ValueError:
        logger.warning("MIN_DEVICES=%r is not an int; using 1", v)
        return 1


def elastic_devices(devices=None) -> List[Any]:
    """The device pool this attempt runs on: ``jax.devices()`` (or the
    given list) truncated to ``$ELASTIC_N_DEVICES`` when the trainer
    marked the pool shrunken. Truncation takes the FIRST n devices —
    the emulated hybrid layout assigns slices to contiguous blocks, so
    this is eviction of the last slice(s), matching what a real
    eviction does to ``jax.devices()``."""
    if devices is None:
        import jax
        devices = jax.devices()
    devices = list(devices)
    raw = os.environ.get(POOL_ENV)
    if not raw:
        return devices
    try:
        n = int(raw)
    except ValueError:
        logger.warning("%s=%r is not an int; using the full pool",
                       POOL_ENV, raw)
        return devices
    if 0 < n < len(devices):
        return devices[:n]
    return devices


def maybe_replan(plan, devices=None, *, config=None, model_cfg=None,
                 log: Optional[logging.Logger] = None
                 ) -> Tuple[Any, List[Any]]:
    """(plan, devices) for this attempt: the declared plan re-resolved
    against the surviving pool when elasticity is on and the pool
    changed. Raises ``PlanError`` when no feasible reflow exists (the
    trainer fails fast with the findings — PLAN001/002 surfaced, not
    crashed)."""
    devs = elastic_devices(devices)
    # re-form ONLY on a trainer-issued pool notice: a declared topology
    # that simply differs from the host's device count (a deliberate
    # subset/debug run) is not an elastic event and must not be
    # silently replanned
    if not os.environ.get(POOL_ENV) or len(devs) == plan.chips \
            or not elastic_enabled(config):
        return plan, devs
    import time

    from gke_ray_train_tpu.plan import replan
    t_replan0 = time.perf_counter()
    new_plan = replan(plan, len(devs), model_cfg=model_cfg)
    replan_dt = time.perf_counter() - t_replan0
    (log or logger).warning(
        "elastic re-formation: pool %d -> %d devices; plan %s -> %s "
        "(mesh %s, per_device_batch %d, topology %s)",
        plan.chips, len(devs), plan.fingerprint(), new_plan.fingerprint(),
        {a: getattr(new_plan, a) for a in new_plan.axis_names()},
        new_plan.per_device_batch, new_plan.topology)
    # obs: the reshard is a first-class run event — `obs report`
    # renders it on the attempt timeline (no-op when obs is off)
    from gke_ray_train_tpu.obs import runtime as obs_runtime
    obs_runtime.emit(
        "reshard", from_devices=plan.chips, to_devices=len(devs),
        from_fingerprint=plan.fingerprint(),
        to_fingerprint=new_plan.fingerprint(),
        mesh={a: getattr(new_plan, a) for a in new_plan.axis_names()},
        per_device_batch=new_plan.per_device_batch)
    # ...and a causal span (obs/trace.py): the plan-level half of the
    # reshard twin pair (ckpt/manager.py spans the resharded restore)
    obs_runtime.span_add(
        "reshard", replan_dt, from_devices=plan.chips,
        to_devices=len(devs), where="replan")
    return new_plan, devs
