"""Dataset preparation — wikitext-2 to shared storage.

Parity with the reference's data-prep Ray task
(ray-jobs/prepare_wikitext2_ray_job.py:18-91): per split, download
wikitext-2-raw-v1 via HF datasets, join the text lines, write one raw
file; idempotently skip existing non-empty files (:39-47). The function is
plain (the Ray decoration lives in the entry script, as in the reference)
so it also runs locally and in tests with a synthetic fallback corpus.
"""

from __future__ import annotations

import logging
import os
from typing import Dict

logger = logging.getLogger(__name__)

SPLITS = ("train", "validation", "test")


def _target_path(out_dir: str, split: str) -> str:
    return os.path.join(out_dir, f"wikitext2_{split}.txt")


def prepare_wikitext2(out_dir: str, *,
                      splits=SPLITS,
                      force: bool = False,
                      synthetic_fallback: bool = False,
                      synthetic_chars: int = 200_000) -> Dict[str, str]:
    """Write one concatenated raw-text file per split; returns
    {split: path}. Idempotent: existing non-empty files are kept
    (prepare_wikitext2_ray_job.py:39-47 behavior).

    ``synthetic_fallback``: in an offline environment (no HF hub egress),
    generate a deterministic synthetic corpus instead of failing — keeps
    the smoke path runnable anywhere.
    """
    os.makedirs(out_dir, exist_ok=True)
    out = {}
    todo = []
    for split in splits:
        path = _target_path(out_dir, split)
        out[split] = path
        if not force and os.path.exists(path) and os.path.getsize(path) > 0:
            logger.info("%s exists and is non-empty; skipping", path)
            continue
        todo.append(split)
    if not todo:
        return out

    try:
        if synthetic_fallback and not hub_reachable():
            raise ConnectionError("HF hub unreachable (offline probe)")
        from datasets import load_dataset
        for split in todo:
            ds = load_dataset("wikitext", "wikitext-2-raw-v1", split=split)
            text = "\n".join(ds["text"])
            with open(out[split], "w") as f:
                f.write(text)
            logger.info("wrote %s (%d chars)", out[split], len(text))
    except Exception as e:  # zero-egress env, hub outage, ...
        if not synthetic_fallback:
            raise
        logger.warning("falling back to synthetic corpus (%s)", e)
        for split in todo:
            text = _synthetic_corpus(
                seed=hash(split) % (2 ** 31),
                n_chars=synthetic_chars if split == "train"
                else synthetic_chars // 10)
            with open(out[split], "w") as f:
                f.write(text)
    return out


def hub_reachable(timeout: float = 3.0) -> bool:
    """Cheap egress probe — load_dataset in a zero-egress container can
    hang for minutes on connect timeouts; fail fast instead."""
    if os.environ.get("HF_HUB_OFFLINE") == "1" or \
            os.environ.get("HF_DATASETS_OFFLINE") == "1":
        return False
    import socket
    try:
        with socket.create_connection(("huggingface.co", 443),
                                      timeout=timeout):
            return True
    except OSError:
        return False


def _synthetic_corpus(seed: int, n_chars: int) -> str:
    """Deterministic fake-wiki text with word-like statistics (zipfian
    vocab, sentences, headings) — enough structure for a char LM to have
    something learnable."""
    import numpy as np
    rng = np.random.default_rng(seed)
    vocab = ["the", "of", "and", "in", "to", "a", "was", "is", "for", "on",
             "as", "by", "with", "he", "she", "at", "from", "that", "it",
             "his", "her", "were", "are", "which", "this", "first", "album",
             "game", "season", "city", "river", "war", "king", "church",
             "north", "south", "century", "world", "state", "team", "music",
             "film", "series", "station", "university", "history"]
    probs = 1.0 / np.arange(1, len(vocab) + 1)
    probs /= probs.sum()
    parts = []
    total = 0
    while total < n_chars:
        if rng.random() < 0.02:
            head = " ".join(rng.choice(vocab, size=2, p=probs)).title()
            s = f"\n = {head} = \n"
        else:
            n = int(rng.integers(5, 18))
            words = rng.choice(vocab, size=n, p=probs)
            s = " ".join(words).capitalize() + ". "
        parts.append(s)
        total += len(s)
    return "".join(parts)[:n_chars]
