"""SFT data formatting — chat templates + prompt-masked tokenization.

Parity with the reference's gretelai text-to-SQL formatter
(format_gretel_sql_for_sft_chat_template,
ray-jobs/fine_tune_llama_ray.py:257-273: system prompt from schema+context,
user question, assistant SQL answer) and the downsample-with-seed behavior
(:288-289, shuffle(seed=42) → select(N)).

Improvement over the reference: the reference's SFTTrainer trains on the
whole templated string (prompt included); here prompt tokens get weight 0
by default (``train_on_prompt=False``) — completion-only loss.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

SQL_SYSTEM_PROMPT = (
    "You are a text-to-SQL assistant. Given a database schema and a "
    "question, write the SQL query that answers the question.\n"
    "Schema:\n{schema}\nContext:\n{context}")


def format_gretel_sql_example(row: Dict) -> Dict[str, str]:
    """gretelai/synthetic_text_to_sql row → {system, user, assistant}."""
    return {
        "system": SQL_SYSTEM_PROMPT.format(
            schema=row.get("sql_context", ""),
            context=row.get("sql_task_type", "")),
        "user": row.get("sql_prompt", ""),
        "assistant": row.get("sql", ""),
    }


def render_chat(tokenizer, msgs: Dict[str, str],
                add_generation_prompt: bool = False) -> str:
    """Render via the tokenizer's own chat template when available (the
    reference relies on Llama-3's template; Mistral/Gemma templates come
    for free the same way), else a plain readable fallback."""
    chat = [{"role": "system", "content": msgs["system"]},
            {"role": "user", "content": msgs["user"]}]
    if not add_generation_prompt:
        chat.append({"role": "assistant", "content": msgs["assistant"]})
    if getattr(tokenizer, "chat_template", None):
        return tokenizer.apply_chat_template(
            chat, tokenize=False, add_generation_prompt=add_generation_prompt)
    parts = [f"<|{m['role']}|>\n{m['content']}\n" for m in chat]
    if add_generation_prompt:
        parts.append("<|assistant|>\n")
    return "".join(parts)


def tokenize_sft_example(tokenizer, msgs: Dict[str, str], *,
                         max_len: int,
                         train_on_prompt: bool = False) -> Dict[str, np.ndarray]:
    """→ {input_ids [L], loss_weights [L]} with prompt tokens masked.

    The prompt/completion split is computed by tokenizing the
    generation-prompt prefix separately — robust to any chat template.
    """
    full = render_chat(tokenizer, msgs, add_generation_prompt=False)
    prefix = render_chat(tokenizer, msgs, add_generation_prompt=True)
    full_ids = np.asarray(tokenizer(full, add_special_tokens=False)["input_ids"],
                          np.int32)[:max_len]
    prefix_ids = tokenizer(prefix, add_special_tokens=False)["input_ids"]
    n_prompt = min(len(prefix_ids), len(full_ids))
    weights = np.ones(len(full_ids), np.float32)
    if not train_on_prompt:
        weights[:n_prompt] = 0.0
    return {"input_ids": full_ids, "loss_weights": weights}


def pad_sft_rows(examples: List[Dict[str, np.ndarray]], seq_len: int,
                 *, pad_id: int = 0) -> Dict[str, np.ndarray]:
    """Unpacked path: one example per row, right-padded to seq_len.
    → {inputs, targets, weights} each [N, seq_len]."""
    n = len(examples)
    inputs = np.full((n, seq_len), pad_id, np.int32)
    targets = np.full((n, seq_len), pad_id, np.int32)
    weights = np.zeros((n, seq_len), np.float32)
    for i, ex in enumerate(examples):
        ids = np.asarray(ex["input_ids"], np.int32)[: seq_len + 1]
        w = np.asarray(ex["loss_weights"], np.float32)[: seq_len + 1]
        L = len(ids) - 1
        if L < 1:
            continue
        inputs[i, :L] = ids[:-1]
        targets[i, :L] = ids[1:]
        weights[i, :L] = w[1:]
    return {"inputs": inputs, "targets": targets, "weights": weights}


def sft_epoch_batches(rows: Dict[str, np.ndarray], global_batch: int, *,
                      num_hosts: int = 1, host_id: int = 0, seed: int = 42,
                      epoch: int = 0, shuffle: bool = True,
                      group_by_length: bool = False):
    """Shuffle + shard + batch pre-padded SFT rows ([N, S] arrays).
    Mirrors ShardedBatches' host partitioning for the SFT path.

    ``group_by_length`` (reference GROUP_BY_LENGTH,
    fine_tune_config.json:29; HF LengthGroupedSampler semantics): batches
    are formed from similar-length examples (less padding waste), with
    the *batch order* reshuffled per epoch."""
    n = len(rows["inputs"])
    if group_by_length:
        lengths = np.count_nonzero(rows["inputs"], axis=1)
        by_len = np.argsort(lengths, kind="stable")[::-1]
        nb = max(n // global_batch, 0)
        batches = by_len[:nb * global_batch].reshape(nb, global_batch)
        if shuffle:
            np.random.default_rng(seed + epoch).shuffle(batches, axis=0)
        # the tail joins at the end so no example is ever dropped
        order = np.concatenate([batches.reshape(-1),
                                by_len[nb * global_batch:]])
    else:
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed + epoch).shuffle(order)
    host_batch = global_batch // num_hosts
    steps = n // global_batch
    for s in range(steps):
        chunk = order[s * global_batch:(s + 1) * global_batch]
        mine = chunk[host_id::num_hosts][:host_batch]
        yield {k: v[mine] for k, v in rows.items()}
    # tail: the last n % global_batch examples train too (HF Trainer's
    # dataloader keeps the final incomplete batch by default, and so did
    # the reference; both paths here used to silently drop it — ADVICE
    # r3 #2). The batch is padded to full host_batch with zero-weight
    # rows so the placed global shape stays constant (one compiled step)
    # and every host yields in lockstep.
    rem = order[steps * global_batch:]
    if len(rem):
        mine = rem[host_id::num_hosts][:host_batch]
        batch = {k: v[mine] for k, v in rows.items()}
        pad = host_batch - len(mine)
        if pad:
            batch = {k: np.concatenate(
                [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                for k, v in batch.items()}
        yield batch


def synthetic_sql_rows(n: int, seed: int = 0) -> List[Dict]:
    """Deterministic gretel-schema-shaped rows for offline/smoke runs."""
    rng = np.random.default_rng(seed)
    tables = ["users", "orders", "events", "products", "sessions"]
    cols = ["id", "name", "ts", "amount", "status", "region"]
    rows = []
    for _ in range(n):
        t = tables[int(rng.integers(len(tables)))]
        c = cols[int(rng.integers(len(cols)))]
        rows.append({
            "sql_context": f"CREATE TABLE {t} ({c} INT, value INT);",
            "sql_task_type": "analytics",
            "sql_prompt": f"total value by {c} in {t}",
            "sql": f"SELECT {c}, SUM(value) FROM {t} GROUP BY {c};",
            "sql_complexity": "window functions" if rng.random() < 0.3
            else "basic",
        })
    return rows


def downsample(rows: List, n: Optional[int], seed: int = 42) -> List:
    """shuffle(seed=42).select(range(n)) parity
    (fine_tune_llama_ray.py:288-289)."""
    if n is None or n >= len(rows):
        return list(rows)
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(rows))[:n]
    return [rows[int(i)] for i in idx]
