from gke_ray_train_tpu.data.tokenizer import (  # noqa: F401
    CharTokenizer, ByteTokenizer, load_hf_tokenizer,
    load_saved_tokenizer, save_tokenizer,
    PAD_ID, BOS_ID, EOS_ID, UNK_ID)
from gke_ray_train_tpu.data.lm_dataset import (  # noqa: F401
    SlidingWindowDataset, ShardedBatches)
from gke_ray_train_tpu.data.sft import (  # noqa: F401
    format_gretel_sql_example, render_chat, tokenize_sft_example, downsample,
    pad_sft_rows, sft_epoch_batches, synthetic_sql_rows)
from gke_ray_train_tpu.data.packing import (  # noqa: F401
    pack_examples, batch_packed)
from gke_ray_train_tpu.data.prepare import prepare_wikitext2  # noqa: F401
from gke_ray_train_tpu.data.prefetch import (  # noqa: F401
    Prefetcher, SyncBatchSource, make_batch_source)
