"""Sliding-window next-token LM dataset + per-host sharded batching.

Replaces the reference's TextDataset (ray-jobs/pytorch_llm_ray.py:107-119,
input ids[i:i+L], target ids[i+1:i+L+1]) and the DistributedSampler that
``train.torch.prepare_data_loader`` injects (:216, epoch reshuffle
:265-266). TPU-redesign: no per-sample __getitem__/collate — whole batches
are gathered from the token array with one vectorized numpy indexing op;
each host owns a disjoint stride of the global batch sequence (SURVEY.md
row D9).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, Optional

import numpy as np


@dataclasses.dataclass
class SlidingWindowDataset:
    ids: np.ndarray          # [N] int32 token stream
    seq_len: int

    def __post_init__(self):
        self.ids = np.asarray(self.ids, dtype=np.int32)

    def __len__(self) -> int:
        return max(0, len(self.ids) - self.seq_len)

    def gather(self, starts: np.ndarray) -> Dict[str, np.ndarray]:
        """Vectorized batch gather: one fancy-index instead of B python
        __getitem__ calls + collate."""
        offsets = np.arange(self.seq_len + 1, dtype=np.int64)
        windows = self.ids[starts[:, None] + offsets[None, :]]
        return {
            "inputs": windows[:, :-1].astype(np.int32),
            "targets": windows[:, 1:].astype(np.int32),
            "weights": np.ones((len(starts), self.seq_len), np.float32),
        }


@dataclasses.dataclass
class ShardedBatches:
    """Deterministic, seeded, per-host-sharded batch iterator.

    Epoch reshuffling parity with sampler.set_epoch
    (pytorch_llm_ray.py:265-266): pass a different ``epoch`` to
    ``iter_epoch``. ``max_samples`` mirrors the reference's test_run
    16k-sample cap (pytorch_llm_ray.py:198-201).
    """
    dataset: SlidingWindowDataset
    global_batch: int
    num_hosts: int = 1
    host_id: int = 0
    seed: int = 42
    shuffle: bool = True
    drop_last: bool = True
    max_samples: Optional[int] = None

    def __post_init__(self):
        if self.global_batch % self.num_hosts != 0:
            raise ValueError(
                f"global batch {self.global_batch} must divide evenly over "
                f"{self.num_hosts} hosts")
        self.host_batch = self.global_batch // self.num_hosts

    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.max_samples is not None:
            n = min(n, self.max_samples)
        return n // self.global_batch if self.drop_last else (
            (n + self.global_batch - 1) // self.global_batch)

    def iter_epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.dataset)
        if self.max_samples is not None:
            n = min(n, self.max_samples)
        order = np.arange(n, dtype=np.int64)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + epoch)
            rng.shuffle(order)
        steps = self.steps_per_epoch()
        for s in range(steps):
            chunk = order[s * self.global_batch:(s + 1) * self.global_batch]
            mine = chunk[self.host_id::self.num_hosts]
            batch = self.dataset.gather(mine)
            if len(mine) < self.host_batch:  # last partial batch, pad
                pad = self.host_batch - len(mine)
                batch = {
                    k: np.concatenate(
                        [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                    for k, v in batch.items()}
            yield batch
