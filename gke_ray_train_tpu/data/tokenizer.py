"""Tokenizers.

CharTokenizer is capability parity with the reference's
(ray-jobs/pytorch_llm_ray.py:20-55): fit char↔id vocab on raw text,
encode/decode, JSON save/load. Ids 0..3 are reserved so segment-id /
padding conventions hold everywhere (the reference has no pad token and
relies on drop_last batching; we make padding explicit).

HF tokenizers (Llama etc.) are loaded lazily through ``transformers`` —
only the tokenizer, never torch model code.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
UNK_ID = 3
_RESERVED = {PAD_ID: "<pad>", BOS_ID: "<bos>", EOS_ID: "<eos>",
             UNK_ID: "<unk>"}


class CharTokenizer:
    """Character-level tokenizer for the from-scratch pre-train path."""

    def __init__(self, stoi: Optional[Dict[str, int]] = None):
        self.stoi: Dict[str, int] = dict(stoi or {})
        self.itos: Dict[int, str] = {i: s for s, i in self.stoi.items()}

    @classmethod
    def fit(cls, text: str) -> "CharTokenizer":
        chars = sorted(set(text))
        stoi = {ch: i + len(_RESERVED) for i, ch in enumerate(chars)}
        return cls(stoi)

    @property
    def vocab_size(self) -> int:
        return len(self.stoi) + len(_RESERVED)

    def encode(self, text: str) -> np.ndarray:
        return np.fromiter((self.stoi.get(ch, UNK_ID) for ch in text),
                           dtype=np.int32, count=len(text))

    def decode(self, ids) -> str:
        return "".join(self.itos.get(int(i), "") for i in ids
                       if int(i) not in _RESERVED)

    def save(self, path: str) -> None:
        """One serialization shared with the artifact sidecar
        (save_tokenizer): {"type": "char", "stoi": ...}."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"type": "char", "stoi": self.stoi}, f)

    @classmethod
    def load(cls, path: str) -> "CharTokenizer":
        with open(path) as f:
            return cls(json.load(f)["stoi"])


class ByteTokenizer:
    """UTF-8 byte tokenizer (vocab 256 + reserved ids) — the offline /
    smoke-test stand-in for an HF tokenizer: same call surface
    (``__call__ → {"input_ids"}``, ``decode``, ``eos_token_id``)."""

    chat_template = None
    eos_token_id = EOS_ID
    pad_token_id = PAD_ID

    @property
    def vocab_size(self) -> int:
        return 256 + len(_RESERVED)

    def __call__(self, text: str, add_special_tokens: bool = False):
        ids = [b + len(_RESERVED) for b in text.encode("utf-8")]
        return {"input_ids": ids}

    def encode(self, text: str) -> List[int]:
        return self(text)["input_ids"]

    def decode(self, ids) -> str:
        # ids outside [len(_RESERVED), 256 + len(_RESERVED)) are skipped
        # like reserved ids: a model whose vocab is padded past the byte
        # range (e.g. to a sharding-divisible size) can legitimately
        # emit them while untrained, and decode must degrade like
        # errors="replace" does — not crash the inference comparison
        bs = bytes(int(i) - len(_RESERVED) for i in ids
                   if len(_RESERVED) <= int(i) < 256 + len(_RESERVED))
        return bs.decode("utf-8", errors="replace")


def load_hf_tokenizer(model_id: str, hf_token: Optional[str] = None):
    """Replacement for AutoTokenizer.from_pretrained at
    ray-jobs/fine_tune_llama_ray.py:207-209 (incl. pad-token fixup)."""
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(model_id, token=hf_token)
    if tok.pad_token is None:
        tok.pad_token = tok.eos_token
    return tok


# sidecar name for the non-HF tokenizers; deliberately NOT
# "tokenizer.json" (that name belongs to HF fast-tokenizer files)
GRAFT_TOKENIZER_FILE = "graft_tokenizer.json"


def save_tokenizer(tok, out_dir: str) -> None:
    """Save the tokenizer next to the model weights so the export dir is
    a self-contained artifact (the reference ships the tokenizer with
    every merged/full model — fine_tune_llama_ray.py:355,374, and with
    the pre-train checkpoint — pytorch_llm_ray.py tokenizer save).

    HF tokenizers write their standard files (``tokenizer_config.json``
    etc. — ``AutoTokenizer.from_pretrained(out_dir)`` then loads the dir
    directly); ByteTokenizer/CharTokenizer write a small JSON sidecar
    that :func:`load_saved_tokenizer` round-trips."""
    os.makedirs(out_dir, exist_ok=True)
    if hasattr(tok, "save_pretrained"):
        tok.save_pretrained(out_dir)
        return
    path = os.path.join(out_dir, GRAFT_TOKENIZER_FILE)
    if isinstance(tok, CharTokenizer):
        tok.save(path)  # same {"type","stoi"} format as CharTokenizer
    elif isinstance(tok, ByteTokenizer):
        with open(path, "w") as f:
            json.dump({"type": "byte"}, f)
    else:
        raise TypeError(f"cannot save tokenizer of type {type(tok)!r}")


def load_saved_tokenizer(model_dir: str):
    """Load whatever :func:`save_tokenizer` put in ``model_dir``:
    the graft sidecar when present, else AutoTokenizer conventions
    (the same call a reference user makes on its output dirs)."""
    sidecar = os.path.join(model_dir, GRAFT_TOKENIZER_FILE)
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            data = json.load(f)
        kind = data.get("type")
        # legacy char files predate the "type" field but carry "stoi"
        if kind == "char" or (kind is None and "stoi" in data):
            if "stoi" not in data:
                raise ValueError(
                    f"char tokenizer sidecar {sidecar} has no 'stoi' "
                    "vocabulary — the file is corrupted")
            return CharTokenizer(data["stoi"])
        if kind == "byte":
            return ByteTokenizer()
        # an unknown type must FAIL, not silently decode with the wrong
        # vocabulary (ADVICE r5 #3: a future 'bpe' sidecar or corrupted
        # JSON used to fall through to ByteTokenizer)
        raise ValueError(
            f"unrecognized tokenizer sidecar type {kind!r} in {sidecar} "
            "(known: 'char', 'byte') — refusing to guess a vocabulary")
    return load_hf_tokenizer(model_dir)
