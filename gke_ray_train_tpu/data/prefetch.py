"""Asynchronous input pipeline: background prefetch + sharded placement.

The reference delegates input to HF ``datasets`` over a GCS FUSE mount
and eats the host-side stall every step — tokenize/pack and the
host→device transfer run serially with the train step, so the TPU idles
whenever the host is the bottleneck (the packed-4k and SFT regimes).
Production JAX stacks (MaxText's multihost dataloading, tf.data-style
pipelined ETL) hide this by prefetching N batches ahead on background
threads and landing them pre-sharded on device.

Two things must overlap to fix the input-bound regime:

1. **device compute vs. host work** — jax's asynchronous dispatch
   already gives the loop ~one step of lookahead, but only while the
   host keeps dispatching; any host stall (slow FUSE read, tokenizer
   hiccup) lands directly in the step cadence.
2. **host production with itself** — when producing one batch
   (read+tokenize+pack+place) costs more than a step, the pipeline is
   host-bound and lookahead cannot help; the only fix is overlapping
   the production of batch N+1..N+k with batch N. The worker pool here
   parallelizes the ``place_fn`` stage: ``workers`` threads pull from
   the iterator (serialized under a lock — Python iterators admit no
   concurrent ``next``), run ``place_fn`` concurrently, and deliver
   **in ticket order**, so the consumed stream is byte-identical to the
   serial one. ``place_fn`` is therefore where expensive per-batch work
   must live to parallelize: the sharded form-up of
   ``parallel.placement.make_place_batch`` (batches land distributed
   over the mesh, never staged replicated) and — as
   ``bench.py::bench_input_bound`` shows — any read/tokenize/pack stage
   routed into it (the iterator then yields cheap work descriptors,
   tf.data ``map``-style). GIL-releasing work (FUSE/network reads,
   ``device_put``, HF fast tokenizers) genuinely parallelizes; work
   left inside the iterator gains only overlap #1.

Backpressure: a worker may not start placing ticket T until
``T < consumed + depth``, bounding device-resident prefetched batches at
``depth`` (plus the ≤ ``workers`` currently being placed).

Shared contract of :class:`Prefetcher` and :class:`SyncBatchSource`
(the ``prefetch=0`` inline path — one iteration shape in the loop):

- **resume fast-forward skip**: the first ``skip`` batches are consumed
  from the iterator but NEVER transferred (``place_fn`` not called) —
  replaying a resumed epoch costs tokenize time only, no device traffic.
- **wait accounting**: ``consume_wait()`` returns host seconds the
  consumer spent blocked since the last call — the loop books it into
  :meth:`train.metrics.ThroughputMeter.data_wait`, surfacing the
  data-stall fraction per log window.
- **exception propagation**: an iterator/placement error re-raises at
  the consumer's ``next()`` (type preserved), after every batch that
  preceded it — exactly like the inline path.
- **clean shutdown**: ``close()`` stops the workers and joins them;
  epoch-boundary exhaustion drains and joins automatically.

Determinism: ticket-ordered delivery means a prefetched run consumes
the identical batch stream — losses are bitwise identical to the
synchronous path (pinned by tests/test_prefetch.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, Optional


class _Failure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class SyncBatchSource:
    """The inline (no-thread) batch source: pull → skip-or-place → yield.

    Counters after iteration: ``yielded`` = batches pulled from the
    underlying iterator (skipped included), ``skipped`` = resume
    fast-forward batches consumed without placement.
    """

    def __init__(self, iterable: Iterable[Dict], *,
                 place_fn: Optional[Callable] = None, skip: int = 0):
        self._it = iter(iterable)
        self._place = place_fn
        self._skip = max(int(skip), 0)
        self.yielded = 0
        self.skipped = 0
        self._wait = 0.0

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        try:
            while True:
                batch = next(self._it)
                self.yielded += 1
                if self.skipped < self._skip:
                    self.skipped += 1
                    continue
                if self._place is not None:
                    batch = self._place(batch)
                return batch
        finally:
            self._wait += time.perf_counter() - t0

    def consume_wait(self) -> float:
        w, self._wait = self._wait, 0.0
        return w

    def close(self) -> None:
        pass


class Prefetcher:
    """Bounded multi-worker prefetch with on-thread device placement and
    deterministic (ticket-ordered) delivery."""

    def __init__(self, iterable: Iterable[Dict], *,
                 place_fn: Optional[Callable] = None, depth: int = 2,
                 skip: int = 0, workers: Optional[int] = None,
                 name: str = "batch-prefetch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = iter(iterable)
        self._place = place_fn
        self._skip = max(int(skip), 0)
        self.yielded = 0
        self.skipped = 0
        self._wait = 0.0
        self.depth = depth
        # default: one placement worker per queue slot (backpressure
        # bounds useful concurrency at `depth` anyway), capped at 8 so a
        # deep queue does not spawn a thread horde; explicit `workers`
        # still clamps to depth — extra producers would only park
        self.workers = max(1, min(int(workers) if workers
                                  else min(depth, 8), depth))
        self._src_lock = threading.Lock()   # iterator pull + ticketing
        self._cond = threading.Condition()  # results / backpressure
        self._results: Dict[int, object] = {}
        self._next_ticket = 0   # next ticket a worker will take
        self._next_out = 0      # next ticket the consumer will deliver
        self._end_ticket: Optional[int] = None  # tickets == stream length
        self._exhausted = False
        self._stop = threading.Event()
        self._done = False
        self._threads = [
            threading.Thread(target=self._work, name=f"{name}-{i}",
                             daemon=True)
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # -- worker side ---------------------------------------------------
    def _work(self) -> None:
        while not self._stop.is_set():
            # pull + ticket under one lock: tickets follow iterator order
            with self._src_lock:
                if self._exhausted:
                    return
                try:
                    batch = next(self._it)
                except StopIteration:
                    self._exhausted = True
                    self._finish(self._next_ticket)
                    return
                except BaseException as e:  # noqa: BLE001 - consumer raises
                    self._exhausted = True
                    ticket = self._next_ticket
                    self._next_ticket += 1
                    self._deliver(ticket, _Failure(e))
                    self._finish(ticket + 1)
                    return
                self.yielded += 1
                if self.skipped < self._skip:
                    # resume fast-forward: consumed, never transferred
                    self.skipped += 1
                    continue
                ticket = self._next_ticket
                self._next_ticket += 1
            # backpressure: at most `depth` placed-but-undelivered batches
            with self._cond:
                while not self._stop.is_set() and \
                        ticket >= self._next_out + self.depth:
                    self._cond.wait(0.05)
            if self._stop.is_set():
                return
            try:
                item = self._place(batch) if self._place is not None \
                    else batch
            except BaseException as e:  # noqa: BLE001 - consumer raises
                item = _Failure(e)
            self._deliver(ticket, item)

    def _deliver(self, ticket: int, item) -> None:
        with self._cond:
            self._results[ticket] = item
            self._cond.notify_all()

    def _finish(self, end_ticket: int) -> None:
        with self._cond:
            if self._end_ticket is None:
                self._end_ticket = end_ticket
            self._cond.notify_all()

    # -- consumer side -------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        item = None
        ended = dead = False
        try:
            with self._cond:
                while True:
                    if self._next_out in self._results:
                        item = self._results.pop(self._next_out)
                        self._next_out += 1
                        self._cond.notify_all()  # open the window
                        break
                    if self._end_ticket is not None and \
                            self._next_out >= self._end_ticket:
                        self._done = ended = True
                        break
                    if not any(t.is_alive() for t in self._threads):
                        self._done = dead = True
                        break
                    self._cond.wait(0.1)
        finally:
            self._wait += time.perf_counter() - t0
        # joins happen OUTSIDE the condition lock (a worker parked on it
        # could never exit otherwise)
        if ended:
            for t in self._threads:
                t.join(timeout=10.0)
            raise StopIteration
        if dead:
            raise RuntimeError("prefetch workers exited without a result "
                               "(killed thread?)")
        if isinstance(item, _Failure):
            self._done = True
            self.close()
            raise item.exc
        return item

    def consume_wait(self) -> float:
        w, self._wait = self._wait, 0.0
        return w

    def close(self) -> None:
        """Stop the workers and reclaim the threads. Safe to call twice,
        and after normal exhaustion (then it is a no-op join)."""
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=10.0)
        self._done = True


def make_batch_source(iterable: Iterable[Dict], *,
                      place_fn: Optional[Callable] = None, depth: int = 0,
                      skip: int = 0, workers: Optional[int] = None):
    """``depth >= 1`` → background :class:`Prefetcher`; ``depth <= 0`` →
    inline :class:`SyncBatchSource`. One call site, one iteration shape."""
    if depth and depth > 0:
        return Prefetcher(iterable, place_fn=place_fn, depth=depth,
                          skip=skip, workers=workers)
    return SyncBatchSource(iterable, place_fn=place_fn, skip=skip)
