"""Sequence packing with segment IDs (SURVEY.md §5.7 — new scope).

The reference exposes PACKING/GROUP_BY_LENGTH flags but ships with both
off (fine_tune_config.json:28-29); its attention has no segment masking so
packing would leak across documents. Here packing is first-class: packed
batches carry segment_ids + within-segment positions, and the model's
attention mask isolates segments exactly (ops/attention.py).

Greedy first-fit packing; segment id 0 is reserved for padding.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional

import numpy as np


def pack_examples(examples: Iterable[Dict[str, np.ndarray]], seq_len: int,
                  *, pad_id: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """examples: iterable of {input_ids [L], loss_weights [L]} (L <= anything;
    longer examples are truncated to seq_len+1 tokens).

    Yields packed rows: inputs/targets [seq_len], weights [seq_len],
    segment_ids [seq_len], positions [seq_len]. Targets are next-token
    within each segment; the boundary token of each segment predicts
    nothing (weight 0) instead of leaking into the next document.
    """
    buf_ids: List[np.ndarray] = []

    def emit(buf: List[np.ndarray]) -> Dict[str, np.ndarray]:
        inputs = np.full(seq_len, pad_id, np.int32)
        targets = np.full(seq_len, pad_id, np.int32)
        weights = np.zeros(seq_len, np.float32)
        segs = np.zeros(seq_len, np.int32)
        pos = np.zeros(seq_len, np.int32)
        off = 0
        for si, (ids, w) in enumerate(buf, start=1):
            L = len(ids)
            inputs[off:off + L - 1] = ids[:-1]
            targets[off:off + L - 1] = ids[1:]
            weights[off:off + L - 1] = w[1:]
            segs[off:off + L - 1] = si
            pos[off:off + L - 1] = np.arange(L - 1)
            off += L - 1
        return {"inputs": inputs, "targets": targets, "weights": weights,
                "segment_ids": segs, "positions": pos}

    used = 0
    for ex in examples:
        ids = np.asarray(ex["input_ids"], np.int32)[: seq_len + 1]
        w = np.asarray(ex["loss_weights"], np.float32)[: seq_len + 1]
        if len(ids) < 2:
            continue
        need = len(ids) - 1  # tokens of sequence space this example uses
        if used + need > seq_len and used > 0:
            yield emit(buf_ids)
            buf_ids, used = [], 0
        buf_ids.append((ids, w))
        used += need
    if buf_ids:
        yield emit(buf_ids)


def batch_packed(packed: Iterable[Dict[str, np.ndarray]],
                 batch_size: int, *, drop_last: bool = True,
                 pad_id: int = 0,
                 seq_len: Optional[int] = None) -> Iterator[Dict[str, np.ndarray]]:
    """Stack packed rows into [B, S] batches; final partial batch is padded
    with empty rows unless dropped."""
    rows: List[Dict[str, np.ndarray]] = []
    for r in packed:
        rows.append(r)
        if len(rows) == batch_size:
            yield {k: np.stack([r[k] for r in rows]) for k in rows[0]}
            rows = []
    if rows and not drop_last:
        S = seq_len if seq_len is not None else len(rows[0]["inputs"])
        empty = {"inputs": np.full(S, pad_id, np.int32),
                 "targets": np.full(S, pad_id, np.int32),
                 "weights": np.zeros(S, np.float32),
                 "segment_ids": np.zeros(S, np.int32),
                 "positions": np.zeros(S, np.int32)}
        while len(rows) < batch_size:
            rows.append(empty)
        yield {k: np.stack([r[k] for r in rows]) for k in rows[0]}
