"""Shared logging machinery: warn-once + run-correlation prefixing.

Trace-time fallback warnings (dense-mask attention fallback, dense
prefill, shallow pipeline microbatches, unknown MFU roofline) must fire
once per distinct shape/config key — not once per step, and not
silently. One seen-set for the whole package so the pattern cannot
drift per module (ADVICE-style reuse; was four private copies).

``configure_run_logging`` stamps every stdlib log line with the same
``run_id``/``attempt``/``rank`` correlation fields the obs event
stream carries (``obs/events.py`` STAMP_FIELDS), so text logs and
events join on one grep: ``grep 'run=<id>' worker.log events-*.jsonl``.
"""

from __future__ import annotations

import logging

_seen: set = set()
_run_filter = None


class _RunContextFilter(logging.Filter):
    """Prepend ``[run=<id> a<attempt> r<rank>]`` to every record, once
    (a record passing through several handlers must not stack prefixes;
    the prefix is a literal — no ``%`` — so ``record.args`` stay
    valid)."""

    def __init__(self, prefix: str):
        super().__init__()
        self.prefix = prefix

    def filter(self, record: logging.LogRecord) -> bool:
        if not getattr(record, "_run_prefixed", False):
            record.msg = f"{self.prefix} {record.msg}"
            record._run_prefixed = True
        return True


def configure_run_logging(run_id, attempt, rank) -> str:
    """Install (or replace — one filter per process, re-armed each
    attempt) the correlation prefix on every root handler. Returns the
    prefix. With no root handler yet, ``basicConfig`` is applied first
    so worker processes spawned without an entry script still carry
    the fields."""
    global _run_filter
    prefix = f"[run={run_id} a{int(attempt)} r{rank}]"
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(level=logging.INFO,
                            format="%(asctime)s %(name)s: %(message)s")
    for h in root.handlers:
        if _run_filter is not None:
            h.removeFilter(_run_filter)
    _run_filter = _RunContextFilter(prefix)
    for h in root.handlers:
        h.addFilter(_run_filter)
    return prefix


def clear_run_logging() -> None:
    """Remove the correlation prefix (attempt end — the next attempt
    re-arms). The filter MUTATES records, so leaving it installed
    outside an attempt would stamp unrelated log lines (and break any
    caller asserting on raw messages)."""
    global _run_filter
    if _run_filter is None:
        return
    for h in logging.getLogger().handlers:
        h.removeFilter(_run_filter)
    _run_filter = None


def warn_once(logger: logging.Logger, key, msg: str, *args) -> None:
    """Emit ``logger.warning(msg, *args)`` the first time ``key`` is
    seen; subsequent calls with the same key are silent. Tests may clear
    ``_seen`` (monkeypatch) to re-arm."""
    if key in _seen:
        return
    _seen.add(key)
    logger.warning(msg, *args)
