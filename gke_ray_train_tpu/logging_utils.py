"""Shared warn-once machinery.

Trace-time fallback warnings (dense-mask attention fallback, dense
prefill, shallow pipeline microbatches, unknown MFU roofline) must fire
once per distinct shape/config key — not once per step, and not
silently. One seen-set for the whole package so the pattern cannot
drift per module (ADVICE-style reuse; was four private copies).
"""

from __future__ import annotations

import logging

_seen: set = set()


def warn_once(logger: logging.Logger, key, msg: str, *args) -> None:
    """Emit ``logger.warning(msg, *args)`` the first time ``key`` is
    seen; subsequent calls with the same key are silent. Tests may clear
    ``_seen`` (monkeypatch) to re-arm."""
    if key in _seen:
        return
    _seen.add(key)
    logger.warning(msg, *args)
