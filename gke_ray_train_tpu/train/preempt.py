"""Graceful preemption (SIGTERM) handling for spot/preemptible hosts.

On GKE, spot and preemptible TPU pod-slices are evicted with a SIGTERM
followed by a grace window (``PREEMPT_GRACE_S``, default 25s — the GCE
preemption notice) before SIGKILL. The dominant production failure mode
is therefore *not* a crash: it is a polite request to leave. This module
turns that request into a flag the train loop checks at each step
boundary (``train/loop.py``): on preemption the loop force-saves a
checkpoint, waits until it is durable, and raises :class:`Preempted` —
a status the trainer's retry loop (``rayint/trainer.py``) deliberately
does NOT count against ``FailureConfig.max_failures`` (it is bounded by
``max_preemptions`` instead; the hardware did nothing wrong).

Slice evictions signal every host of the slice; the loop additionally
AGREES on the exit step with a per-boundary host allgather (multi-host
only, ``train/loop.py``) so async-dispatch skew cannot send ranks into
forced saves at different steps — all ranks enter the same collective
save.

Stdlib-only by design: importable from the driver-side trainer without
pulling in jax.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)

DEFAULT_GRACE_S = 25.0

_flag = threading.Event()
_deadline: Optional[float] = None   # monotonic end of the grace window
_installed = False
_prev_handler = None
_lock = threading.Lock()
# device-pool target attached to the preemption (elastic training): a
# slice eviction / spot shrink notice names the SURVIVING chip count,
# so the trainer's post-mortem can re-form the mesh instead of burning
# retries waiting for the old topology (rayint/trainer.py)
_pool: Optional[int] = None


class Preempted(Exception):
    """The distinct "preempted" exit status of a training attempt.

    Carries the attempt metadata the trainer records: the step the loop
    stopped at, the step it had resumed from, how long the forced
    checkpoint save took (must fit the grace window), the surviving
    device-pool size when the preemption was a pool-change notice
    (elastic shrink/grow — ``pool``), and the attempt's goodput ledger
    (``train/metrics.py``) so a preempted attempt's wall-clock
    decomposition survives the exception path.
    """

    def __init__(self, step: int, resumed_step: Optional[int] = None,
                 save_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 pool: Optional[int] = None,
                 ledger: Optional[dict] = None):
        self.step = step
        self.resumed_step = resumed_step
        self.save_s = save_s
        self.grace_s = grace_s
        self.pool = pool
        self.ledger = ledger
        saved = (f"checkpoint durable in {save_s:.2f}s"
                 if save_s is not None else "no checkpoint manager — "
                 "nothing saved")
        super().__init__(f"preempted at step {step} ({saved})")


def grace_s() -> float:
    """SIGTERM→SIGKILL window advertised by the platform."""
    return float(os.environ.get("PREEMPT_GRACE_S", DEFAULT_GRACE_S))


def _handler(signum, frame):  # pragma: no cover - exercised via trigger()
    request(source="SIGTERM")


def install() -> bool:
    """Install the SIGTERM handler (idempotent). Returns False when the
    caller is not the main thread (flag-based ``request`` still works)."""
    global _installed, _prev_handler
    with _lock:
        if _installed:
            return True
        try:
            _prev_handler = signal.signal(signal.SIGTERM, _handler)
        except ValueError:
            logger.warning(
                "cannot install SIGTERM handler outside the main thread; "
                "preemption is still honored via preempt.request()")
            return False
        _installed = True
    return True


def uninstall() -> None:
    """Restore the previous SIGTERM disposition (test teardown)."""
    global _installed, _prev_handler
    with _lock:
        if not _installed:
            return
        try:
            signal.signal(signal.SIGTERM,
                          _prev_handler if _prev_handler is not None
                          else signal.SIG_DFL)
        except ValueError:  # pragma: no cover - non-main-thread teardown
            pass
        _installed = False
        _prev_handler = None


def request(source: str = "request", pool: Optional[int] = None) -> None:
    """Mark this process as preempted; the loop exits at the next step
    boundary. Safe from signal handlers and any thread. ``pool`` names
    the surviving device count when the preemption is a pool-change
    notice (slice eviction / spot shrink / node return) — the trainer
    reads it off the raised :class:`Preempted` and re-forms the mesh."""
    global _deadline, _pool
    if pool is not None:
        _pool = int(pool)
    if not _flag.is_set():
        _deadline = time.monotonic() + grace_s()
        logger.warning(
            "preemption requested (%s): %.0fs grace window — will "
            "checkpoint at the next step boundary and exit 'preempted'",
            source, grace_s())
    _flag.set()


def pool_target() -> Optional[int]:
    """Surviving device count attached to the pending preemption, if
    the notice was a pool change (None = plain eviction of this job)."""
    return _pool


def trigger() -> None:
    """Deliver a preemption the way the platform would: a real SIGTERM
    when the handler is installed (exercising the signal path), the flag
    directly otherwise (non-main-thread workers)."""
    if _installed:
        os.kill(os.getpid(), signal.SIGTERM)
    else:
        request(source="trigger")


def requested() -> bool:
    return _flag.is_set()


def remaining_grace_s() -> Optional[float]:
    if _deadline is None:
        return None
    return max(0.0, _deadline - time.monotonic())


def reset() -> None:
    """Clear the flag (start of a fresh attempt — a retried attempt must
    not inherit the previous attempt's preemption)."""
    global _deadline, _pool
    _flag.clear()
    _deadline = None
    _pool = None
