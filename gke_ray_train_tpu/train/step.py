"""The jitted train step — the visible, hackable hot loop.

This is the TPU re-design of the only training loop whose internals the
reference exposes (ray-jobs/pytorch_llm_ray.py:270-284: zero_grad →
forward → CrossEntropyLoss(flattened) → backward (DDP all-reduce) →
clip_grad_norm(1.0) → step → sched.step), plus the grad-accumulation the
fine-tune path gets from HF Trainer (fine_tune_config.json:14).

TPU-first differences:
- One jitted function does microbatch scan + loss + grad + clip + update;
  gradient sync is *implicit* — GSPMD inserts the psum/reduce-scatter the
  sharding specs imply (no DDP hooks, SURVEY.md row D4).
- Grad accumulation is ``lax.scan`` over microbatches inside the step
  (no python-side loop, no re-dispatch per microbatch).
- Loss is token-weighted (padding/prompt masking), accumulated exactly:
  grads of the nll *sum* are averaged by total token weight at the end.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.transformer import (
    Params, forward, init_params, param_specs)
from gke_ray_train_tpu.parallel.mesh import BATCH_AXES
from gke_ray_train_tpu.parallel.sharding import tree_shardings
from gke_ray_train_tpu.train.lora import LoraConfig, init_lora, lora_specs

Batch = Dict[str, jnp.ndarray]

# trees at or under this many bytes init EAGERLY and are device_put onto
# the mesh (bitwise-identical to the plain path, zero init-program
# compiles); larger trees take the jitted sharded init (see
# make_train_state's docstring)
_EAGER_INIT_LIMIT = 256 * 2**20


class TrainState(NamedTuple):
    params: Params
    lora: Optional[Params]       # None unless LoRA mode
    opt_state: Any
    step: jnp.ndarray            # int32 scalar


def token_nll(logits: jnp.ndarray, targets: jnp.ndarray,
              weights: jnp.ndarray):
    """Sum of weighted token NLL + sum of weights (exact-mean bookkeeping).

    fp32 math regardless of compute dtype — same reduction the
    reference gets from CrossEntropyLoss over flattened logits
    (pytorch_llm_ray.py:233,275). Formulated as logsumexp(logits) -
    logits[target] rather than log_softmax + gather: identical values,
    but the [B, S, V] log-probability array (1 GB at 8B's 128k vocab)
    is never materialized — backward recomputes the softmax from the
    logits it already holds."""
    logits32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits32, axis=-1)
    tgt = jnp.take_along_axis(logits32, targets[..., None], axis=-1)[..., 0]
    w = weights.astype(jnp.float32)
    return jnp.sum((lse - tgt) * w), jnp.sum(w)


def opt_state_specs(optimizer: optax.GradientTransformation,
                    trainable_shapes: Any, trainable_specs: Any) -> Any:
    """PartitionSpec tree for an optax state: any subtree whose structure
    equals the trainable pytree (mu, nu, trace, ...) inherits the
    trainable's specs; every other leaf (counts, scalars) replicates.
    This is what makes optimizer state ZeRO-sharded by construction
    (SURVEY.md row D5)."""
    target_def = jax.tree.structure(trainable_shapes)

    def rec(node):
        if jax.tree.structure(node) == target_def and \
                jax.tree.leaves(node):
            return trainable_specs
        if hasattr(node, "_fields"):  # NamedTuple optax states
            return type(node)(*[rec(getattr(node, f)) for f in node._fields])
        if isinstance(node, (list, tuple)):
            return type(node)(rec(c) for c in node)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return P()

    return rec(jax.eval_shape(optimizer.init, trainable_shapes))


def make_train_state(cfg: ModelConfig, optimizer: optax.GradientTransformation,
                     key: jax.Array, *, mesh: Optional[Mesh] = None,
                     lora_cfg: Optional[LoraConfig] = None,
                     params: Optional[Params] = None) -> TrainState:
    """Initialize params (sharded at creation when a mesh is given — an 8B
    fp32 init must never materialize on one host) and optimizer state.

    ``params``: pass pre-built weights (hub-loaded, quantized) to skip
    the random init entirely — without this, a QLoRA caller substituting
    its own base would still materialize the full fp32 tree here first
    and OOM a single chip at 8B dims.

    Optimizer state shardings are *propagated* from param shardings by
    jitting optimizer.init — mu/nu inherit the fsdp sharding, scalars
    replicate. This is the ZeRO analogue (SURVEY.md row D5).

    Meshed init is SHARDING-INVARIANT: the meshed and plain paths — and
    any two elastic topologies — produce IDENTICAL values from the same
    key (the pipeline/moe matches-plain oracles rely on it; on jaxlib
    0.4.x non-partitionable threefry, a jitted draw's values otherwise
    CHANGE with its out_shardings — the seed-failure kernelcheck's
    sweeps ran down). Small trees init eagerly and are placed with
    ``device_put`` — plain-path-identical by construction, and no init
    program to compile; trees past ``_EAGER_INIT_LIMIT`` (an 8B fp32
    init must never materialize on one host) take the jitted sharded
    path under ``sharding_invariant_rng`` (partitionable threefry,
    scoped — the flag's ~15% generation cost is paid only at a scale
    where it is noise next to the init itself)."""
    from gke_ray_train_tpu.parallel.sharding import (
        shard_tree, sharding_invariant_rng)

    def tree_bytes(shapes) -> int:
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree.leaves(shapes))

    if params is None:
        if mesh is not None:
            abstract = jax.eval_shape(lambda k: init_params(cfg, k), key)
            if tree_bytes(abstract) <= _EAGER_INIT_LIMIT:
                params = shard_tree(init_params(cfg, key), mesh,
                                    param_specs(cfg))
            else:
                with sharding_invariant_rng():
                    p_shard = tree_shardings(mesh, param_specs(cfg))
                    params = jax.jit(lambda k: init_params(cfg, k),
                                     out_shardings=p_shard)(key)
        else:
            params = init_params(cfg, key)

    lora = None
    if lora_cfg is not None:
        lkey = jax.random.fold_in(key, 1)
        if mesh is not None:
            abstract = jax.eval_shape(
                lambda k: init_lora(cfg, lora_cfg, k), lkey)
            if tree_bytes(abstract) <= _EAGER_INIT_LIMIT:
                lora = shard_tree(init_lora(cfg, lora_cfg, lkey), mesh,
                                  lora_specs(cfg, lora_cfg))
            else:
                with sharding_invariant_rng():
                    l_shard = tree_shardings(mesh,
                                             lora_specs(cfg, lora_cfg))
                    lora = jax.jit(lambda k: init_lora(cfg, lora_cfg, k),
                                   out_shardings=l_shard)(lkey)
        else:
            lora = init_lora(cfg, lora_cfg, lkey)

    trainable = lora if lora is not None else params
    step = jnp.zeros((), jnp.int32)
    if mesh is not None:
        # Explicit out_shardings for every optimizer-state leaf: jit
        # propagation alone leaves constants (adam count) and
        # replicated-param moments on a single device, which breaks the
        # jitted step after a checkpoint restore commits them there.
        t_specs = (lora_specs(cfg, lora_cfg) if lora is not None
                   else param_specs(cfg))
        os_specs = opt_state_specs(optimizer, trainable, t_specs)
        opt_state = jax.jit(optimizer.init,
                            out_shardings=tree_shardings(mesh, os_specs))(
            trainable)
        step = jax.device_put(step, NamedSharding(mesh, P()))
    else:
        opt_state = jax.jit(optimizer.init)(trainable)
    return TrainState(params=params, lora=lora, opt_state=opt_state,
                      step=step)


# sentinel: distinguishes "caller did not pass it" (plan supplies the
# value) from an explicit override
_UNSET: Any = object()


def make_train_step(cfg: ModelConfig,
                    optimizer: optax.GradientTransformation,
                    *,
                    mesh: Optional[Mesh] = None,
                    lora_cfg: Optional[LoraConfig] = None,
                    grad_accum: Any = _UNSET,
                    schedule: Optional[Callable] = None,
                    donate: Any = _UNSET,
                    donate_batch: Any = _UNSET,
                    pipe_microbatches: Any = _UNSET,
                    plan=None
                    ) -> Callable[[TrainState, Batch], tuple]:
    """Build the jitted ``(state, batch) -> (state, metrics)`` function.

    ``plan``: an :class:`~gke_ray_train_tpu.plan.ExecutionPlan` — the
    declarative source for grad_accum / donation / pipeline
    microbatching (explicit kwargs still win), and the route through
    ``plan.compile_step_with_plan`` so training, bench and analysis
    share ONE compile surface.

    batch: dict with "inputs"/"targets" [B, S] int32, "weights" [B, S]
    float, optional "segment_ids"/"positions" [B, S]. B must be divisible
    by grad_accum; microbatches are scanned in sequence.

    ``donate_batch`` (with ``donate``): the batch argument is donated
    too — each step's device-resident batch buffers are freed eagerly
    instead of surviving until the Python reference dies. The input
    pipeline owns its own host copies and never re-feeds a placed batch
    (data/prefetch.py), so this is pure peak-memory headroom. Pass
    False when the SAME placed batch is fed repeatedly (bench timing
    loops) — a donated buffer must not be reused.

    ``pipe_microbatches``: pipeline microbatch count per forward when the
    mesh has a pipe axis > 1 (models/pipeline.py; default = stage count).
    """
    if grad_accum is _UNSET:
        grad_accum = plan.grad_accum if plan is not None else 1
    if donate is _UNSET:
        donate = plan.donate_state if plan is not None else True
    if donate_batch is _UNSET:
        donate_batch = plan.donate_batch if plan is not None else True
    if pipe_microbatches is _UNSET:
        pipe_microbatches = (plan.pipe_microbatches or None) \
            if plan is not None else None
    lora_mode = lora_cfg is not None
    lora_dropout = lora_cfg.dropout if lora_mode else 0.0
    moe = cfg.n_experts > 0
    overlap = plan.overlap if plan is not None else "off"
    fused_ops = plan.fused_ops if plan is not None else False
    # fused cross-entropy (ops/fused_ce.py) replaces materialized
    # logits + token_nll where its contract holds: no logit softcap
    # (the cap is applied to logits the kernel never forms) and no
    # pipeline mesh (the stage-folded batch spec is not the kernel
    # wrapper's row layout)
    fused_ce = (fused_ops and cfg.logit_softcap is None
                and (mesh is None or int(mesh.shape.get("pipe", 1)) == 1))

    manual_grad = None
    if overlap == "manual":
        from gke_ray_train_tpu.train.overlap import (
            check_manual_support, make_manual_grad_fn)
        check_manual_support(cfg, mesh, lora=lora_mode)
        manual_grad = make_manual_grad_fn(
            cfg, mesh,
            batch_keys=(plan.batch_keys() if plan is not None
                        else ("inputs", "targets", "weights")),
            fused_ops=fused_ops, use_fused_ce=fused_ce,
            # DCN-aware gradient sync (parallel/hierarchical.py): on a
            # multi-slice plan the reduction stages at the slice
            # boundary; DCN_SYNC picks the cross-slice payload and
            # DCN_COMPRESS=bf16 casts the hier hop with error feedback
            num_slices=plan.num_slices if plan is not None else 1,
            dcn_sync=plan.dcn_sync if plan is not None else "flat",
            dcn_compress=(plan.dcn_compress if plan is not None
                          else "none"))

    def micro_loss(trainable: Params, frozen: Params, micro: Batch,
                   drop_rng=None):
        fkw = dict(positions=micro.get("positions"),
                   segment_ids=micro.get("segment_ids"),
                   mesh=mesh, pipe_microbatches=pipe_microbatches,
                   with_aux=moe,
                   token_weights=micro["weights"] if moe else None,
                   fused_ops=fused_ops,
                   return_pre_unembed=fused_ce)
        if lora_mode:
            out = forward(frozen, micro["inputs"], cfg, lora=trainable,
                          lora_scale=lora_cfg.scale,
                          lora_dropout=lora_dropout,
                          lora_rng=drop_rng, **fkw)
        else:
            out = forward(trainable, micro["inputs"], cfg, **fkw)
        hidden, aux = out if moe else (out, None)
        if fused_ce:
            from gke_ray_train_tpu.models.transformer import unembed_head
            from gke_ray_train_tpu.ops.fused_ce import fused_cross_entropy
            dtype = jnp.dtype(cfg.dtype)
            # the head must come from the DIFFERENTIATED arg in full
            # fine-tuning (trainable == params is argnum 0 of grad_fn;
            # taking it from `frozen` would silently zero the lm_head /
            # tied-embed gradient). LoRA keeps the frozen base head —
            # adapters never train the unembedding.
            head_params = frozen if lora_mode else trainable
            nll, w = fused_cross_entropy(
                hidden, unembed_head(head_params, cfg).astype(dtype),
                micro["targets"], micro["weights"], mesh=mesh)
        else:
            nll, w = token_nll(hidden, micro["targets"], micro["weights"])
        if moe:
            # Switch load-balance term, billed per token so the final
            # divide-by-total-weight recovers ce_mean + coef * aux_mean
            nll = nll + cfg.router_aux_coef * aux["router_aux"] * w
        return nll, w

    def train_step(state: TrainState, batch: Batch):
        trainable = state.lora if lora_mode else state.params
        frozen = state.params

        def reshape(x):
            return x.reshape((grad_accum, x.shape[0] // grad_accum)
                             + x.shape[1:])
        micros = jax.tree.map(reshape, batch)

        grad_fn = jax.value_and_grad(micro_loss, has_aux=True)

        # LoRA dropout rng: deterministic per (step, microbatch) — derived
        # from the step counter so resume reproduces the same masks and
        # the step fn keeps its (state, batch) signature
        drop_rngs = None
        if lora_mode and lora_dropout > 0.0:
            drop_rngs = jax.random.split(
                jax.random.fold_in(jax.random.key(0), state.step),
                grad_accum)

        dcn_residual = manual_grad is not None \
            and getattr(manual_grad, "compressed", False)

        def accum(carry, xs):
            micro = xs[0]
            drop_rng = xs[1] if drop_rngs is not None else None
            if dcn_residual:
                g_acc, nll_acc, w_acc, resid = carry
                # compressed DCN hop with error feedback: microbatch
                # k's bf16 quantization residual feeds microbatch
                # k+1's pre-quantization value (train/overlap.py);
                # the step-final residual is dropped with the carry
                (nll, w), g, resid = manual_grad(trainable, micro,
                                                 resid)
                return (jax.tree.map(jnp.add, g_acc, g),
                        nll_acc + nll, w_acc + w, resid), None
            g_acc, nll_acc, w_acc = carry
            if manual_grad is not None:
                # the shard_map microbatch pipeline (train/overlap.py):
                # per-layer fsdp all-gathers double-buffered behind
                # compute, grads reduced with GSPMD's exact
                # accumulation structure — bitwise-identical to the
                # grad_fn branch, asserted by tests/test_overlap.py
                (nll, w), g = manual_grad(trainable, micro)
            else:
                (nll, w), g = grad_fn(trainable, frozen, micro, drop_rng)
            return (jax.tree.map(jnp.add, g_acc, g),
                    nll_acc + nll, w_acc + w), None

        zeros = jax.tree.map(jnp.zeros_like, trainable)
        scan_xs = (micros,) if drop_rngs is None else (micros, drop_rngs)
        carry0 = (zeros, jnp.zeros((), jnp.float32),
                  jnp.zeros((), jnp.float32))
        if dcn_residual:
            # the residual is params-shaped (sharded leaves carry the
            # DCN-hop error at local-shard granularity) and zeroed per
            # step — no TrainState change, no checkpoint-layout change
            carry0 = carry0 + (jax.tree.map(jnp.zeros_like, trainable),)
        (g_sum, nll_sum, w_sum, *_), _ = jax.lax.scan(
            accum, carry0, scan_xs)

        inv_w = jnp.where(w_sum > 0, 1.0 / w_sum, 0.0)
        grads = jax.tree.map(lambda g: (g * inv_w).astype(g.dtype), g_sum)
        loss = nll_sum * inv_w

        updates, new_opt = optimizer.update(grads, state.opt_state, trainable)
        new_trainable = optax.apply_updates(trainable, updates)

        new_state = TrainState(
            params=state.params if lora_mode else new_trainable,
            lora=new_trainable if lora_mode else None,
            opt_state=new_opt,
            step=state.step + 1,
        )
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "tokens": w_sum,
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return new_state, metrics

    argnums = (0, 1) if (donate and donate_batch) else \
        ((0,) if donate else ())
    if plan is not None:
        # one compile surface: plan-routed steps jit through
        # compile_step_with_plan (which also tags donate_argnums)
        from gke_ray_train_tpu.plan import compile_step_with_plan
        return compile_step_with_plan(plan, mesh, train_step,
                                      donate_argnums=argnums)
    fn = jax.jit(train_step, donate_argnums=argnums)
    try:
        # introspection hook for tests/tooling: jit wrappers do not
        # expose their donate_argnums publicly
        fn.donate_argnums = argnums
    except (AttributeError, TypeError):  # pragma: no cover - frozen type
        pass
    return fn


def make_eval_step(cfg: ModelConfig, *, mesh: Optional[Mesh] = None,
                   lora_cfg: Optional[LoraConfig] = None,
                   pipe_microbatches: Optional[int] = None,
                   batch_shardings: Optional[Dict[str, Any]] = None):
    """(state, batch) -> summed (nll, weight) — callers aggregate across
    batches/hosts then divide (exact eval loss, SURVEY.md §5.5).

    ``batch_shardings``: explicit per-key input shardings for the batch
    (the same :func:`batch_shardings` contract as the train step). With
    them pinned, eval compiles ONCE for the declared layout — numpy
    rows, pre-placed arrays, or arrays committed elsewhere all dispatch
    into that one executable instead of retracing per distinct input
    layout, and on multi-host meshes the batch is batch-axis-sharded by
    construction rather than silently replicated."""
    lora_mode = lora_cfg is not None

    def eval_step(state: TrainState, batch: Batch):
        logits = forward(state.params, batch["inputs"], cfg,
                         positions=batch.get("positions"),
                         segment_ids=batch.get("segment_ids"),
                         mesh=mesh,
                         lora=state.lora if lora_mode else None,
                         lora_scale=lora_cfg.scale if lora_mode else 1.0,
                         pipe_microbatches=pipe_microbatches)
        return token_nll(logits, batch["targets"], batch["weights"])

    if batch_shardings is not None:
        # None = leave the state's shardings to propagate from the args
        return jax.jit(eval_step,
                       in_shardings=(None, dict(batch_shardings)))
    return jax.jit(eval_step)


def batch_shardings(mesh: Mesh, batch_keys=("inputs", "targets", "weights"),
                    *, context_sharded: bool = False) -> Dict[str, Any]:
    seq = "context" if context_sharded else None
    return {k: NamedSharding(mesh, P(BATCH_AXES, seq)) for k in batch_keys}
