"""Tracing/profiling hooks (SURVEY.md §5.1).

The reference has no profiler anywhere (no torch profiler, no NVTX —
§5.1); its only perf observability is loss curves. Hitting the ≥40% MFU
north star needs step-level traces, so this wires ``jax.profiler``
(XProf/TensorBoard format) into the training loop as a first-class,
config-gated subsystem: trace a window of steps mid-run (after compile +
warmup noise) and write to the shared storage mount where TensorBoard
reads it.

Config surface (fine_tune_config.json / pre-train config):
  "PROFILE": true | "gs-mounted/dir"   — enable (default dir under the
                                         run's output dir)
  "PROFILE_START_STEP": 10             — steps to run after (re)start
                                         before tracing begins (skips
                                         compile + warmup, also after a
                                         checkpoint resume)
  "PROFILE_NUM_STEPS": 5               — traced window length
Debug-NaNs smoke switch (§5.2): "DEBUG_NANS": true.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

logger = logging.getLogger(__name__)


class TraceProfiler:
    """Start/stop a jax.profiler trace around a step window.

    Host-side and idempotent; every host traces its own process (device
    traces land per-host, the standard multi-host XProf layout).
    """

    def __init__(self, logdir: str, start_step: int = 10,
                 num_steps: int = 5):
        self.logdir = logdir
        self.start_step = start_step
        self.num_steps = num_steps
        self._first = None           # first global step seen this run
        self._stop_at = None
        self._active = False
        self._done = False

    @property
    def start_offset(self) -> int:
        """Back-compat alias for start_step (read/write)."""
        return self.start_step

    @start_offset.setter
    def start_offset(self, v: int) -> None:
        self.start_step = v

    @property
    def active(self) -> bool:
        """Whether a trace is in flight RIGHT NOW. jax.profiler is
        process-global, so the anomaly-capture scheduler
        (obs/capture.py) checks this before arming its own one-shot
        trace — two concurrent start_trace calls would fail both."""
        return self._active

    @property
    def stop_step(self) -> int:
        """Exclusive end of the trace window relative to this run's
        first step: traced steps are [start_step, stop_step)."""
        return self.start_step + self.num_steps

    def step(self, global_step: int) -> None:
        """Call once per train step, AFTER the step ran (post-increment
        index). The window is relative to the first step this process
        runs — a checkpoint resume at step 1000 still skips its own
        compile/warmup steps before tracing."""
        if self._done:
            return
        if self._first is None:
            self._first = global_step
        # start_trace after `start_offset` steps have completed, so the
        # first *traced* step is first + start_offset
        if not self._active and \
                global_step >= self._first + self.start_step - 1:
            try:
                jax.profiler.start_trace(self.logdir)
                self._active = True
                self._stop_at = global_step + self.num_steps
                logger.info("profiler: tracing steps %d-%d to %s",
                            global_step + 1, self._stop_at, self.logdir)
            except Exception as e:  # noqa: BLE001 - profiling never fatal
                logger.warning("profiler start failed: %s", e)
                self._done = True
        elif self._active and global_step >= self._stop_at:
            self.close()

    def close(self) -> None:
        if self._active:
            try:
                jax.profiler.stop_trace()
                logger.info("profiler: trace written to %s", self.logdir)
            except Exception as e:  # noqa: BLE001
                logger.warning("profiler stop failed: %s", e)
            self._active = False
        self._done = True


def profiler_from_config(config: dict, default_dir: str) -> Optional[
        TraceProfiler]:
    """Build a TraceProfiler from reference-style flat config keys, or
    None when profiling is off."""
    prof = config.get("PROFILE", False)
    if not prof:
        return None
    logdir = prof if isinstance(prof, str) else default_dir
    return TraceProfiler(
        logdir,
        start_step=int(config.get("PROFILE_START_STEP", 10)),
        num_steps=int(config.get("PROFILE_NUM_STEPS", 5)))


def apply_debug_flags(config: dict) -> None:
    """§5.2 smoke-mode checks: jax_debug_nans turns silent NaN training
    into an immediate, located failure."""
    if bool(config.get("DEBUG_NANS", False)):
        jax.config.update("jax_debug_nans", True)
        logger.info("jax_debug_nans enabled")
