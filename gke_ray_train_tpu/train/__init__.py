from gke_ray_train_tpu.train.optim import (  # noqa: F401
    warmup_cosine_schedule, make_optimizer, default_weight_decay_mask)
from gke_ray_train_tpu.train.step import (  # noqa: F401
    TrainState, make_train_state, make_train_step, make_eval_step,
    token_nll, batch_shardings)
from gke_ray_train_tpu.train.lora import (  # noqa: F401
    LoraConfig, init_lora, lora_specs, merge_lora)
from gke_ray_train_tpu.train.metrics import (  # noqa: F401
    ThroughputMeter, train_flops_per_token, peak_flops_per_device)
from gke_ray_train_tpu.train.evaluate import (  # noqa: F401
    sharded_eval_loss, sharded_eval_sums)
