"""Throughput & MFU accounting — first-class, not derived offline.

The reference logs only loss/epoch/LR/step (ray-jobs/pytorch_llm_ray.py:
287-292) and publishes no perf numbers (BASELINE.md); tokens/sec/chip and
MFU are this framework's north-star metrics (BASELINE.json) so they are
computed in the loop from the model's exact FLOP count.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Optional

import jax


@contextlib.contextmanager
def paused(meter: Optional["ThroughputMeter"]):
    """Book the enclosed block as stall time (no-op when meter is None);
    exception-safe — the meter can never be left permanently paused."""
    if meter is None:
        yield
        return
    meter.pause()
    try:
        yield
    finally:
        meter.resume()

from gke_ray_train_tpu.models.config import ModelConfig

# ---------------------------------------------------------------------------
# goodput ledger — ONE per-attempt decomposition of wall-clock
# ---------------------------------------------------------------------------

# the terms of the per-attempt goodput ledger (ISSUE 8). These existed
# piecemeal — compile_s / restart_to_first_step_s in the loop timings,
# data_stall_frac in the meter, recompile/restore splits in
# BENCH_MODE=recovery, ckpt_save_s on Preempted — and are unified here:
# every attempt's wall-clock decomposes into exactly these buckets, and
# tests assert they reconcile (sum == attempt wall within tolerance).
# ckpt_async_s is the RESIDUAL blocking time of an async-commit save
# (device→host snapshot + committer enqueue; the serialize-to-storage
# tail runs in the background and never appears here) and
# peer_restore_s is a restore served from a living peer slice's hot
# state instead of storage — the two terms ISSUE 18 drives toward
# zero-cost checkpointing/recovery. A sync save books the classic
# eval_ckpt_stall_s; a storage restore books restore_s.
LEDGER_TERMS = ("compile_s", "restore_s", "fast_forward_s",
                "data_stall_s", "eval_ckpt_stall_s", "ckpt_async_s",
                "peer_restore_s", "step_s", "lost_s")


@dataclasses.dataclass
class GoodputLedger:
    """Accumulates one training attempt's wall-clock decomposition.

    The loop (``train/loop.py``) feeds it: restore and first-step
    compile are timed directly, fast-forward is the remainder of the
    restart window, input-pipeline waits arrive via :meth:`data_wait`,
    and eval/checkpoint stalls via :meth:`pause`/:meth:`resume` (the
    same protocol as :class:`ThroughputMeter`, so ``paused(ledger)``
    works). :meth:`close` books everything not otherwise attributed as
    ``step_s`` — the goodput numerator: wall-clock actually converted
    into training steps. ``lost_s`` is NOT set here: the trainer
    computes it as the attempt-wall residual (worker setup/teardown,
    and on crashed attempts the whole unledgered span), so the terms
    sum to the attempt wall-clock by construction — the reconciliation
    tests pin exactly that identity.
    """
    compile_s: float = 0.0
    restore_s: float = 0.0
    fast_forward_s: float = 0.0
    data_stall_s: float = 0.0
    eval_ckpt_stall_s: float = 0.0
    ckpt_async_s: float = 0.0
    peer_restore_s: float = 0.0
    step_s: float = 0.0
    lost_s: float = 0.0
    _pause_t0: Optional[float] = None
    _closed: bool = False

    def note(self, term: str, seconds: Optional[float]) -> None:
        if seconds is None or term not in LEDGER_TERMS:
            return
        setattr(self, term, getattr(self, term) + max(float(seconds), 0.0))

    def data_wait(self, seconds: float) -> None:
        self.data_stall_s += max(float(seconds), 0.0)

    def pause(self) -> None:
        if self._pause_t0 is None:
            self._pause_t0 = time.perf_counter()

    def resume(self) -> None:
        if self._pause_t0 is not None:
            self.eval_ckpt_stall_s += time.perf_counter() - self._pause_t0
            self._pause_t0 = None

    def close(self, loop_wall_s: float) -> None:
        """Attribute the unaccounted remainder of the loop's wall-clock
        to ``step_s``. Idempotent — the preemption exit closes early
        (the ledger must ride the Preempted exception) and the loop's
        finally closes again on every path."""
        if self._closed:
            return
        self.resume()
        covered = (self.compile_s + self.restore_s + self.fast_forward_s
                   + self.data_stall_s + self.eval_ckpt_stall_s
                   + self.ckpt_async_s + self.peer_restore_s)
        self.step_s = max(float(loop_wall_s) - covered, 0.0)
        self._closed = True

    def as_dict(self) -> dict:
        return {t: float(getattr(self, t)) for t in LEDGER_TERMS}


def finish_ledger(led: Optional[dict], wall_s: float) -> dict:
    """One attempt's final ledger: the loop's terms (or nothing, when
    the attempt died before/outside the loop) with ``lost_s`` set to
    the attempt-wall residual and ``wall_s`` recorded, so
    ``sum(LEDGER_TERMS) == wall_s`` holds exactly."""
    out = {t: float((led or {}).get(t, 0.0)) for t in LEDGER_TERMS}
    covered = sum(v for k, v in out.items() if k != "lost_s")
    out["lost_s"] = max(float(wall_s) - covered, 0.0)
    out["wall_s"] = float(wall_s)
    return out


def sum_ledgers(ledgers) -> dict:
    """Element-wise sum of per-attempt ledgers plus the headline
    ``goodput_frac`` = step time / total wall — the number a
    production fleet optimizes (ROADMAP #4)."""
    keys = LEDGER_TERMS + ("wall_s",)
    total = {k: float(sum(led.get(k, 0.0) for led in ledgers))
             for k in keys}
    total["goodput_frac"] = (total["step_s"] / total["wall_s"]
                             if total["wall_s"] > 0 else 0.0)
    return total


def ledger_metrics(led: dict) -> dict:
    """One ledger dict -> the ``goodput_*`` metric names the obs
    registry and the TB writer publish (obs/metrics.py METRIC_NAMES
    pins these — ONE mapping, so the dashboard scalars, the Prometheus
    export and the report all read the identical decomposition)."""
    out = {f"goodput_{t}": float(led.get(t, 0.0)) for t in LEDGER_TERMS}
    if "wall_s" in led:
        out["goodput_wall_s"] = float(led["wall_s"])
        if led["wall_s"] > 0:
            out["goodput_frac"] = float(led.get("step_s", 0.0)) \
                / float(led["wall_s"])
    return out

# Peak dense bf16 TFLOP/s per chip, by device_kind substring.
PEAK_FLOPS = {
    "v5 lite": 197e12,   # v5e (jax device_kind "TPU v5 lite")
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,        # v5p reports "TPU v5"
    "v4": 275e12,
    "v6 lite": 918e12,   # trillium
    "v6e": 918e12,
    "cpu": 1e12,         # nominal, keeps MFU finite in smoke tests
}


def peak_flops_per_device(default: float = 197e12) -> float:
    kind = jax.devices()[0].device_kind.lower()
    for k, v in sorted(PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if k in kind:
            return v
    # an unrecognized device_kind (a future "TPU v7 lite", a GPU) would
    # silently misreport MFU against the default roofline — say so once
    # (VERDICT r4 weak #7)
    import logging

    from gke_ray_train_tpu.logging_utils import warn_once
    warn_once(logging.getLogger(__name__), ("peak_flops", kind),
              "device_kind %r matches no PEAK_FLOPS entry; MFU uses the "
              "default %.0f TFLOP/s roofline and may be wrong — extend "
              "PEAK_FLOPS in %s", kind, default / 1e12, __name__)
    return default


def train_flops_per_token(cfg: ModelConfig, seq_len: int, *,
                          trainable: str = "full") -> float:
    """Dense matmuls: fwd 2N + bwd 4N (= 2N weight-grad + 2N act-grad)
    plus the attention term 12 * n_layers * d_attn * seq (QK^T and AV,
    fwd+bwd), halved for causal masking.

    trainable="lora": the frozen base skips its weight-grad matmuls
    (4N instead of 6N; adapter FLOPs are negligible at r<<d) — using
    the full-train count would overstate QLoRA MFU by ~1.5x.

    MoE models bill ACTIVE params (router + top-k experts per token,
    ModelConfig.active_param_count) — the total count would overstate
    the FLOPs a routed token actually performs by ~E/k."""
    n = cfg.active_param_count()
    dense = (4.0 if trainable == "lora" else 6.0) * n
    d_attn = cfg.n_heads * cfg.resolved_head_dim
    attn = 12 * cfg.n_layers * d_attn * seq_len * 0.5
    return dense + attn


@dataclasses.dataclass
class ThroughputMeter:
    """Wall-clock tokens/sec/chip + MFU over a sliding window of steps.

    ``trainable`` must be "lora" for (Q)LoRA runs: the frozen base skips
    its weight-grad matmuls, so billing the full 6N count would overstate
    the flagship QLoRA MFU by ~1.5x (VERDICT r3 weak #3).

    Stall exclusion (VERDICT r4 weak #8): the loop calls
    :meth:`pause`/:meth:`resume` around eval and checkpoint saves, so the
    headline ``mfu``/``tokens_per_sec*`` measure the STEADY-STATE train
    step; the stall-inclusive numbers stay in ``*_incl_stalls`` for
    honesty (cumulative job throughput is what a cluster bill sees)."""
    cfg: ModelConfig
    seq_len: int
    n_devices: int
    peak_flops: Optional[float] = None
    trainable: str = "full"
    _t0: float = dataclasses.field(default_factory=time.perf_counter)
    _tokens: float = 0.0
    _steps: int = 0
    _paused_total: float = 0.0
    _pause_t0: Optional[float] = None
    _data_wait: float = 0.0

    def __post_init__(self):
        if self.peak_flops is None:
            self.peak_flops = peak_flops_per_device()

    def update(self, tokens_this_step: float) -> None:
        self._tokens += float(tokens_this_step)
        self._steps += 1

    def data_wait(self, seconds: float) -> None:
        """Book host seconds the loop spent blocked on the input pipeline
        (queue wait under prefetch; iterate+place time synchronously).
        Feeds ``data_stall_frac`` — the fraction of the training window
        the accelerator idled for data, i.e. what prefetch should drive
        to ~0 once the host is no longer the bottleneck."""
        self._data_wait += max(float(seconds), 0.0)

    def pause(self) -> None:
        """Mark the start of a non-training stall (eval, ckpt save)."""
        if self._pause_t0 is None:
            self._pause_t0 = time.perf_counter()

    def resume(self) -> None:
        if self._pause_t0 is not None:
            self._paused_total += time.perf_counter() - self._pause_t0
            self._pause_t0 = None

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._tokens = 0.0
        self._steps = 0
        self._paused_total = 0.0
        self._pause_t0 = None
        self._data_wait = 0.0

    def snapshot(self) -> dict:
        now = time.perf_counter()
        dt_wall = max(now - self._t0, 1e-9)
        paused = self._paused_total + (
            now - self._pause_t0 if self._pause_t0 is not None else 0.0)
        dt = max(dt_wall - paused, 1e-9)

        def rates(denom):
            tps = self._tokens / denom
            flops = tps * train_flops_per_token(self.cfg, self.seq_len,
                                                trainable=self.trainable)
            return tps, flops / (self.peak_flops * max(self.n_devices, 1))

        tps, mfu = rates(dt)
        tps_wall, mfu_wall = rates(dt_wall)
        return {
            "tokens_per_sec": tps,
            "tokens_per_sec_per_chip": tps / max(self.n_devices, 1),
            "mfu": mfu,
            "steps_per_sec": self._steps / dt,
            # input-pipeline health: fraction of the training window the
            # loop sat blocked waiting for the next (placed) batch
            "data_stall_frac": min(self._data_wait / dt, 1.0),
            # cumulative (stall-inclusive) job view
            "tokens_per_sec_per_chip_incl_stalls":
                tps_wall / max(self.n_devices, 1),
            "mfu_incl_stalls": mfu_wall,
        }
