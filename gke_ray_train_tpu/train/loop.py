"""Host-side training loop.

The behavioral spec is the reference's visible loop
(ray-jobs/pytorch_llm_ray.py:263-310): per-epoch batch iteration with
epoch reshuffle, rank-0 logging every ``log_every`` batches (loss + LR,
:283-284), end-of-epoch checkpoint + metrics report through the trainer
context (:296-310). Differences by design:

- metrics include tokens/sec/chip and MFU (ThroughputMeter) — the
  BASELINE.json north-star metrics the reference never logs.
- checkpointing is collective (orbax) with keep-best retention and the
  resume-on-start the reference lacks.
- every host runs the loop in lockstep (SPMD); `is_host0` only gates
  *printing*, never collectives (the reference's filesystem-flag barrier
  antipattern, SURVEY.md §5.2, does not exist here).
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Callable, Iterable, Optional

import jax
import numpy as np

from gke_ray_train_tpu.analysis.guards import RuntimeGuards, allow_transfers
from gke_ray_train_tpu.data.prefetch import make_batch_source
from gke_ray_train_tpu.obs import runtime as obs_runtime
from gke_ray_train_tpu.train import preempt
from gke_ray_train_tpu.train.metrics import (
    GoodputLedger, ThroughputMeter, paused)
from gke_ray_train_tpu.train.step import TrainState

logger = logging.getLogger(__name__)


def _fetch_metrics(m: dict) -> dict:
    """ONE batched host sync for the whole metrics tree.

    The pre-shardlint form — ``float(jax.device_get(v))`` per key —
    paid one device round-trip per metric every log step (TPU001);
    ``jax.device_get`` on the dict transfers every leaf in a single
    fetch, inside the transfer guard's explicit allow-list."""
    with allow_transfers():
        return {k: float(v) for k, v in jax.device_get(m).items()}


def run_training(state: TrainState,
                 train_step: Callable,
                 epoch_batches: Callable[[int], Iterable],
                 *,
                 epochs: int = 1,
                 steps_per_epoch: Optional[int] = None,
                 log_every: int = 20,
                 meter: Optional[ThroughputMeter] = None,
                 ckpt_manager=None,
                 report_fn: Optional[Callable] = None,
                 eval_fn: Optional[Callable] = None,
                 eval_every: Optional[int] = None,
                 eval_at_epoch_end: bool = False,
                 ckpt_every: Optional[int] = None,
                 place_batch: Optional[Callable] = None,
                 prefetch: int = 0,
                 ckpt_view: Optional[tuple] = None,
                 profiler=None,
                 tb_writer=None,
                 heartbeat_fn: Optional[Callable] = None,
                 fault_injector=None,
                 guards: Optional[RuntimeGuards] = None,
                 is_host0: bool = True) -> tuple:
    """Returns (final_state, last_metrics).

    last_metrics carries two compile-level timings alongside the step
    metrics: ``compile_s`` (wall time of the first step call incl. its
    trace+compile — near zero under a warm persistent compile cache or
    a deserialized AOT executable, perf/cache.py) and
    ``restart_to_first_step_s`` (run_training entry → first completed
    step: restore + fast-forward + compile; the recovery-path metric).

    epoch_batches(epoch) → iterable of host-local numpy batch dicts.
    place_batch(batch) → device arrays (sharded form-up); default asis.
    prefetch: queue depth of the asynchronous input pipeline
    (data/prefetch.py) — a background thread runs the epoch iterator AND
    ``place_batch`` ahead of the step, overlapping tokenize/pack and the
    host→device transfer with device compute. 0 = synchronous (identical
    batch stream either way; resume fast-forward never transfers skipped
    batches on either path). When a meter is attached, the fraction of
    the train window spent blocked on the pipeline is surfaced as
    ``data_stall_frac`` in the periodic log line and TB scalars.
    report_fn(metrics_dict) → trainer-context report (Ray or local).
    ckpt_view: optional (save_view, load_view) pair mapping the state to
    the subset the checkpoint persists — LoRA mode saves only adapters +
    optimizer state (the frozen/quantized base is rebuilt from the
    pretrained weights on resume, and quantized uint4 codes are not
    serializable anyway).
    heartbeat_fn(step, done=False) → per-step liveness report
    (rayint/supervisor.py; entry scripts wire ctx.heartbeat). Called
    after every completed step — supervision arms at the first beat,
    so first-step compile and resume fast-forward are not stalls; the
    done=True call at loop exit exempts this rank from stall detection
    (post-loop export work is unsupervised by design). Size
    HEARTBEAT_TIMEOUT_S above the longest eval/checkpoint pause: the
    clock only refreshes on step ADVANCE.
    fault_injector: deterministic fault hook fired once per completed
    step (testing/faults.py). None = built from $FAULT_SPEC, which is
    unset in production — the env read is the only overhead.
    guards: runtime enforcement of the shardlint properties
    (analysis/guards.py). None = resolved from env: TRANSFER_GUARD
    wraps the hot loop in jax's device→host transfer guard (the
    batched metrics fetch, eval, and checkpoint saves are the
    explicit allow-list); DIVERGENCE_GUARD allgathers a fingerprint
    of each host's lowered step-fn HLO before the first step and
    fails fast — with the per-host diff — when hosts traced
    different programs (otherwise that bug presents as a collective
    deadlock the watchdog can only name).

    Preemption (train/preempt.py): when the SIGTERM flag is up at a
    step boundary the loop force-saves a checkpoint, waits until it is
    durable, and raises Preempted — the trainer retries WITHOUT
    consuming the max_failures budget.
    """
    # time-to-first-step accounting (BENCH_MODE=compile / recovery):
    # the clock starts BEFORE the checkpoint restore below — at 8B scale
    # restore is the other dominant term besides compile, and
    # restart_to_first_step_s must cover restore + fast-forward +
    # compile (compile_s isolates the first step call, ≈0 when the step
    # is a deserialized AOT executable; perf/cache.py)
    t_loop0 = time.perf_counter()
    loop_timing: dict = {}
    # per-attempt goodput ledger (train/metrics.py): every second of
    # this call decomposes into LEDGER_TERMS; the trainer reads it off
    # the context (or the Preempted exception) into Result.goodput
    ledger = GoodputLedger()
    # unified telemetry (obs/): the attempt-scoped session the trainer
    # (or a test/bench) configured; None = one is-None check per step.
    # Per-step feed = host floats the loop already measures (iteration
    # wall minus data wait minus eval/ckpt pauses) — no device sync,
    # no event emission off the log cadence, so the A/B stream with
    # obs on is bitwise-identical to obs off (tests pin that).
    obs = obs_runtime.active()
    if obs is not None and obs.capture is not None and profiler is not None:
        # jax.profiler is process-global: the anomaly capture must not
        # collide with the config-gated trace window
        obs.capture._conflict = lambda: bool(
            getattr(profiler, "active", False))
    _obs_prev = [t_loop0, 0.0]   # [last note time, last eval/ckpt total]
    # step-window span accumulator (obs/trace.py): [step_s,
    # data_stall_s, steps] since the last flush. Spans aggregate at the
    # log cadence (plus one tail flush at loop exit), never per step —
    # the same hot-path contract the event stream keeps. data_stall_s
    # accumulates the identical wait floats the ledger books, so the
    # span-derived stall total reconciles with the ledger exactly.
    _win = [0.0, 0.0, 0]

    def _flush_window(step):
        if obs is not None and _win[2]:
            obs.span_add("step_window", _win[0] + _win[1], step=step,
                         steps=_win[2], data_stall_s=_win[1])
        _win[:] = [0.0, 0.0, 0]
    if guards is None:
        guards = RuntimeGuards.from_config()
    # KERNELCHECK=1 (analysis/kernelcheck.py): before anything trains,
    # run every registered kernel's cheapest case against its oracle,
    # gated by the pinned tolerance ledger. Sits HERE — after
    # distributed_init, before restore — so a kernel/oracle
    # disagreement fails the attempt loudly instead of corrupting a
    # run; KernelCheckError is an AssertionError, which the trainer
    # classifies as non-retryable.
    if os.environ.get("KERNELCHECK", "0").lower() not in (
            "", "0", "false", "no", "off"):
        from gke_ray_train_tpu.analysis.kernelcheck import quick_verify
        quick_verify(log=logger.info)
    save_view = (ckpt_view[0] if ckpt_view else (lambda st: st))
    load_view = (ckpt_view[1] if ckpt_view else (lambda st, v: v))
    if fault_injector is None:
        from gke_ray_train_tpu.testing.faults import FaultInjector
        fault_injector = FaultInjector.from_env(ckpt_manager=ckpt_manager)
    elif ckpt_manager is not None:
        fault_injector.bind_ckpt(ckpt_manager)
    resumed_step = None
    if ckpt_manager is not None:
        t_restore0 = time.perf_counter()
        try:
            view, resumed = ckpt_manager.restore_if_available(
                save_view(state))
            if resumed is not None:
                state = load_view(state, view)
        except Exception as e:  # noqa: BLE001 - layout-mismatch fallback
            if ckpt_view is None:
                raise
            # a checkpoint written before the view existed stores the
            # FULL state (ADVICE r1: pre-view LoRA checkpoints must stay
            # restorable) — retry against the full-state template
            logger.warning(
                "ckpt_view restore failed (%s: %s); retrying as a "
                "full-state checkpoint (pre-view layout)",
                type(e).__name__, e)
            full, resumed = ckpt_manager.restore_if_available(state)
            if resumed is not None:
                state = full
        restore_dt = time.perf_counter() - t_restore0
        # a resume served from the peer slice's hot state (ckpt/peer.py)
        # books peer_restore_s, not restore_s — the ledger says which
        # recovery path paid for the attempt's start
        peer_served = getattr(ckpt_manager, "last_restore_source",
                              None) == "peer"
        ledger.note("peer_restore_s" if peer_served else "restore_s",
                    restore_dt)
        if resumed is not None and is_host0:
            logger.info("resumed at step %d", resumed)
        resumed_step = resumed
        if obs is not None:
            # span duration is the EXACT float the ledger booked — the
            # critical-path reconciliation (obs/critical.py) depends on
            # the two streams agreeing bitwise, not approximately
            if peer_served:
                obs.span_add("peer_restore", restore_dt, step=resumed,
                             resumed_step=resumed)
                _pmeta = getattr(ckpt_manager, "last_peer_restore",
                                 None) or {}
                obs.emit("peer_restore", step=resumed,
                         restore_s=restore_dt,
                         bytes=_pmeta.get("bytes"),
                         from_slice=_pmeta.get("from_slice"))
            else:
                obs.span_add("restore", restore_dt, step=resumed,
                             resumed_step=resumed)
        if obs is not None and resumed is not None:
            obs.emit("resume", step=resumed, resumed_step=resumed)
        # attempt metadata for Result.attempt_log (rayint/trainer.py);
        # context is stdlib-only, so this costs nothing standalone
        from gke_ray_train_tpu.rayint.context import get_context
        get_context().note_resume(resumed)

    last_metrics = {}
    global_step = int(jax.device_get(state.step))

    n_procs = max(jax.process_count(), 1)
    # multi-host flag agreement runs only every K-th boundary: blocking
    # on a cross-host collective EVERY step would serialize the async
    # dispatch overlap the input pipeline exists for. K is uniform
    # across hosts, so ranks still agree on the exit step; worst-case
    # exit delay is K steps against the ~25s grace window.
    preempt_sync_every = max(
        1, int(os.environ.get("PREEMPT_SYNC_EVERY", "4")))
    _boundary = [0]

    def _preempt_requested() -> bool:
        """Collective preemption verdict. SIGTERM lands on every host of
        an evicted slice, but async dispatch skews the hosts' Python
        loops by a step or two — a host exiting at ITS flag-observation
        step would enter a forced save its peers never join and wedge
        the slice inside the grace window. The allgather (a tiny host
        collective, multi-host only) makes every rank exit at the SAME
        boundary: any host's flag preempts all."""
        local = preempt.requested()
        if n_procs <= 1:
            return local
        _boundary[0] += 1
        if _boundary[0] % preempt_sync_every:
            # off-cycle boundaries never exit, even with the local flag
            # up — exits happen only where every rank runs the collective
            return False
        from jax.experimental import multihost_utils
        with allow_transfers():
            # the flag allgather is a sanctioned host collective —
            # its fetch must pass the transfer guard
            flags = multihost_utils.process_allgather(
                np.asarray(1 if local else 0, np.int32))
        return bool(np.max(flags))

    def _preempt_exit(state, m, step):
        """Grace-window exit: force-save, wait until durable, raise the
        distinct 'preempted' status (train/preempt.py)."""
        # flush TB FIRST: the grace window may end in SIGKILL mid-save,
        # and a killed attempt's last scalars must already be on disk
        # (the close() in the finally never runs under SIGKILL)
        if tb_writer is not None:
            tb_writer.flush()
        save_s = None
        if ckpt_manager is not None:
            t0 = time.perf_counter()
            with allow_transfers():
                if m is not None and ckpt_manager.latest_step() != step:
                    ckpt_manager.save(step, save_view(state),
                                      metrics=_fetch_metrics(m),
                                      force=True)
                ckpt_manager.wait()
            save_s = time.perf_counter() - t0
            ledger.note("eval_ckpt_stall_s", save_s)
            kept = ckpt_manager.latest_step()
            if kept != step:
                # best-by-score retention can delete a forced save whose
                # metric is not among the best — the resume then loses
                # every step since the surviving checkpoint. Training
                # managers should use recency retention (the entry
                # scripts do); shout, because this is silent data loss.
                logger.error(
                    "preemption save at step %d was DROPPED by "
                    "retention (surviving latest: %s) — the retry "
                    "resumes from there; use score_attribute=None on "
                    "resume managers", step, kept)
            elif is_host0:
                logger.warning(
                    "preemption: checkpoint at step %d durable in %.2fs "
                    "(grace remaining: %s s)", step, save_s,
                    preempt.remaining_grace_s())
        # close the ledger NOW so it rides the exception (the finally
        # below re-closes idempotently) — a preempted attempt's ledger
        # must survive process boundaries on the Ray path, and a pool-
        # change notice carries the surviving device count for the
        # trainer's elastic re-form
        ledger.close(time.perf_counter() - t_loop0)
        if obs is not None:
            if save_s is not None:
                obs.span_add("preempt_save", save_s, step=step)
            obs.emit("preempt_exit", step=step, save_s=save_s,
                     grace_remaining_s=preempt.remaining_grace_s(),
                     pool=preempt.pool_target())
        raise preempt.Preempted(step=step, resumed_step=resumed_step,
                                save_s=save_s, grace_s=preempt.grace_s(),
                                pool=preempt.pool_target(),
                                ledger=ledger.as_dict())
    # resume fast-forward (HF Trainer resume_from_checkpoint semantics):
    # batches the restored step counter already consumed are SKIPPED, not
    # retrained — the epoch iterators are seeded by epoch index, so
    # replaying them positions the data stream exactly where the
    # checkpoint left off; a fully-trained checkpoint yields no new steps
    to_skip = global_step
    # NOTE: supervision arms at the FIRST step-completion beat, not
    # here — first-step compile and the resume fast-forward can
    # legitimately dwarf HEARTBEAT_TIMEOUT_S (worker_timeout_s bounds
    # that phase when needed)
    #
    # TRANSFER_GUARD teeth: the steady-state region below runs under
    # jax's device→host transfer guard (thread-local, so the prefetch
    # thread's h2d placement is untouched); every sanctioned fetch
    # site inside wraps itself in allow_transfers()
    _guard_region = contextlib.ExitStack()
    _guard_region.enter_context(guards.transfer_ctx())
    try:
      for epoch in range(epochs):
        if _preempt_requested():
            _preempt_exit(state, None, global_step)
        if meter is not None:
            meter.reset()
        m = None
        trained_this_epoch = 0
        # one iteration shape for both pipelines: the source pulls from
        # the epoch iterator, applies the resume fast-forward skip
        # (skipped batches are consumed but NEVER placed/transferred),
        # and runs place_batch — inline when prefetch=0, on a background
        # thread with a depth-`prefetch` device-resident queue otherwise
        source = make_batch_source(epoch_batches(epoch),
                                   place_fn=place_batch,
                                   depth=prefetch, skip=to_skip)
        try:
          for batch in source:
            if _preempt_requested():
                _preempt_exit(state, m, global_step)
            wait_s = source.consume_wait()
            if trained_this_epoch == 0:
                # fast-forwarding consumed batches costs wall clock
                # (tokenize/pack) that must not deflate the tokens/sec
                # window of the steps actually trained — the reset also
                # drops the first batch's pipeline-warmup wait (the
                # ledger books that span as fast_forward, below)
                if meter is not None:
                    meter.reset()
            else:
                if meter is not None:
                    meter.data_wait(wait_s)
                ledger.data_wait(wait_s)
                # the span-side twin is accumulated HERE, at the same
                # point the ledger books — a crash later in the
                # iteration must leave both streams agreeing, or the
                # report's span/ledger reconciliation (rc=3) fires on
                # a healthy trace over a non-telemetry failure
                if obs is not None:
                    _win[1] += max(float(wait_s), 0.0)
            trained_this_epoch += 1
            if not loop_timing:
                # DIVERGENCE_GUARD (multi-host, opt-in): every host
                # must have lowered the SAME step program before the
                # first collective dispatch wedges on a mismatch
                guards.check_divergence(train_step, state, batch)
                t_step0 = time.perf_counter()
                state, m = train_step(state, batch)
                # block: the first call's wall time must cover the
                # compile it triggered, not just the async dispatch
                jax.block_until_ready(m["loss"])
                now = time.perf_counter()
                loop_timing = {
                    "compile_s": now - t_step0,
                    "restart_to_first_step_s": now - t_loop0,
                }
                # ledger decomposition of the restart window: restore
                # was timed directly; the first step call is compile;
                # on a RESUMED attempt everything else between entry
                # and the first completed step IS the fast-forward
                # (iterator replay, guard checks, pipeline warmup). A
                # fresh start fast-forwarded nothing — its warmup stays
                # in step_s rather than fabricating resume time.
                ledger.note("compile_s", loop_timing["compile_s"])
                if obs is not None:
                    obs.span_add("compile", loop_timing["compile_s"],
                                 step=global_step + 1)
                if resumed_step is not None:
                    ff_dt = (loop_timing["restart_to_first_step_s"]
                             - loop_timing["compile_s"]
                             - ledger.restore_s)
                    ledger.note("fast_forward_s", ff_dt)
                    if obs is not None:
                        # ledger.note clamps negatives to 0; mirror it
                        # so span and ledger stay bitwise-equal
                        obs.span_add("fast_forward", max(ff_dt, 0.0),
                                     step=global_step + 1)
                if obs is not None:
                    from gke_ray_train_tpu.obs import (
                        runtime as _obs_runtime)
                    obs.emit("first_step", step=global_step + 1,
                             compile_s=loop_timing["compile_s"],
                             restart_to_first_step_s=loop_timing[
                                 "restart_to_first_step_s"],
                             restore_s=ledger.restore_s,
                             fast_forward_s=ledger.fast_forward_s,
                             backend=_obs_runtime.current_backend())
            else:
                state, m = train_step(state, batch)
            global_step += 1
            if heartbeat_fn is not None:
                # step-granular liveness: the metric the supervisor
                # watches is "this rank completed another step"
                heartbeat_fn(global_step)
            if profiler is not None:
                profiler.step(global_step)
            if obs is not None:
                # anomaly detection feed (obs/capture.py): host
                # iteration wall minus this batch's data wait and minus
                # every ledgered non-step term booked since the last
                # note (eval/ckpt pauses, and on the first step the
                # restore/compile/fast-forward window) — under async
                # dispatch, backpressure makes what remains track
                # device step time. Pure host floats, no sync.
                _now = time.perf_counter()
                _booked = (ledger.eval_ckpt_stall_s + ledger.compile_s
                           + ledger.restore_s + ledger.fast_forward_s
                           + ledger.ckpt_async_s
                           + ledger.peer_restore_s)
                _iter_v = max(_now - _obs_prev[0] - wait_s
                              - (_booked - _obs_prev[1]), 0.0)
                obs.note_step(global_step, _iter_v, wait_s)
                _obs_prev[0] = _now
                _obs_prev[1] = _booked
                # step-window span feed (the stall half was booked at
                # the ledger's own site above)
                _win[0] += _iter_v
                _win[2] += 1
            if meter is not None:
                # tokens metric is device-resident; fetching it each step
                # would sync — use the (static) batch token count instead
                meter.update(int(np.prod(batch["inputs"].shape)))
            if log_every and global_step % log_every == 0:
                m_host = _fetch_metrics(m)
                last_metrics = {"epoch": epoch, "step": global_step,
                                **loop_timing, **m_host}
                if meter is not None:
                    last_metrics.update(meter.snapshot())
                if tb_writer is not None:
                    tb_writer.log(global_step, last_metrics)
                if obs is not None:
                    # log-cadence telemetry sink: gauges + ONE `step`
                    # event + file export, from the host dict already
                    # fetched above — obs adds no device traffic
                    obs.log_metrics(global_step, last_metrics,
                                    epoch=epoch)
                    _flush_window(global_step)
                if is_host0:
                    logger.info(
                        "epoch %d step %d loss %.4f lr %.3g%s",
                        epoch, global_step, m_host.get("loss", float("nan")),
                        m_host.get("learning_rate", float("nan")),
                        (f" tok/s/chip {last_metrics['tokens_per_sec_per_chip']:.0f}"
                         f" mfu {last_metrics['mfu']:.1%}"
                         f" stall {last_metrics['data_stall_frac']:.1%}"
                         if meter is not None else ""))
            if eval_fn is not None and eval_every and \
                    global_step % eval_every == 0:
                # eval/ckpt stalls are excluded from the meter's
                # steady-state window; the *_incl_stalls metrics keep
                # the cumulative view (VERDICT r4 weak #8). Sync on the
                # async-dispatched train step FIRST so its in-flight
                # compute is booked as training, not stall
                if meter is not None:
                    jax.block_until_ready(m)
                _ev0 = ledger.eval_ckpt_stall_s
                try:
                    with paused(meter), paused(ledger), \
                            allow_transfers():
                        eval_metrics = eval_fn(state)
                finally:
                    # span duration = exactly what the ledger booked
                    # for this pause (the delta, not a re-measurement)
                    # — emitted on the exception path too, because
                    # paused() books on __exit__ regardless and the
                    # two streams must agree for the crashed attempt's
                    # report to reconcile
                    if obs is not None:
                        obs.span_add("eval",
                                     ledger.eval_ckpt_stall_s - _ev0,
                                     step=global_step)
                last_metrics.update(eval_metrics)
                if tb_writer is not None:
                    tb_writer.log(global_step, eval_metrics)
                if obs is not None:
                    obs.emit("eval", step=global_step,
                             metrics=eval_metrics)
                if is_host0:
                    logger.info("eval @ %d: %s", global_step, eval_metrics)
            # SAVE_STRATEGY="steps": mid-epoch checkpoints (HF save_steps
            # semantics, reference fine_tune_config.json:22-23)
            if ckpt_manager is not None and ckpt_every and \
                    global_step % ckpt_every == 0:
                m_host = _fetch_metrics(m)
                if getattr(ckpt_manager, "async_commit", False):
                    # async-commit save (ISSUE 18): the loop blocks only
                    # for the device→host snapshot + enqueue — booked as
                    # ckpt_async_s, the residual blocking cost of async
                    # checkpointing. The storage serialize runs on the
                    # committer thread behind the write-ahead marker and
                    # lands as a ckpt_commit EVENT, never loop time.
                    t_save0 = time.perf_counter()
                    snap_dt = 0.0
                    try:
                        with paused(meter), allow_transfers():
                            ckpt_manager.save(global_step,
                                              save_view(state),
                                              metrics=m_host)
                    finally:
                        snap_dt = time.perf_counter() - t_save0
                        ledger.note("ckpt_async_s", snap_dt)
                        if obs is not None:
                            obs.span_add("ckpt_snapshot", snap_dt,
                                         step=global_step, forced=False)
                    if obs is not None:
                        obs.emit("ckpt_snapshot", step=global_step,
                                 snapshot_s=snap_dt, forced=False)
                else:
                    t_save0 = time.perf_counter()
                    _ck0 = ledger.eval_ckpt_stall_s
                    try:
                        with paused(meter), paused(ledger), \
                                allow_transfers():
                            ckpt_manager.save(global_step,
                                              save_view(state),
                                              metrics=m_host)
                    finally:
                        if obs is not None:
                            obs.span_add("ckpt_save",
                                         ledger.eval_ckpt_stall_s - _ck0,
                                         step=global_step, forced=False)
                    if obs is not None:
                        obs.emit("ckpt_save", step=global_step,
                                 save_s=time.perf_counter() - t_save0,
                                 forced=False)
            if fault_injector is not None:
                # after the step's bookkeeping AND its scheduled save, so
                # kind=ckpt_truncate at step k tears the step-k save
                fault_injector.on_step(global_step)
        finally:
            # normal exhaustion already joined the workers; this reclaims
            # them on the exception path (a failing step must not leak
            # prefetch threads parked on backpressure)
            source.close()
        yielded = source.yielded
        to_skip -= source.skipped

        # end of epoch: checkpoint + report (collective; all hosts enter)
        if m is None:
            if yielded > 0:
                # every batch of this epoch was consumed before the
                # restore point — nothing to retrain, nothing to re-save
                if is_host0:
                    logger.info("epoch %d already completed before "
                                "resume point (step %d); skipping",
                                epoch, global_step)
                continue
            # an iterator that yielded NOTHING is a data/config error on
            # fresh AND resumed runs alike — never mask it as "resumed"
            raise ValueError(
                f"epoch {epoch} produced 0 batches — the dataset is "
                "smaller than one global batch (shrink GLOBAL_BATCH / "
                "PER_DEVICE_TRAIN_BATCH_SIZE or grow the dataset)")
        m_host = _fetch_metrics(m)
        epoch_metrics = {"epoch": epoch, "step": global_step,
                         **loop_timing, **m_host}
        if meter is not None:
            epoch_metrics.update(meter.snapshot())
        if eval_fn is not None and eval_at_epoch_end:
            _ev0 = ledger.eval_ckpt_stall_s
            try:
                with paused(ledger), allow_transfers():
                    epoch_metrics.update(eval_fn(state))
            finally:
                if obs is not None:
                    obs.span_add("eval",
                                 ledger.eval_ckpt_stall_s - _ev0,
                                 step=global_step)
        if tb_writer is not None:
            tb_writer.log(global_step, epoch_metrics)
            tb_writer.flush()
        if obs is not None:
            obs.emit("epoch_end", step=global_step, epoch=epoch)
        last_metrics = epoch_metrics
        if ckpt_manager is not None:
            if getattr(ckpt_manager, "async_commit", False):
                t_save0 = time.perf_counter()
                try:
                    with allow_transfers():
                        ckpt_manager.save(global_step, save_view(state),
                                          metrics=m_host)
                finally:
                    snap_dt = time.perf_counter() - t_save0
                    ledger.note("ckpt_async_s", snap_dt)
                    if obs is not None:
                        obs.span_add("ckpt_snapshot", snap_dt,
                                     step=global_step, forced=False)
            else:
                _ck0 = ledger.eval_ckpt_stall_s
                try:
                    with paused(ledger), allow_transfers():
                        ckpt_manager.save(global_step, save_view(state),
                                          metrics=m_host)
                finally:
                    if obs is not None:
                        obs.span_add("ckpt_save",
                                     ledger.eval_ckpt_stall_s - _ck0,
                                     step=global_step, forced=False)
        if report_fn is not None:
            report_fn(epoch_metrics)
    finally:
        # seal the attempt's goodput ledger on EVERY exit path (normal,
        # Preempted — already closed there, idempotent — and crash) and
        # park it on the context for Result.attempt_log / Result.goodput
        ledger.close(time.perf_counter() - t_loop0)
        from gke_ray_train_tpu.rayint.context import get_context
        get_context().note_goodput(ledger.as_dict())
        if obs is not None:
            # tail step-window span: the steps since the last log
            # boundary must not fall off the trace — critical-path
            # coverage is checked against the ledger
            _flush_window(global_step)
            # ledger terms -> the obs registry, and the registry -> TB
            # (train/tb.py log_registry): the dashboard, the Prometheus
            # textfile and `obs report` all read the SAME decomposition
            from gke_ray_train_tpu.train.metrics import ledger_metrics
            obs.registry.set_many(ledger_metrics(ledger.as_dict()))
            if tb_writer is not None:
                tb_writer.log_registry(global_step, obs.registry)
            obs.export()
        # leave the transfer-guard region before the post-loop export/
        # merge work — only the hot loop is guarded
        _guard_region.close()
        # a failing step must still flush an in-flight trace — the
        # profile matters most in exactly that case
        if profiler is not None:
            profiler.close()
        if tb_writer is not None:
            tb_writer.close()

    if ckpt_manager is not None:
        ckpt_manager.wait()
    if heartbeat_fn is not None:
        # supervised region ends here: post-loop export/merge work can
        # legitimately exceed the heartbeat timeout
        heartbeat_fn(global_step, done=True)
    return state, last_metrics
