"""LoRA as a low-rank param pytree (SURVEY.md row D6).

The reference delegates adapters to peft (``LoraConfig`` targeting all
projection matrices, ray-jobs/fine_tune_llama_ray.py:245-252; merge via
``merge_and_unload`` at :349-353). Here an adapter is a second pytree with
the same block structure as the model params; only it is passed to the
optimizer in LoRA mode, and merging is one einsum per target at save time:
``W += (alpha/r) * A @ B``.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gke_ray_train_tpu.models.config import ModelConfig, PROJ_TARGETS
from gke_ray_train_tpu.models.transformer import Params

# Default targets = every projection matrix, matching the reference config
# LORA_TARGET_MODULES (fine_tune_config.json:33: all q/k/v/o/gate/up/down).
ALL_TARGETS = PROJ_TARGETS


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    r: int = 64
    alpha: int = 16
    targets: Tuple[str, ...] = ALL_TARGETS
    # dropout on the adapter-branch input (reference LORA_DROPOUT,
    # fine_tune_config.json:32). The train step applies it with a
    # per-(step, microbatch) rng; inference/merge ignore it.
    dropout: float = 0.0

    @property
    def scale(self) -> float:
        return self.alpha / self.r

    @staticmethod
    def from_dict(cfg: dict) -> "LoraConfig":
        """From reference-style flat config keys (fine_tune_config.json:30-33)."""
        return LoraConfig(
            r=int(cfg.get("LORA_R", 64)),
            alpha=int(cfg.get("LORA_ALPHA", 16)),
            dropout=float(cfg.get("LORA_DROPOUT", 0.0)),
        )


def _target_shapes(cfg: ModelConfig) -> dict:
    hd = cfg.resolved_head_dim
    return {
        "wq": (cfg.d_model, cfg.n_heads * hd),
        "wk": (cfg.d_model, cfg.n_kv_heads * hd),
        "wv": (cfg.d_model, cfg.n_kv_heads * hd),
        "wo": (cfg.n_heads * hd, cfg.d_model),
        "w_gate": (cfg.d_model, cfg.d_ff),
        "w_up": (cfg.d_model, cfg.d_ff),
        "w_down": (cfg.d_ff, cfg.d_model),
    }


ATTN_TARGETS = ("wq", "wk", "wv", "wo")


def _effective_targets(cfg: ModelConfig, lora_cfg: LoraConfig):
    """MoE models adapt attention only: the routed expert bank has no
    single delta-W an (A, B) pair could target (peft does the same for
    Mixtral by default)."""
    if cfg.n_experts > 0:
        return tuple(t for t in lora_cfg.targets if t in ATTN_TARGETS)
    return lora_cfg.targets


def init_lora(cfg: ModelConfig, lora_cfg: LoraConfig, key: jax.Array) -> Params:
    """A ~ N(0, 1/r) (kaiming-ish), B = 0 — adapters start as identity.

    Adapters (and therefore their Adam moments) are ALWAYS fp32: they
    are the only trained parameters, and bf16 masters silently drop
    updates below ~value/256 (peft's prepare_model_for_kbit_training
    keeps trainables fp32 for the same reason). The forward casts them
    to the compute dtype at use (_proj)."""
    pdt = jnp.dtype(jnp.float32)
    shapes = _target_shapes(cfg)
    R = cfg.n_repeats
    targets = _effective_targets(cfg, lora_cfg)
    keys = iter(jax.random.split(key, len(cfg.block_pattern)
                                 * len(targets) + 1))

    def block():
        out = {}
        for t in targets:
            d_in, d_out = shapes[t]
            out[t] = {
                "a": (jax.random.normal(next(keys), (R, d_in, lora_cfg.r),
                                        jnp.float32)
                      / jnp.sqrt(lora_cfg.r)).astype(pdt),
                "b": jnp.zeros((R, lora_cfg.r, d_out), pdt),
            }
        return out

    return {"blocks": [block() for _ in cfg.block_pattern]}


def lora_specs(cfg: ModelConfig, lora_cfg: LoraConfig) -> Params:
    """Adapters are small: keep the rank dim replicated, shard the long dim
    the same way the base matrix shards (fsdp on d_model-ish inputs,
    model on head/ffn outputs)."""
    in_spec = {"wq": "fsdp", "wk": "fsdp", "wv": "fsdp", "wo": "model",
               "w_gate": "fsdp", "w_up": "fsdp", "w_down": "model"}
    out_spec = {"wq": "model", "wk": "model", "wv": "model", "wo": "fsdp",
                "w_gate": "model", "w_up": "model", "w_down": "fsdp"}

    def block():
        # leading repeat dim follows the base weights onto `pipe`
        # (no-op while the pipe axis is size 1)
        return {t: {"a": P("pipe", in_spec[t], None),
                    "b": P("pipe", None, out_spec[t])}
                for t in _effective_targets(cfg, lora_cfg)}

    return {"blocks": [block() for _ in cfg.block_pattern]}


def merge_lora(params: Params, lora: Params, lora_cfg: LoraConfig, *,
               on_host: bool = False) -> Params:
    """W + (alpha/r) A@B for every adapted matrix — the equivalent of
    peft's merge_and_unload (reference fine_tune_llama_ray.py:349-353),
    but a pure function on pytrees (jit/shard friendly).

    ``on_host``: run the merge on the CPU backend (leaves moved off the
    accelerator first). Dequantizing an 8B NF4 base into a merged fp32
    tree needs ~32 GB — far over one chip's HBM but trivial in host RAM;
    the single-host export path uses this (the multi-host path keeps the
    merge on device, where each host holds only its shard)."""
    # deferred import keeps ops.quant (and its pytree registration) out
    # of LoRA-only runs; the old train↔ops cycle is gone (PROJ_TARGETS
    # now lives in models.config)
    from gke_ray_train_tpu.ops.quant import (
        QTensor, dequantize, is_qtensor, maybe_dequantize)

    import contextlib

    cpu = jax.devices("cpu")[0] if on_host else None
    # jitted helpers (dequantize's NF4 lookup) dispatch to the DEFAULT
    # device no matter where their operands live — without this the
    # "host" merge math would still run (and OOM) on the accelerator
    dev_ctx = (jax.default_device(cpu) if cpu is not None
               else contextlib.nullcontext())

    def pull(x):
        if cpu is None:
            return x
        if is_qtensor(x):
            return QTensor(jax.device_put(x.codes, cpu),
                           jax.device_put(x.scales, cpu), x.kind, x.group)
        return jax.device_put(x, cpu)

    merged = jax.tree.map(lambda x: x, params)  # shallow-ish copy
    with dev_ctx:
        for p_blk, l_blk in zip(merged["blocks"], lora["blocks"]):
            for t, ab in l_blk.items():
                delta = jnp.einsum("lir,lro->lio",
                                   pull(ab["a"]).astype(jnp.float32),
                                   pull(ab["b"]).astype(jnp.float32)) \
                    * lora_cfg.scale
                # QLoRA bases dequantize on merge — peft's
                # merge_and_unload does the same before folding in
                base = maybe_dequantize(pull(p_blk[t]), jnp.float32)
                out_dtype = (jnp.float32 if is_qtensor(p_blk[t])
                             else p_blk[t].dtype)
                p_blk[t] = (base + delta).astype(out_dtype)
            # quantized weights WITHOUT adapters (e.g. q/v-only LoRA)
            # must still come back to full precision — the HF export
            # consumes plain arrays only
            for t, w in p_blk.items():
                if is_qtensor(w):
                    p_blk[t] = dequantize(pull(w), jnp.float32)
    if cpu is not None:
        # non-target leaves (embed/norms/lm_head) follow so the export
        # reads a uniformly host-resident tree
        merged = jax.tree.map(
            lambda x: jax.device_put(x, cpu)
            if not isinstance(x, (int, float)) else x, merged)
    return merged
