"""Sharded exact-eval walk (SURVEY.md §5.5; VERDICT r3 weak #5).

The reference's eval is HF Trainer's (every rank evaluates its
DistributedSampler shard, reference fine_tune_config.json:24-25); the
round-2 TPU port instead had every host walk ALL eval rows — correct
(the weighted mean is unchanged when each example is counted n_hosts
times) but O(in_shards) wasted compute every eval. This module
partitions the rows across input-shard groups (parallel/placement.py)
while keeping the SPMD program in lockstep:

- every shard group walks the SAME number of steps (the global row count
  is padded up to steps * host_batch * in_shards);
- padding rows carry zero weights, so they contribute nothing to the
  token-weighted sums;
- the jitted eval step reduces over the *global* placed batch, so the
  distinct per-shard rows combine into exact global (nll, weight) sums —
  identical eval_loss to the all-rows walk, 1/in_shards the work.
"""

from __future__ import annotations

from typing import Callable, Dict

import numpy as np


def sharded_eval_sums(state, eval_step: Callable,
                      rows: Dict[str, np.ndarray], *,
                      host_batch: int, in_shards: int = 1,
                      in_shard_id: int = 0,
                      place_batch: Callable = None) -> tuple:
    """Walk this shard group's partition of ``rows`` and return the
    global (nll_sum, weight_sum) floats.

    COLLECTIVE under multi-host: every host must call this with the same
    ``rows`` (the partition is computed locally from in_shard_id) and
    the same shapes; ``eval_step`` must reduce over the global batch
    (train.step.make_eval_step does).
    """
    eb = max(host_batch, 1)
    n_rows = len(rows["inputs"])
    stride = eb * in_shards
    steps = max((n_rows + stride - 1) // stride, 1)
    nll = w = 0.0
    for s in range(steps):
        start = s * stride + in_shard_id * eb
        b = {k: v[start:start + eb] for k, v in rows.items()}
        got = len(b["inputs"])
        if got < eb:
            # zero-weight padding keeps the placed global shape constant
            # (one compiled eval step) and every shard in lockstep even
            # when only some shards have tail rows
            b = {k: np.concatenate(
                [v, np.zeros((eb - got,) + v.shape[1:], v.dtype)])
                for k, v in b.items()}
        if place_batch is not None:
            b = place_batch(b)
        n, ww = eval_step(state, b)
        nll += float(n)
        w += float(ww)
    return nll, w


def sharded_eval_loss(state, eval_step: Callable,
                      rows: Dict[str, np.ndarray], *,
                      host_batch: int, in_shards: int = 1,
                      in_shard_id: int = 0,
                      place_batch: Callable = None) -> float:
    nll, w = sharded_eval_sums(
        state, eval_step, rows, host_batch=host_batch,
        in_shards=in_shards, in_shard_id=in_shard_id,
        place_batch=place_batch)
    return nll / max(w, 1.0)
