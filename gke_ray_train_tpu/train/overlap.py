"""Manual communication/compute overlap — the ``OVERLAP=manual`` path.

The GSPMD scan step (``train/step.py``) leaves every FSDP collective
where GSPMD put it: the per-layer weight all-gather lands immediately
before the dot that consumes it, so the step stalls for the full fabric
latency of every gather — ``tests/budgets/tiny_fsdp8.json`` pinned that
as ``overlap_frac 0.0`` with 100% of collective bytes exposed (PR 9).
This module rewrites the grad path as a ``shard_map`` microbatch
pipeline, the way Megatron-LM-style stacks hide their collectives:

- every fsdp-sharded leaf is gathered through an explicit collective
  the *program* places, not GSPMD;
- the layer loop double-buffers the gather: layer *k+1*'s params are
  prefetched (and pinned before this layer's compute with an
  ``optimization_barrier``) while layer *k* computes, so the gathered
  result is consumed only by the NEXT loop iteration — the carried
  shape ``perf/costs.py::overlap_stats`` classifies as hidden, which is
  what moves the budget's ``overlap_frac``/``exposed_collective_bytes``;
- the grad reduction mimics GSPMD's exact accumulation structure (one
  all-reduce over the consecutive {data x fsdp} group, then the local
  fsdp slice), which is what makes the manual path's losses AND grads
  **bitwise-identical** to the GSPMD scan on the CPU mesh — the
  equivalence `tests/test_overlap.py` drills and ``BENCH_MODE=overlap``
  re-asserts per run.

On a **multi-slice hybrid mesh** (``num_slices > 1`` — the data axis
spans slices, PR 5's contract) the reduction is additionally
DCN-aware (``parallel/hierarchical.py``): both ``DCN_SYNC`` arms stage
the accumulation fold at the slice boundary (intra-slice partials
first, the cross-slice combine second — the shared grouping that keeps
flat-vs-hier **bitwise-identical**), and the arm picks the cross-slice
payload: ``flat`` sends the full leaf over DCN (GSPMD's
all-reduce-then-slice traffic shape), ``hier`` reduce-scatters over
the intra-slice axes first so only ``1/ici_size`` of the bytes cross —
the budgeted number ``tests/budgets/tiny_hybrid_2x4_*.json`` pins.
``DCN_COMPRESS=bf16`` casts only the hier DCN hop, with error feedback
carried across the grad-accumulation scan (not bitwise;
tolerance-pinned in ``tests/tolerances/hier_psum.json``).

Scope: data/fsdp meshes, dense blocks, full fine-tuning. The plan
validator refuses ``overlap='manual'`` on structural-axis topologies
(model/context/pipe > 1), and :func:`check_manual_support` refuses
LoRA and MoE configs loudly — those paths would need their own manual
collectives (TP reduces, ring permutes, expert all-to-alls) that this
pipeline does not emit.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
from jax.sharding import Mesh, PartitionSpec as P

from gke_ray_train_tpu.models.config import ModelConfig
from gke_ray_train_tpu.models.transformer import (
    Params, param_specs, pre_unembed, resolve_seq_impl, run_block_stack,
    unembed_head, _unembed, make_attention_mask)
from gke_ray_train_tpu.ops.rope import rope_frequencies, sinusoidal_positions
from gke_ray_train_tpu.ops.smap import shard_map

_DP_AXES = ("data", "fsdp")


class ManualOverlapUnsupported(ValueError):
    """The model/mesh combination has no manual-overlap path."""


def check_manual_support(cfg: ModelConfig, mesh: Optional[Mesh], *,
                         lora: bool = False) -> None:
    if mesh is None:
        raise ManualOverlapUnsupported(
            "overlap='manual' needs a mesh — the whole point is placing "
            "the mesh collectives by hand")
    for axis in ("model", "context", "pipe"):
        if int(mesh.shape.get(axis, 1)) != 1:
            raise ManualOverlapUnsupported(
                f"overlap='manual' supports data/fsdp meshes only "
                f"(mesh has {axis}={mesh.shape[axis]}); use "
                "overlap='xla' there")
    if lora:
        raise ManualOverlapUnsupported(
            "overlap='manual' does not support LoRA (the adapter grads "
            "flow outside the fsdp gather structure); set OVERLAP=off "
            "or =xla for adapter runs")
    if cfg.n_experts > 0:
        raise ManualOverlapUnsupported(
            "overlap='manual' does not support MoE blocks (expert "
            "dispatch needs its own manual all-to-alls); set "
            "OVERLAP=off or =xla")


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _pin(args):
    """``optimization_barrier`` with a (trivial) VJP — jax 0.4.x defines
    no AD rule for the primitive. Forward pins the schedule (the
    prefetched gather is issued before the compute that the barrier
    releases); the cotangent passes through untouched."""
    return jax.lax.optimization_barrier(args)


def _pin_fwd(args):
    return _pin(args), None


def _pin_bwd(_, ct):
    return (ct,)


_pin.defvjp(_pin_fwd, _pin_bwd)


def _leaf_fsdp_dims(spec, mesh: Mesh) -> Tuple[int, ...]:
    """Dims of a leaf sharded over a >1 mesh axis. Manual overlap runs
    on data/fsdp meshes, so any such axis is ``fsdp``."""
    out = []
    for dim, entry in enumerate(spec):
        names = (entry if isinstance(entry, tuple)
                 else ((entry,) if entry else ()))
        for ax in names:
            if ax and int(mesh.shape.get(ax, 1)) > 1:
                out.append(dim)
    return tuple(out)


def _fsdp_gather(x, dim: int, shard_reduce=None):
    """All-gather one leaf over ``fsdp`` along ``dim`` — with a backward
    that reproduces GSPMD's accumulation structure EXACTLY. Single
    slice (``shard_reduce=None``): one all-reduce over the consecutive
    {data x fsdp} device group (the ``[1,8]<=[8]`` form the GSPMD grad
    path emits), then the local fsdp shard sliced out — the default AD
    transpose (``psum_scatter`` over fsdp + a second psum over data)
    sums the same partials in a different grouping, which costs the
    last ulp and the bitwise off/manual loss equivalence with it.
    Multi-slice: the slice-staged ``DCN_SYNC`` arm the caller passes as
    ``shard_reduce(ct, dim) -> local shard``
    (``parallel/hierarchical.py``)."""
    shard = x.shape[dim]

    @jax.custom_vjp
    def gather(x):
        return jax.lax.all_gather(x, "fsdp", axis=dim, tiled=True)

    def fwd(x):
        return gather(x), None

    def bwd(_, ct):
        if shard_reduce is not None:
            return (shard_reduce(ct, dim),)
        full = jax.lax.psum(ct, _DP_AXES)
        idx = jax.lax.axis_index("fsdp") * shard
        return (jax.lax.dynamic_slice_in_dim(full, idx, shard, axis=dim),)

    gather.defvjp(fwd, bwd)
    return gather(x)


def _gather_full(tree, spec_tree, mesh: Mesh, shard_reduce=None):
    """Gather every sharded dim of every leaf (the non-block params:
    embed / lm_head / final norm)."""
    def one(x, spec):
        for dim in _leaf_fsdp_dims(spec, mesh):
            x = _fsdp_gather(x, dim, shard_reduce)
        return x
    return jtu.tree_map(one, tree, spec_tree,
                        is_leaf=lambda s: isinstance(s, P))


def _gather_layer(blocks, block_specs, mesh: Mesh, i, shard_reduce=None):
    """Gather ONE layer of the stacked block leaves: dynamic-slice the
    repeat dim at (traced) index ``i``, then gather the fsdp dims. The
    leading stacked dim is the ``pipe`` axis (size 1 on these meshes)
    and is never gathered."""
    def one(x, spec):
        sl = jax.lax.dynamic_slice_in_dim(x, i, 1, axis=0)
        for dim in _leaf_fsdp_dims(spec, mesh):
            if dim == 0:
                continue
            sl = _fsdp_gather(sl, dim, shard_reduce)
        return sl
    return jtu.tree_map(one, blocks, block_specs,
                        is_leaf=lambda s: isinstance(s, P))


# ---------------------------------------------------------------------------
# the pipelined local step (runs per device inside shard_map)
# ---------------------------------------------------------------------------

def _pipelined_hidden(full_nonblock: Params, blocks_local, cfg: ModelConfig,
                      mesh: Mesh, tokens, positions, segment_ids,
                      fused_ops: bool, shard_reduce=None):
    """tokens -> final hidden state, with the per-layer double-buffered
    fsdp gather. Per-layer math is :func:`run_block_stack` — the same
    function ``forward``'s scan body calls, so the two paths cannot
    fork. ``mesh=None`` inside: each device computes the dense program
    on its local batch rows with the gathered full weights — exactly
    the per-device program GSPMD compiles for these meshes, which is
    why the values match bitwise."""
    import math

    dtype = jnp.dtype(cfg.dtype)
    B, S = tokens.shape
    specs = param_specs(cfg)
    block_specs = specs["blocks"]

    x = full_nonblock["embed"].astype(dtype)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), dtype)
    if cfg.positional == "sinusoidal":
        table = jnp.asarray(sinusoidal_positions(cfg.max_seq_len,
                                                 cfg.d_model))
        x = x + table.astype(dtype)[positions]
        rope = None
    else:
        rope = jnp.asarray(rope_frequencies(
            cfg.resolved_head_dim, theta=cfg.rope_theta,
            llama3_scaling=cfg.rope_scaling))

    impl = resolve_seq_impl(cfg, None, S)
    masks = {kind: None for kind in set(cfg.block_pattern)}
    if impl == "xla":
        for kind in masks:
            masks[kind] = make_attention_mask(
                positions, positions, segment_ids, segment_ids,
                causal=True,
                sliding_window=(cfg.sliding_window if kind == "sliding"
                                else None))

    R = cfg.n_repeats
    cur0 = _gather_layer(blocks_local, block_specs, mesh, 0, shard_reduce)

    def body(carry, i):
        x, aux, cur = carry
        # prefetch layer i+1 while layer i computes; the barrier pins
        # the issue order (the gather must complete before x is
        # released to this layer's compute — the double-buffer
        # discipline). The wrap-around gather of layer 0 on the last
        # iteration is carried out unused; its cotangent is zero.
        nxt = _gather_layer(blocks_local, block_specs, mesh, (i + 1) % R,
                            shard_reduce)
        nxt, x = _pin((nxt, x))
        layer_slice = jtu.tree_map(lambda v: v[0], cur)
        x, aux = run_block_stack(
            x, aux, layer_slice, cfg, impl, dtype, rope, positions,
            masks, segment_ids, None, fused_ops=fused_ops)
        return (x, aux, nxt), None

    bodyf = body
    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        bodyf = jax.checkpoint(body, prevent_cse=False, policy=policy)
    (x, _, _), _ = jax.lax.scan(
        bodyf, (x, jnp.zeros((), jnp.float32), cur0), jnp.arange(R))
    return x


def make_manual_grad_fn(cfg: ModelConfig, mesh: Mesh, *,
                        batch_keys: Tuple[str, ...] =
                        ("inputs", "targets", "weights"),
                        fused_ops: bool = False,
                        use_fused_ce: bool = False,
                        num_slices: int = 1,
                        dcn_sync: str = "flat",
                        dcn_compress: str = "none"):
    """Build ``(params, micro) -> ((nll_sum, w_sum), grads)`` — the
    drop-in replacement for the GSPMD path's
    ``value_and_grad(micro_loss)`` that the accum scan consumes. The
    returned function is a ``shard_map`` over the whole mesh: inputs
    arrive as the local param shards / local batch rows, the fsdp
    gathers and grad reductions are placed explicitly, and the outputs
    come back sharded exactly like the GSPMD grads (params-like tree +
    replicated scalars).

    ``num_slices``/``dcn_sync``/``dcn_compress``: the DCN-aware
    reduction knobs (module docstring). With compression on, the
    signature grows an error-feedback residual:
    ``(params, micro, residual) -> ((nll, w), grads, new_residual)`` —
    the residual tree is params-shaped (zeros at step start; the accum
    scan in ``train/step.py`` carries it across microbatches) and the
    returned fn carries ``grad_fn.compressed = True``."""
    from gke_ray_train_tpu.parallel.hierarchical import (
        compressed_cross_psum, flat_reduce_shard, hier_reduce_full,
        hier_reduce_shard, intra_reduce_shard, slice_topology,
        staged_psum)

    check_manual_support(cfg, mesh)
    specs = param_specs(cfg)
    topo = slice_topology(mesh, num_slices)
    compressed = dcn_compress != "none" and topo is not None
    if dcn_sync == "hier" and topo is None:
        # a loud no-op: single-slice pools have no DCN hop to shrink
        # (plan validation already downgraded a declared NUM_SLICES=1
        # hier; this catches direct callers)
        dcn_sync = "flat"

    # the sharded-leaf reduction _fsdp_gather's backward applies:
    #   single slice      — None (the joint psum + slice, unchanged)
    #   flat  multi-slice — staged full payload over DCN
    #   hier  multi-slice — scattered shard over DCN (1/ici_size)
    #   compressed        — intra-slice half only; the DCN hop runs
    #                       after value_and_grad, with the residual
    if topo is None:
        shard_reduce = None
    elif compressed:
        shard_reduce = lambda ct, dim: intra_reduce_shard(ct, topo, dim)  # noqa: E731
    elif dcn_sync == "hier":
        shard_reduce = lambda ct, dim: hier_reduce_shard(ct, topo, dim)  # noqa: E731
    else:
        shard_reduce = lambda ct, dim: flat_reduce_shard(ct, topo, dim)  # noqa: E731

    def _scalar_sum(x):
        return jax.lax.psum(x, _DP_AXES) if topo is None \
            else staged_psum(x, topo)

    def local_grad(params_local, micro_local, resid_local=None):
        B_loc, S = micro_local["inputs"].shape
        positions = micro_local.get("positions")
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32), (B_loc, S))
        segment_ids = micro_local.get("segment_ids")

        def loss_fn(p):
            nonblock = {k: v for k, v in p.items() if k != "blocks"}
            nb_specs = {k: v for k, v in specs.items() if k != "blocks"}
            full_nb = _gather_full(nonblock, nb_specs, mesh, shard_reduce)
            x = _pipelined_hidden(full_nb, p["blocks"], cfg, mesh,
                                  micro_local["inputs"], positions,
                                  segment_ids, fused_ops, shard_reduce)
            dtype = jnp.dtype(cfg.dtype)
            if use_fused_ce and cfg.logit_softcap is None:
                from gke_ray_train_tpu.ops.fused_ce import \
                    fused_cross_entropy
                xn = pre_unembed(x, full_nb, cfg, None)
                nll, w = fused_cross_entropy(
                    xn.astype(dtype),
                    unembed_head(full_nb, cfg).astype(dtype),
                    micro_local["targets"], micro_local["weights"])
            else:
                from gke_ray_train_tpu.train.step import token_nll
                logits = _unembed(x, full_nb, cfg, dtype, None)
                nll, w = token_nll(logits, micro_local["targets"],
                                   micro_local["weights"])
            return nll, w

        (nll, w), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params_local)

        if compressed:
            # sharded leaves arrive as intra-slice partials; the DCN
            # hop runs here, bf16 with the error-feedback residual.
            # Replicated leaves (norms — a rounding error of bytes)
            # ride the uncompressed hier hop; their residual stays 0.
            def hop(gl, rl, spec):
                if _leaf_fsdp_dims(spec, mesh):
                    return compressed_cross_psum(gl, rl, topo,
                                                 dcn_compress)
                return hier_reduce_full(gl, topo), rl

            paired = jtu.tree_map(hop, g, resid_local, specs,
                                  is_leaf=lambda s: isinstance(s, P))
            is_pair = lambda x: isinstance(x, tuple)  # noqa: E731
            g = jtu.tree_map(lambda p: p[0], paired, is_leaf=is_pair)
            new_resid = jtu.tree_map(lambda p: p[1], paired,
                                     is_leaf=is_pair)
            return (g, _scalar_sum(nll), _scalar_sum(w), new_resid)

        def reduce_leaf(gl, spec):
            # gathered leaves were already reduced over BOTH axes by
            # _fsdp_gather's backward; replicated leaves (norms,
            # biases) still need the cross-device sum
            if _leaf_fsdp_dims(spec, mesh):
                return gl
            if topo is None:
                return jax.lax.psum(gl, _DP_AXES)
            return (hier_reduce_full(gl, topo) if dcn_sync == "hier"
                    else staged_psum(gl, topo))

        g = jtu.tree_map(reduce_leaf, g, specs,
                         is_leaf=lambda s: isinstance(s, P))
        return g, _scalar_sum(nll), _scalar_sum(w)

    batch_specs = {k: P(_DP_AXES, None) for k in batch_keys}
    if compressed:
        mapped = shard_map(local_grad, mesh=mesh,
                           in_specs=(specs, batch_specs, specs),
                           out_specs=(specs, P(), P(), specs),
                           check_vma=False)

        @functools.wraps(local_grad)
        def grad_fn(params: Params, micro: Dict[str, Any], residual):
            g, nll, w, new_resid = mapped(params, micro, residual)
            return (nll, w), g, new_resid
    else:
        mapped = shard_map(local_grad, mesh=mesh,
                           in_specs=(specs, batch_specs),
                           out_specs=(specs, P(), P()),
                           check_vma=False)

        @functools.wraps(local_grad)
        def grad_fn(params: Params, micro: Dict[str, Any]):
            g, nll, w = mapped(params, micro)
            return (nll, w), g

    grad_fn.compressed = compressed
    return grad_fn
