"""Optimizer & LR schedule.

Behavioral parity with the reference's hand-rolled loop
(ray-jobs/pytorch_llm_ray.py:236-258): AdamW(lr, weight_decay=0.01),
linear warmup over 5% of total steps, cosine decay to 1% of base LR,
global-norm gradient clipping at 1.0 (:277-279). The bitsandbytes
``paged_adamw_32bit`` of the fine-tune path (fine_tune_config.json:17) has
no TPU analogue and needs none: optimizer state is GSPMD-sharded over the
``fsdp`` axis via the same specs as the params, so memory paging is
replaced by sharding (SURVEY.md row D5).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax


def warmup_cosine_schedule(base_lr: float, total_steps: int, *,
                           warmup_frac: float = 0.05,
                           min_lr_frac: float = 0.01) -> optax.Schedule:
    """Reference schedule (pytorch_llm_ray.py:243-252): 5% linear warmup
    from 0, cosine to min_lr_frac * base_lr."""
    warmup_steps = max(1, int(total_steps * warmup_frac))
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=base_lr,
        warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=base_lr * min_lr_frac,
    )


# leaves AdamW must not decay: norm scales and projection biases. Keyed
# by NAME because the stacked block layout makes norm scales [R, D] and
# biases [R, dim] — an ndim>=2 test wrongly classified them as matrices
# (the pre-r5 mask decayed stacked norm scales despite its docstring).
_NO_DECAY_KEYS = frozenset({
    "attn_norm", "mlp_norm", "attn_post_norm", "mlp_post_norm",
    "final_norm", "bq", "bk", "bv"})


def default_weight_decay_mask(params: Any) -> Any:
    """Decay only weight matrices — norm scales and biases are excluded.

    (Deviation from the reference, which lets torch AdamW decay
    everything; decaying RMSNorm scales toward zero is simply wrong for
    pre-LN transformers, so we fix it rather than port it.)
    """
    def decay(path, p):
        key = next((e.key for e in reversed(path) if hasattr(e, "key")),
                   None)
        return key not in _NO_DECAY_KEYS and p.ndim >= 2

    return jax.tree_util.tree_map_with_path(decay, params)


def make_optimizer(schedule: optax.Schedule | float, *,
                   weight_decay: float = 0.01,
                   clip_norm: Optional[float] = 1.0,
                   b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                   weight_decay_mask: Optional[Callable] = None,
                   ) -> optax.GradientTransformation:
    txs = []
    if clip_norm is not None:
        txs.append(optax.clip_by_global_norm(clip_norm))
    txs.append(optax.adamw(
        schedule, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
        mask=weight_decay_mask or default_weight_decay_mask))
    return optax.chain(*txs)
