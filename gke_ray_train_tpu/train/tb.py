"""TensorBoard scalar reporting (SURVEY.md row D12 / §5.5; VERDICT r1
missing #4).

The reference gets TensorBoard events for free from HF Trainer via
``REPORT_TO: "tensorboard"`` (/root/reference/ray-jobs/
fine_tune_config.json:26, consumed by SFTConfig). Here a thin writer
emits the same scalar curves (loss, learning_rate, grad_norm, eval_loss)
plus the TPU-first metrics the reference never logs (tokens/sec/chip,
MFU) from host 0 into ``OUTPUT_DIR_BASE`` — dashboard-visible on the
FUSE mount the RayCluster CR provides.
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger(__name__)


class TensorBoardWriter:
    """Host-0 scalar event writer; numeric metrics only, silently skips
    the rest. Never fatal: if no TB backend is importable the writer
    degrades to a warning + no-op (training must not depend on a
    dashboard library)."""

    def __init__(self, logdir: str):
        self.logdir = logdir
        self._w = None
        try:
            from tensorboardX import SummaryWriter
            self._w = SummaryWriter(logdir)
        except Exception:  # noqa: BLE001
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._w = SummaryWriter(logdir)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "REPORT_TO=tensorboard but no writer backend "
                    "importable (%s); scalars will not be logged",
                    type(e).__name__)

    def log(self, step: int, metrics: dict) -> None:
        if self._w is None:
            return
        for k, v in metrics.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            self._w.add_scalar(k, float(v), global_step=step)

    def log_registry(self, step: int, registry) -> None:
        """Publish an obs MetricsRegistry snapshot (obs/metrics.py) as
        ``obs/<name>`` scalars — the goodput ledger terms and the
        serving latency/occupancy reach TensorBoard from the SAME
        registry the Prometheus/JSON exporters read; there is no second
        computation path to drift. Histograms publish their p50/p99."""
        if self._w is None or registry is None:
            return
        snap = registry.snapshot()
        snap.pop("labels", None)
        flat = {}
        for k, v in snap.items():
            if isinstance(v, dict):          # histogram snapshot
                flat[f"obs/{k}_p50"] = v.get("p50")
                flat[f"obs/{k}_p99"] = v.get("p99")
            else:
                flat[f"obs/{k}"] = v
        self.log(step, {k: v for k, v in flat.items() if v is not None})

    def flush(self) -> None:
        if self._w is not None:
            self._w.flush()

    def close(self) -> None:
        if self._w is not None:
            self._w.close()
            self._w = None


def writer_from_config(config: dict, default_dir: str,
                       is_host0: bool = True) -> Optional[TensorBoardWriter]:
    """Honor REPORT_TO (reference fine_tune_config.json:26): 'tensorboard'
    → host-0 writer under OUTPUT_DIR_BASE; 'none'/absent → None."""
    report_to = str(config.get("REPORT_TO", "none")).lower()
    if report_to in ("none", "", "null"):
        return None
    if report_to != "tensorboard":
        logger.warning("REPORT_TO=%r not supported (only 'tensorboard' / "
                       "'none'); disabling reporting", report_to)
        return None
    if not is_host0:
        return None
    return TensorBoardWriter(default_dir)
