"""Config surface: key audit + honored-key factories (SURVEY.md §5.6).

The reference's single user-facing config is a flat UPPER_CASE JSON
(/root/reference/ray-jobs/fine_tune_config.json, consumed across
fine_tune_llama_ray.py:198-399). Parity rule here: every key is either
HONORED (listed in KNOWN_KEYS and read somewhere) or WARNED about —
never silently ignored (VERDICT r1 weak #4).

Reference-only bitsandbytes keys are mapped, not dropped:
``BNB_4BIT_QUANT_TYPE`` feeds the QUANT_KIND default,
``USE_NESTED_QUANT``/``BNB_4BIT_COMPUTE_DTYPE`` warn when they ask for
something the TPU quantizer does differently.
"""

from __future__ import annotations

import logging

import optax

from gke_ray_train_tpu.train.optim import make_optimizer, \
    warmup_cosine_schedule

logger = logging.getLogger(__name__)

# config keys owned by the declarative ExecutionPlan (plan.py): mesh
# topology, batch shape, donation, input pipeline, compile-once policy,
# runtime guards, identity. Declared here so the plan <-> config-surface
# contract is checkable: plancheck rule PLAN005 asserts this set equals
# plan.CONFIG_KEYS.values() exactly (and that it is a KNOWN_KEYS
# subset) — a knob renamed on either side fails lint instead of being
# silently ignored.
PLAN_SCOPED_KEYS = frozenset({
    # mesh topology
    "MESH_DATA", "MESH_FSDP", "MESH_MODEL", "MESH_CONTEXT", "MESH_PIPE",
    "NUM_SLICES", "PIPE_MICROBATCHES", "PIPE_VIRTUAL_STAGES",
    # batch shape the step compiles against
    "PER_DEVICE_TRAIN_BATCH_SIZE", "GRADIENT_ACCUMULATION_STEPS",
    "MAX_SEQ_LENGTH", "PACKING",
    # donation policy
    "DONATE_STATE", "DONATE_BATCH",
    # input pipeline
    "PREFETCH_BATCHES",
    # compile-once policy (perf/cache.py)
    "COMPILE_CACHE", "COMPILE_CACHE_DIR", "AOT_TRAIN_STEP",
    # runtime guards (analysis/guards.py)
    "TRANSFER_GUARD", "RECOMPILE_LIMIT", "DIVERGENCE_GUARD",
    # serving shape (serve/engine.py): slot count, length buckets,
    # served-weight quantization, multi-tenant adapter pool size,
    # prefix/KV reuse and speculative decoding (ISSUE 17) — all
    # serve-surface compile-relevant, never train-relevant
    "MAX_BATCH", "DECODE_BUCKETS", "SERVE_QUANT",
    "MAX_ADAPTERS", "PREFIX_CACHE", "SPEC_DRAFT", "SPEC_K",
    # observability (obs/): unified telemetry on/off + dir, the
    # anomaly-triggered profiler capture policy, and causal span
    # tracing (obs/trace.py — per-rank span streams, critical-path
    # attribution in `obs report`). Operational knobs — never
    # compile-relevant (toggling telemetry must not stale a sidecar;
    # plan.COMPILE_SURFACES excludes them).
    "OBS", "OBS_DIR", "OBS_CAPTURE", "OBS_CAPTURE_BUDGET", "TRACE",
    # autotuning (autotune/): AUTOTUNE=1 overlays a tuned-plan registry
    # hit (keyed by model digest + topology + surface) onto the
    # resolved plan before anything compiles. The flag itself is
    # operational (consulting the registry must not stale a sidecar);
    # the overlay re-fingerprints through the fields it changes.
    # AUTOTUNE_INGEST opts an autotuned run out of the attempt-end
    # observed-row feedback hook — operational for the same reason.
    "AUTOTUNE", "AUTOTUNE_INGEST",
    # kernel & overlap execution path (ROADMAP #3): OVERLAP picks the
    # collective-hiding mode (off | xla | manual), FUSED_OPS routes the
    # memory-bound epilogues through the fused Pallas kernels. Both are
    # compile-relevant (plan.COMPILE_SURFACES includes them on the
    # train surface, so AOT sidecars stale on a retune).
    "OVERLAP", "FUSED_OPS",
    # DCN-aware gradient sync (parallel/hierarchical.py): DCN_SYNC
    # picks the cross-slice reduction arm (flat | hier) on a
    # multi-slice hybrid mesh; DCN_COMPRESS=bf16 casts only the hier
    # DCN hop with error feedback. Train-surface compile-relevant.
    "DCN_SYNC", "DCN_COMPRESS",
    # identity: declared chip topology + pinned cost budget
    "TOPOLOGY", "BUDGET_PRESET",
})

# every key the fine-tune entry point honors (reference keys + mesh/TPU
# extensions). Keys present in a config but not listed here draw a warning.
# Plan-scoped keys are unioned in below (one declaration, no drift).
KNOWN_KEYS = frozenset({
    # model / data / output
    "MODEL_ID", "DATASET_NAME", "OUTPUT_DIR_BASE",
    "PRETRAINED_CHECKPOINT_DIR",
    "NUM_TRAIN_SAMPLES", "NUM_EVAL_SAMPLES",
    "SFT_SUBDIR_NAME", "MERGED_MODEL_SUBDIR_NAME",
    "FULL_FT_MODEL_SUBDIR_NAME",
    # LoRA / quantization
    "USE_QLORA", "LORA_ALPHA", "LORA_DROPOUT", "LORA_R",
    "LLAMA_TARGET_MODULES", "QUANT_KIND",
    "BNB_4BIT_COMPUTE_DTYPE", "BNB_4BIT_QUANT_TYPE", "USE_NESTED_QUANT",
    # optimization
    "NUM_TRAIN_EPOCHS", "LEARNING_RATE", "WEIGHT_DECAY",
    "OPTIM", "LR_SCHEDULER_TYPE", "MAX_GRAD_NORM", "WARMUP_RATIO",
    # cadences / reporting
    "LOGGING_STEPS", "SAVE_STRATEGY", "SAVE_STEPS_SFT",
    "EVALUATION_STRATEGY_SFT", "EVAL_STEPS_SFT", "REPORT_TO",
    # sequence handling (MAX_SEQ_LENGTH/PACKING are plan-scoped)
    "GROUP_BY_LENGTH",
    # inference comparison
    "INFERENCE", "NUM_EVAL_SAMPLES_INFERENCE",
    "MAX_NEW_GENERATION_TOKENS_INFERENCE",
    # post-train serving smoke (serve/engine.py): run the comparison
    # prompts through the continuous-batching engine after training
    "SERVE_AFTER_TRAIN",
    # elastic training (rayint/elastic.py): opt into mesh re-formation
    # on pool shrink/grow, and the smallest pool worth re-forming on.
    # Trainer-scoped (like SERVE_AFTER_TRAIN), not plan-scoped: they
    # change retry policy, never the compiled program.
    "ELASTIC", "MIN_DEVICES",
    # goodput knobs (ckpt/manager.py, ckpt/peer.py): ASYNC_CKPT=1 moves
    # the storage commit behind a write-ahead marker on a background
    # thread; PEER_REPLICATION=1 streams snapshots to the peer slice's
    # hot store; CKPT_COMMIT_TIMEOUT_S bounds the exit-time commit
    # drain. Trainer-scoped like ELASTIC: recovery policy only — the
    # compiled program and the loss stream are bitwise unchanged.
    # CKPT_STORAGE_DELAY_S emulates the storage round-trip per commit
    # (the chaos drill's stand-in for GCS latency)
    "ASYNC_CKPT", "PEER_REPLICATION", "CKPT_COMMIT_TIMEOUT_S",
    "CKPT_STORAGE_DELAY_S",
    # autotune registry/search knobs (autotune/): AUTOTUNE_DIR points
    # the tuned-plan registry somewhere other than <repo>/tuned_plans;
    # AUTOTUNE_BUDGET caps the full-compile count the search spends
    # (successive halving beyond it). AUTOTUNE_DRIFT_BAND is the
    # calibration drift tolerance: |corrected modeled − measured| /
    # measured beyond it marks a registry entry stale at ingest and
    # the overlay refuses it. Trainer/CLI-scoped like
    # KERNELCHECK — none changes the compiled program (the AUTOTUNE
    # flag itself is plan-scoped above).
    "AUTOTUNE_DIR", "AUTOTUNE_BUDGET", "AUTOTUNE_DRIFT_BAND",
    # kernelcheck (analysis/kernelcheck.py): KERNELCHECK=1 runs the
    # registry's differential startup probe in every worker (each
    # kernel's cheapest case vs its oracle, gated by the pinned
    # ledger); TOLERANCE_UPDATE=1 re-records tests/tolerances/*.json.
    # Trainer/CLI-scoped: neither changes the compiled program.
    "KERNELCHECK", "TOLERANCE_UPDATE",
    # TPU / model-numerics extensions (the plan owns the mesh keys)
    "TRAIN_DTYPE", "PARAM_DTYPE", "ATTN_IMPL", "REMAT_POLICY",
    "SMOKE_TEST",
    # profiling / debug (train/profiling.py)
    "PROFILE", "PROFILE_START_STEP", "PROFILE_NUM_STEPS", "DEBUG_NANS",
}) | PLAN_SCOPED_KEYS


def audit_config(config: dict, *, known=KNOWN_KEYS,
                 extra_known=()) -> list:
    """Warn (once, host-0 callers gate) about unknown keys; returns them.
    Keys starting with "_" are comments (JSON has none natively)."""
    unknown = sorted(k for k in config
                     if k not in known and k not in extra_known
                     and not k.startswith("_"))
    if unknown:
        logger.warning("config keys not recognized (ignored): %s", unknown)
    if bool(config.get("USE_NESTED_QUANT", False)):
        logger.warning("USE_NESTED_QUANT: nested/double quantization is "
                       "not implemented; using single-level %s",
                       config.get("QUANT_KIND", "nf4"))
    return unknown


def quant_kind_from_config(config: dict, use_lora: bool) -> str:
    """QUANT_KIND, defaulting through the reference's BNB_4BIT_QUANT_TYPE
    (fine_tune_config.json:10) so reference configs quantize the same way."""
    default = (config.get("BNB_4BIT_QUANT_TYPE", "nf4")
               if use_lora else "none")
    return str(config.get("QUANT_KIND", default)).lower()


def schedule_from_config(config: dict, total_steps: int) -> optax.Schedule:
    """Honor LR_SCHEDULER_TYPE (reference fine_tune_config.json:15; HF
    Trainer semantics): cosine (default), linear (decay to 0), constant /
    constant_with_warmup. Unknown names warn and fall back to cosine."""
    base_lr = float(config.get("LEARNING_RATE", 2e-4))
    warmup_frac = float(config.get("WARMUP_RATIO", 0.03))
    kind = str(config.get("LR_SCHEDULER_TYPE", "cosine")).lower()
    warmup_steps = max(1, int(total_steps * warmup_frac))
    if kind == "cosine":
        return warmup_cosine_schedule(base_lr, total_steps,
                                      warmup_frac=warmup_frac)
    if kind == "linear":
        return optax.schedules.join_schedules([
            optax.schedules.linear_schedule(0.0, base_lr, warmup_steps),
            optax.schedules.linear_schedule(
                base_lr, 0.0, max(total_steps - warmup_steps, 1)),
        ], [warmup_steps])
    if kind == "constant":
        # HF semantics: flat LR from step 0, no warmup
        return optax.schedules.constant_schedule(base_lr)
    if kind == "constant_with_warmup":
        return optax.schedules.join_schedules([
            optax.schedules.linear_schedule(0.0, base_lr, warmup_steps),
            optax.schedules.constant_schedule(base_lr),
        ], [warmup_steps])
    logger.warning("LR_SCHEDULER_TYPE=%r not recognized; using cosine", kind)
    return warmup_cosine_schedule(base_lr, total_steps,
                                  warmup_frac=warmup_frac)


def optimizer_from_config(config: dict, schedule) -> \
        optax.GradientTransformation:
    """Honor OPTIM (reference fine_tune_config.json:17). The adamw family
    (incl. bitsandbytes' paged_adamw_* — paging is replaced by GSPMD
    optimizer-state sharding, SURVEY.md row D5) maps to optax.adamw;
    adafactor and sgd are honored directly; unknown names warn → adamw."""
    name = str(config.get("OPTIM", "adamw")).lower()
    wd = float(config.get("WEIGHT_DECAY", 0.001))
    clip = float(config.get("MAX_GRAD_NORM", 0.3))
    if "adamw" in name or name == "adam":
        return make_optimizer(schedule, weight_decay=wd, clip_norm=clip)
    if "adafactor" in name:
        return optax.chain(optax.clip_by_global_norm(clip),
                           optax.adafactor(learning_rate=schedule,
                                           weight_decay_rate=wd or None))
    if name == "sgd":
        return optax.chain(optax.clip_by_global_norm(clip),
                           optax.sgd(schedule, momentum=0.9))
    logger.warning("OPTIM=%r not recognized; using adamw", name)
    return make_optimizer(schedule, weight_decay=wd, clip_norm=clip)


def cadence_from_config(config: dict) -> dict:
    """Resolve SAVE_STRATEGY / EVALUATION_STRATEGY_SFT (reference
    fine_tune_config.json:22-25; HF Trainer semantics: "steps" | "epoch" |
    "no") into loop arguments."""
    save_strat = str(config.get("SAVE_STRATEGY", "steps")).lower()
    eval_strat = str(config.get("EVALUATION_STRATEGY_SFT", "steps")).lower()
    if save_strat not in ("steps", "epoch", "no"):
        logger.warning("SAVE_STRATEGY=%r not recognized; using 'steps'",
                       save_strat)
        save_strat = "steps"
    if eval_strat not in ("steps", "epoch", "no"):
        logger.warning("EVALUATION_STRATEGY_SFT=%r not recognized; "
                       "using 'steps'", eval_strat)
        eval_strat = "steps"
    out = {
        "save_enabled": save_strat != "no",
        "ckpt_every": (int(config.get("SAVE_STEPS_SFT", 50))
                       if save_strat == "steps" else None),
        "eval_enabled": eval_strat != "no",
        "eval_every": (int(config.get("EVAL_STEPS_SFT", 50))
                       if eval_strat == "steps" else None),
        "eval_at_epoch_end": eval_strat == "epoch",
    }
    return out
