"""Multi-host global-batch form-up (SURVEY.md row D9).

The reference gets global-batch semantics from the ``DistributedSampler``
that Ray Train injects via ``train.torch.prepare_data_loader``
(/root/reference/ray-jobs/pytorch_llm_ray.py:216): every rank loads its
1/world_size of each batch and DDP treats the union as the global batch.

The TPU equivalent has one extra step the torch path hides: under
multi-process JAX, a jitted function sharded over a mesh consumes
*global* ``jax.Array``s whose shards live across hosts — feeding
host-local numpy is wrong (and rejected) once ``process_count() > 1``.
``jax.make_array_from_process_local_data`` is the designed form-up: each
host contributes its local rows, JAX assembles the global array without
any cross-host data movement (every host's rows land on its own devices).

Single-host runs take the identical code path (process_count()==1 makes
local == global), so tests on the 8-fake-device CPU mesh exercise the
real multi-host logic.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gke_ray_train_tpu.parallel.mesh import AXIS_CONTEXT, BATCH_AXES

# batch keys that shard over the sequence axis too when context
# parallelism is on (token-aligned [B, S] arrays)
_SEQ_KEYS = ("inputs", "targets", "weights", "segment_ids", "positions")


def input_shard_layout(mesh: Mesh) -> Tuple[int, int]:
    """(shard_count, shard_index): how host input pipelines must
    partition batch rows for this mesh.

    Processes do NOT always tile the batch axes 1:1 — when the model or
    context axis spans hosts (e.g. TP across a pod slice), groups of
    processes address the *same* batch rows and must feed identical
    data. This computes, from the sharding itself, how many distinct
    row-groups exist (shard_count) and which one this process belongs to
    (shard_index) — the TPU-correct generalization of the reference's
    rank/world_size DistributedSampler split
    (/root/reference/ray-jobs/pytorch_llm_ray.py:216).
    """
    n_tiles = math.prod(mesh.shape[a] for a in BATCH_AXES)
    sharding = NamedSharding(mesh, P(BATCH_AXES))
    imap = sharding.devices_indices_map((n_tiles,))
    groups: Dict[int, set] = {}
    for d, idx in imap.items():
        groups.setdefault(d.process_index, set()).add(idx[0].start or 0)
    distinct = sorted({tuple(sorted(g)) for g in groups.values()})
    # well-formedness: the distinct row-groups must partition the tiles
    # into equal shares (place_batch sizes global_B as local_B * count)
    covered = [t for g in distinct for t in g]
    if sorted(covered) != list(range(n_tiles)) or \
            len({len(g) for g in distinct}) != 1:
        raise ValueError(
            f"process batch tiles do not evenly partition the batch axis "
            f"(groups={distinct}); use a standard mesh layout")
    mine = tuple(sorted(groups[jax.process_index()]))
    return len(distinct), distinct.index(mine)


def place_batch(mesh: Mesh, batch: Dict[str, np.ndarray], *,
                context_sharded: bool = False,
                shard_count: Optional[int] = None) -> Dict[str, jax.Array]:
    """Host-local batch dict [local_B, S] → global sharded arrays
    [local_B * shard_count, S].

    Every host must call this collectively (SPMD) with equal shapes, the
    same way every rank's DataLoader yields in the reference. Hosts in
    the same input shard group (see ``input_shard_layout``) must pass
    identical data. Non-batch dims always match the local shape: each
    device slices its model/context portion from its own host's copy, so
    the pipeline never needs to pre-split sequences.
    """
    if shard_count is None:
        shard_count = input_shard_layout(mesh)[0]
    seq = AXIS_CONTEXT if context_sharded else None
    out = {}
    for k, v in batch.items():
        v = np.asarray(v)
        spec = P(BATCH_AXES, seq) if k in _SEQ_KEYS else P(BATCH_AXES)
        sharding = NamedSharding(mesh, spec)
        global_shape = (v.shape[0] * shard_count,) + v.shape[1:]
        out[k] = jax.make_array_from_process_local_data(
            sharding, v, global_shape)
    return out


def make_place_batch(mesh: Mesh, *, context_sharded: bool = False
                     ) -> Callable[[Dict[str, np.ndarray]],
                                   Dict[str, jax.Array]]:
    """Bind mesh + context flag into the ``place_batch`` hook shape that
    ``train.loop.run_training`` accepts (layout computed once)."""
    shard_count, _ = input_shard_layout(mesh)

    def place(batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        return place_batch(mesh, batch, context_sharded=context_sharded,
                           shard_count=shard_count)
    return place


def host_batch_size(global_batch: int, *,
                    num_shards: Optional[int] = None,
                    mesh: Optional[Mesh] = None) -> int:
    """Rows each input shard must contribute per step. Errors early
    (with the fix spelled out) instead of letting the form-up fail
    mid-train. Requires the shard count or the mesh to derive it from —
    process_count() is NOT a valid default (model/context axes spanning
    hosts make input shards != processes)."""
    if num_shards is None and mesh is None:
        raise TypeError("host_batch_size needs num_shards= or mesh=")
    n = (num_shards if num_shards is not None
         else input_shard_layout(mesh)[0])
    if global_batch % n != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {n} input "
            "shards — pick PER_DEVICE_TRAIN_BATCH_SIZE * mesh data axes "
            "so every host group contributes the same number of rows")
    return global_batch // n
