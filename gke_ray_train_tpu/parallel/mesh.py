"""Topology & distributed init — the TPU-native replacement for the
reference's process-group bootstrap + NCCL backend.

Reference behavior being replaced (see SURVEY.md §5.8):
- Ray sets MASTER_ADDR/PORT/WORLD_SIZE/RANK and Accelerate calls
  ``torch.distributed.init_process_group`` (narrated in the reference at
  ray-jobs/fine_tune_llama_ray.py:413-419); NCCL is selected explicitly at
  ray-jobs/pytorch_llm_ray.py:362-364.

TPU-native design: ``jax.distributed.initialize`` performs multi-host
rendezvous (coordinator = host 0, address supplied by the Ray trainer),
after which there is *no communication library to manage* — collectives
(psum / all_gather / reduce_scatter / ppermute) are emitted by GSPMD from
sharding specs and ride ICI within a slice, DCN between slices.

Mesh axes (fixed vocabulary across the framework):

==========  ========================================================
axis        what is sharded over it
==========  ========================================================
``data``    pure data parallelism — batch only (DCN-friendly, outermost)
``fsdp``    batch AND params/optimizer state (ZeRO-3-style, over ICI)
``model``   tensor parallelism — attention heads / ffn hidden
``context`` sequence/context parallelism — ring attention over ICI
``pipe``    pipeline parallelism — layer (repeat) dim of the stacked
            blocks; stages exchange activations via collective-permute
            (models/pipeline.py)
==========  ========================================================
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_MODEL = "model"
AXIS_CONTEXT = "context"
AXIS_PIPE = "pipe"
MESH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_MODEL, AXIS_CONTEXT, AXIS_PIPE)

# Batch dims are sharded over both DP-like axes; this is the standard GSPMD
# trick that makes FSDP "just a sharding spec" (SURVEY.md §2c row FSDP).
BATCH_AXES = (AXIS_DATA, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Any axis may be -1 ("fill with what remains").

    Mirrors the reference's infra-shape env vars NUM_NODES /
    NUM_GPUS_PER_NODE (ray-jobs/fine_tune_llama_ray.py:439-441) but as a
    5-axis logical topology instead of a flat world size.
    """

    data: int = 1
    fsdp: int = -1
    model: int = 1
    context: int = 1
    # Pipeline stages. Last mesh dim → stages sit on adjacent ICI
    # neighbors, so the stage-to-stage activation permute is one hop.
    pipe: int = 1
    # Number of DCN-connected slices. When >1, the `data` axis is laid out
    # across slices (DCN-outermost) via a hybrid device mesh.
    num_slices: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        """Resolve -1 entries so the product equals ``n_devices``."""
        sizes = {f.name: getattr(self, f.name) for f in dataclasses.fields(self)
                 if f.name in MESH_AXES}
        fills = [k for k, v in sizes.items() if v == -1]
        if len(fills) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {fills}")
        bad = {k: v for k, v in sizes.items() if v != -1 and v < 1}
        if bad:
            raise ValueError(f"mesh axis sizes must be >=1 (or -1): {bad}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if fills:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"cannot fill axis {fills[0]}: {n_devices} devices not "
                    f"divisible by fixed product {fixed} ({sizes})")
            sizes[fills[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} has {math.prod(sizes.values())} slots but "
                f"{n_devices} devices are present")
        return dataclasses.replace(self, **sizes)

    @property
    def shape(self) -> tuple:
        return (self.data, self.fsdp, self.model, self.context, self.pipe)

    @staticmethod
    def from_dict(cfg: dict) -> "MeshConfig":
        """Build from the flat UPPER_CASE config convention the reference
        uses for fine_tune_config.json (SURVEY.md §5.6)."""
        return MeshConfig(
            data=int(cfg.get("MESH_DATA", 1)),
            fsdp=int(cfg.get("MESH_FSDP", -1)),
            model=int(cfg.get("MESH_MODEL", 1)),
            context=int(cfg.get("MESH_CONTEXT", 1)),
            pipe=int(cfg.get("MESH_PIPE", 1)),
            num_slices=int(cfg.get("NUM_SLICES", 1)),
        )


def build_mesh(config: MeshConfig | None = None,
               devices: Optional[Sequence[Any]] = None) -> Mesh:
    """Build the 5-axis device mesh.

    Single-slice: ``mesh_utils.create_device_mesh`` lets JAX pick a
    device order that maps logical neighbors onto physical ICI neighbors
    (critical for ring attention on ``context`` and all-gathers on
    ``fsdp``). Multi-slice: a hybrid mesh puts ``data`` across DCN.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = (config or MeshConfig()).resolve(len(devices))

    if config.num_slices > 1:
        if config.data % config.num_slices != 0:
            raise ValueError(
                f"data axis ({config.data}) must be divisible by "
                f"num_slices ({config.num_slices})")
        per_slice = (config.data // config.num_slices, config.fsdp,
                     config.model, config.context, config.pipe)
        if all(getattr(d, "slice_index", None) is not None
               for d in devices):
            # real multi-slice hardware: failures here are config bugs
            # (slice count mismatch etc.) and must surface, not degrade
            dev_array = mesh_utils.create_hybrid_device_mesh(
                per_slice, (config.num_slices, 1, 1, 1, 1), devices=devices)
        else:
            # fake/CPU devices carry no slice_index attribute — emulate
            # the DCN-outermost layout: contiguous device blocks become
            # slices, the data axis (outermost, largest stride) spans
            # them, so only batch-gradient psums cross the slice
            # boundary (SURVEY.md §5.8)
            logger.warning(
                "devices report no slice_index (fake/CPU backend); "
                "emulating the %d-slice hybrid mesh row-major",
                config.num_slices)
            dev_array = np.asarray(devices).reshape(config.shape)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(
                config.shape, devices=devices)
        except (ValueError, NotImplementedError):
            # Fake/CPU devices or odd topologies: plain row-major layout.
            dev_array = np.asarray(devices).reshape(config.shape)
    return Mesh(dev_array, MESH_AXES)


def slice_assignments(devices: Sequence[Any],
                      num_slices: Optional[int] = None) -> list:
    """Slice identity per device — THE ``slice_index`` contract.

    Real multi-slice TPU devices carry ``.slice_index``; fake/CPU
    devices emulate the hybrid layout :func:`build_mesh` uses
    (contiguous row-major blocks become slices), so device ``i`` of
    ``n`` belongs to slice ``i // (n // num_slices)``. Everything that
    needs slice identity — per-slice failure domains in
    ``rayint/supervisor.py``, the ``slice_evict`` fault in
    ``testing/faults.py``, the elastic pool emulation (evicting the
    LAST slice = truncating the device list) — reads it through this
    one function so the contract cannot fork.

    ``num_slices`` defaults to ``$NUM_SLICES`` (1 when unset — a
    single-slice pool is one failure domain).
    """
    devices = list(devices)
    if devices and all(getattr(d, "slice_index", None) is not None
                       for d in devices):
        return [int(d.slice_index) for d in devices]
    n = len(devices)
    ns = int(num_slices if num_slices is not None
             else os.environ.get("NUM_SLICES", "1"))
    if ns <= 1 or n == 0 or n % ns:
        return [0] * n
    per_slice = n // ns
    return [i // per_slice for i in range(n)]


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def batch_sharding(mesh: Mesh, *, context_sharded: bool = False) -> NamedSharding:
    """Sharding for a [batch, seq, ...] array: batch over (data, fsdp),
    optionally sequence over context (sequence parallelism)."""
    seq = AXIS_CONTEXT if context_sharded else None
    return NamedSharding(mesh, P(BATCH_AXES, seq))


def _distributed_state_initialized() -> bool:
    """True if jax.distributed.initialize already ran in this process.

    Uses the distributed client handle rather than jax.process_count():
    the latter lazily initializes the XLA backend, which would make a
    subsequent jax.distributed.initialize raise.
    """
    try:
        from jax._src import distributed as _jd
        return _jd.global_state.client is not None
    except Exception:
        return False


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host rendezvous — the analogue of the reference's
    MASTER_ADDR/MASTER_PORT + init_process_group handshake
    (ray-jobs/fine_tune_llama_ray.py:413-418).

    Arguments default from env (set by rayint.JaxTrainer on each worker):
    ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID``. No-op in
    single-process mode or when already initialized.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("PROCESS_ID", "0"))
    if num_processes <= 1:
        logger.info("single-process run; skipping jax.distributed.initialize")
        return
    if _distributed_state_initialized():  # no-op if a launcher already did it
        return
    if coordinator_address is None:
        raise ValueError(
            f"multi-process run requested (NUM_PROCESSES={num_processes}) "
            "but no coordinator address given — set COORDINATOR_ADDRESS or "
            "pass coordinator_address=. Refusing to degrade to "
            f"{num_processes} independent single-process trainings.")
    # NOTE: must not touch jax.devices()/process_count() here — any backend
    # query initializes XLA, after which jax.distributed.initialize raises.
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info("jax.distributed initialized: process %d/%d, %d devices",
                process_id, num_processes, len(jax.devices()))
