from gke_ray_train_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    batch_sharding,
    named_sharding,
    distributed_init,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_CONTEXT,
    AXIS_PIPE,
    MESH_AXES,
)
from gke_ray_train_tpu.parallel.placement import (  # noqa: F401
    host_batch_size,
    input_shard_layout,
    make_place_batch,
    place_batch,
)
from gke_ray_train_tpu.parallel.hierarchical import (  # noqa: F401
    SliceTopology,
    hier_psum,
    slice_topology,
)
