"""Pytree sharding helpers.

The reference's FSDP/ZeRO story is delegated to DDP + bitsandbytes paged
optimizers (SURVEY.md rows D4/D5). Here sharded data-parallelism is purely
declarative: every param pytree travels with a matching pytree of
``PartitionSpec``; placing params/optimizer state is one ``jax.device_put``.
"""

from __future__ import annotations

import contextlib
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@contextlib.contextmanager
def sharding_invariant_rng():
    """Partitionable threefry for the duration: random draws made inside
    are IDENTICAL however — and whether — their outputs are sharded.

    On jaxlib 0.4.x the default (non-partitionable) threefry makes a
    jitted draw's VALUES depend on its ``out_shardings`` (kernelcheck's
    differential sweeps caught meshed ``init_params`` diverging from
    the plain oracle by ~3 init-stds). Every init path wraps itself in
    this context, making meshed init == plain init == init on ANY
    topology (the elastic same-seed-any-pool contract, PR 8) a real
    invariant. Scoped rather than set globally: partitionable
    generation costs ~15% wall on CPU-heavy suites, and init is where
    sharding-invariance is a *correctness* contract."""
    old = bool(jax.config.jax_threefry_partitionable)
    jax.config.update("jax_threefry_partitionable", True)
    try:
        yield
    finally:
        jax.config.update("jax_threefry_partitionable", old)


def tree_shardings(mesh: Mesh, spec_tree: Any) -> Any:
    """Map a pytree of PartitionSpec into a pytree of NamedSharding."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_tree(tree: Any, mesh: Mesh, spec_tree: Any) -> Any:
    """Place a (host-local) pytree onto the mesh per its spec tree."""
    return jax.device_put(tree, tree_shardings(mesh, spec_tree))


def constrain(x: Any, mesh: Mesh, *spec) -> Any:
    """with_sharding_constraint under an explicit mesh."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n`` (shape padding for even
    sharding: vocab / ffn dims must divide the model axis)."""
    return ((n + m - 1) // m) * m
