"""Pytree sharding helpers.

The reference's FSDP/ZeRO story is delegated to DDP + bitsandbytes paged
optimizers (SURVEY.md rows D4/D5). Here sharded data-parallelism is purely
declarative: every param pytree travels with a matching pytree of
``PartitionSpec``; placing params/optimizer state is one ``jax.device_put``.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def tree_shardings(mesh: Mesh, spec_tree: Any) -> Any:
    """Map a pytree of PartitionSpec into a pytree of NamedSharding."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_tree(tree: Any, mesh: Mesh, spec_tree: Any) -> Any:
    """Place a (host-local) pytree onto the mesh per its spec tree."""
    return jax.device_put(tree, tree_shardings(mesh, spec_tree))


def constrain(x: Any, mesh: Mesh, *spec) -> Any:
    """with_sharding_constraint under an explicit mesh."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n`` (shape padding for even
    sharding: vocab / ffn dims must divide the model axis)."""
    return ((n + m - 1) // m) * m
