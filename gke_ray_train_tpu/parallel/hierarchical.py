"""DCN-aware hierarchical gradient sync (ROADMAP #4's perf half).

On a hybrid multi-slice mesh the *data* axis — and only data, per the
PR-5 contract — spans slices, so every gradient reduction GSPMD emits
as one flat all-reduce sends its **full payload across the DCN link**
between slices. Multi-slice systems (MegaScale-style hierarchical
collectives; Gemini-style multi-slice training, PAPERS.md) decompose
that reduction so only ``1/ici_size`` of the bytes ever leave a slice:

    intra-slice reduce-scatter (ICI)
      → inter-slice all-reduce over the scattered shard (DCN)
        → intra-slice all-gather (ICI)

This module is that decomposition for the manual overlap pipeline
(``train/overlap.py``), where the program — not GSPMD — places every
collective. :class:`SliceTopology` factors the mesh's ``data`` axis
into its slice-crossing and slice-local parts (slices are the
outermost, contiguous blocks of the data axis — the hybrid layout
``parallel/mesh.py`` builds and ``test_mesh.py`` pins), and the
reduction helpers express both ``DCN_SYNC`` arms:

- ``flat``: the full payload crosses DCN (one cross-slice all-reduce
  per leaf — GSPMD's traffic shape);
- ``hier``: the scattered shard crosses (``1/ici_size`` of the bytes).

**The bitwise contract.** Both arms stage the accumulation fold at the
slice boundary — intra-slice partial sums first, the cross-slice
combine second. That shared grouping is what makes the flat and hier
loss streams **bitwise-identical** on the CPU mesh (the PR-11
discipline: match the accumulation grouping, get the bits), and it is
robust by construction: a reduce-scatter/all-gather decomposition of a
staged fold sums exactly the same partials in exactly the same order
(verified empirically on XLA:CPU; the *joint* single all-reduce is a
left fold over all ranks, which no pre-reducing decomposition can
reproduce — so on a multi-slice mesh the manual pipeline's flat arm
stages its fold, costing one ulp-class regrouping exactly once, at the
``NUM_SLICES`` 1→2 plan change that recompiles everything anyway).

``DCN_COMPRESS=bf16`` additionally casts only the DCN hop of the
*hier* arm, with error feedback across the grad-accumulation scan
(microbatch *k*'s quantization residual is added back into microbatch
*k+1*'s pre-quantization value; the step-final residual is dropped).
Not bitwise — registered in ``ops/registry.py`` with a kernelcheck
tolerance ledger. Only fsdp-sharded leaves compress (they carry ~all
the bytes); replicated leaves and the loss scalars ride f32.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

_DATA = "data"
_FSDP = "fsdp"


class HierSyncUnsupported(ValueError):
    """The mesh/plan combination has no hierarchical sync path."""


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """The DCN factorization of a data/fsdp mesh: ``data`` =
    ``num_slices`` (outermost, DCN) x ``data_intra`` (slice-local,
    ICI). ``ici_size`` is the intra-slice reduction width — the factor
    the hier hop divides the DCN payload by."""
    num_slices: int
    data: int
    fsdp: int

    @property
    def data_intra(self) -> int:
        return self.data // self.num_slices

    @property
    def ici_size(self) -> int:
        return self.data_intra * self.fsdp

    @property
    def intra_groups(self) -> Tuple[Tuple[int, ...], ...]:
        """data-axis index groups WITHIN a slice (contiguous blocks —
        slices are outermost on the data axis)."""
        di = self.data_intra
        return tuple(tuple(s * di + j for j in range(di))
                     for s in range(self.num_slices))

    @property
    def cross_groups(self) -> Tuple[Tuple[int, ...], ...]:
        """data-axis index groups ACROSS slices (same intra-slice
        position in every slice — the DCN hop's peers)."""
        di = self.data_intra
        return tuple(tuple(s * di + j for s in range(self.num_slices))
                     for j in range(di))


def slice_topology(mesh, num_slices: int) -> Optional[SliceTopology]:
    """The mesh's :class:`SliceTopology`, or None when single-slice
    (the joint flat psum is the right — and bitwise-pinned — path
    there). Validates the PR-5 hybrid contract: the data axis, and
    only data, spans slices."""
    if num_slices <= 1:
        return None
    data = int(mesh.shape.get(_DATA, 1))
    fsdp = int(mesh.shape.get(_FSDP, 1))
    if data % num_slices:
        raise HierSyncUnsupported(
            f"data axis ({data}) must be divisible by num_slices "
            f"({num_slices}) — the data axis is the only axis that "
            "spans slices (the PR-5 hybrid-mesh contract)")
    for axis in ("model", "context", "pipe"):
        if int(mesh.shape.get(axis, 1)) != 1:
            raise HierSyncUnsupported(
                f"hierarchical DCN sync supports data/fsdp meshes only "
                f"(mesh has {axis}={mesh.shape[axis]}); structural axes "
                "are never touched")
    return SliceTopology(num_slices=num_slices, data=data, fsdp=fsdp)


# ---------------------------------------------------------------------------
# staged reductions (run INSIDE shard_map — they speak axis names)
# ---------------------------------------------------------------------------

def staged_psum(x, topo: SliceTopology):
    """Full-payload psum with the slice-staged fold: fsdp → data-intra
    → data-cross. Numerically the association both DCN_SYNC arms share;
    traffic-wise the FLAT arm — the cross stage carries the full leaf
    over DCN. Used for the flat arm, the loss scalars, and any leaf
    that cannot scatter."""
    if topo.fsdp > 1:
        x = jax.lax.psum(x, _FSDP)
    if topo.data_intra > 1:
        x = jax.lax.psum(x, _DATA,
                         axis_index_groups=[list(g) for g in
                                            topo.intra_groups])
    return jax.lax.psum(x, _DATA,
                        axis_index_groups=[list(g) for g in
                                           topo.cross_groups])


def _scatter_axes(shape: Tuple[int, ...], topo: SliceTopology,
                  dim: Optional[int] = None
                  ) -> Optional[Tuple[int, bool]]:
    """(dim, also_scatter_intra_data): the dim the hier path scatters
    along, or None when no dim tiles the fsdp width (the leaf rides
    the staged full-payload path — replicated scalars/tiny vectors)."""
    dims = range(len(shape)) if dim is None else (dim,)
    for d in dims:
        if topo.fsdp > 1 and shape[d] % topo.fsdp == 0 and shape[d] > 0:
            per = shape[d] // topo.fsdp
            return d, (topo.data_intra > 1
                       and per % topo.data_intra == 0)
        if topo.fsdp == 1 and topo.data_intra > 1 \
                and shape[d] % topo.data_intra == 0 and shape[d] > 0:
            return d, True
    return None


def hier_reduce_full(x, topo: SliceTopology, dim: Optional[int] = None):
    """The hierarchical psum of a full (replicated-result) leaf:
    reduce-scatter over the intra-slice axes → cross-slice all-reduce
    over the scattered shard (DCN pays ``1/ici_size`` of the bytes) →
    all-gather back. Bitwise-identical to :func:`staged_psum` (same
    partials, same order — the scatter only changes WHERE each partial
    lands). Falls back to the staged fold when no dim tiles."""
    plan = _scatter_axes(x.shape, topo, dim)
    if plan is None:
        return staged_psum(x, topo)
    d, scatter_intra = plan
    intra = [list(g) for g in topo.intra_groups]
    cross = [list(g) for g in topo.cross_groups]
    p = x
    if topo.fsdp > 1:
        p = jax.lax.psum_scatter(p, _FSDP, scatter_dimension=d,
                                 tiled=True)
    if topo.data_intra > 1:
        if scatter_intra:
            p = jax.lax.psum_scatter(p, _DATA, scatter_dimension=d,
                                     tiled=True,
                                     axis_index_groups=intra)
        else:
            p = jax.lax.psum(p, _DATA, axis_index_groups=intra)
    p = jax.lax.psum(p, _DATA, axis_index_groups=cross)
    if topo.data_intra > 1 and scatter_intra:
        p = jax.lax.all_gather(p, _DATA, axis=d, tiled=True,
                               axis_index_groups=intra)
    if topo.fsdp > 1:
        p = jax.lax.all_gather(p, _FSDP, axis=d, tiled=True)
    return p


def flat_reduce_shard(ct, topo: SliceTopology, dim: int):
    """FLAT arm, fsdp-sharded leaf: staged full-payload psum of the
    whole cotangent (the cross stage sends the FULL leaf over DCN —
    GSPMD's all-reduce-then-slice traffic shape), then the local fsdp
    shard."""
    full = staged_psum(ct, topo)
    shard = ct.shape[dim] // topo.fsdp
    idx = jax.lax.axis_index(_FSDP) * shard
    return jax.lax.dynamic_slice_in_dim(full, idx, shard, axis=dim)


def hier_reduce_shard(ct, topo: SliceTopology, dim: int):
    """HIER arm, fsdp-sharded leaf: reduce-scatter over fsdp (and the
    slice-local part of data when it tiles) → cross-slice all-reduce
    over the scattered shard — ``1/ici_size`` of the bytes over DCN —
    → gather back only what the local shard needs. Bitwise-identical
    to :func:`flat_reduce_shard` (same staged fold)."""
    intra = [list(g) for g in topo.intra_groups]
    cross = [list(g) for g in topo.cross_groups]
    p = jax.lax.psum_scatter(ct, _FSDP, scatter_dimension=dim,
                             tiled=True)
    scatter_intra = (topo.data_intra > 1
                     and p.shape[dim] % topo.data_intra == 0)
    if topo.data_intra > 1:
        if scatter_intra:
            p = jax.lax.psum_scatter(p, _DATA, scatter_dimension=dim,
                                     tiled=True, axis_index_groups=intra)
        else:
            p = jax.lax.psum(p, _DATA, axis_index_groups=intra)
    p = jax.lax.psum(p, _DATA, axis_index_groups=cross)
    if scatter_intra:
        p = jax.lax.all_gather(p, _DATA, axis=dim, tiled=True,
                               axis_index_groups=intra)
    return p


def intra_reduce_shard(ct, topo: SliceTopology, dim: int):
    """The intra-slice HALF of the sharded-leaf reduction (compressed
    arm): reduce-scatter over fsdp + slice-local data psum, STOPPING
    before the DCN hop — the caller applies
    :func:`compressed_cross_psum` with its error-feedback residual
    after ``value_and_grad`` hands the partial back."""
    p = jax.lax.psum_scatter(ct, _FSDP, scatter_dimension=dim,
                             tiled=True)
    if topo.data_intra > 1:
        p = jax.lax.psum(p, _DATA,
                         axis_index_groups=[list(g) for g in
                                            topo.intra_groups])
    return p


def compressed_cross_psum(p, residual, topo: SliceTopology,
                          compress: str = "bf16"):
    """The compressed DCN hop with error feedback: the intra-slice
    partial (plus the previous microbatch's residual) is cast to the
    compression dtype, summed across slices — HALF the (already
    1/fsdp-scattered) bytes over DCN for bf16 — and the local
    quantization error becomes the next microbatch's residual.
    Replica-consistency: the returned value is a function of the
    cross-slice psum alone, so every slice applies identical gradient
    updates; the residual is slice-local by design (classic EF-SGD).
    Returns ``(reduced, new_residual)``, both f32."""
    if compress != "bf16":
        raise HierSyncUnsupported(
            f"DCN_COMPRESS={compress!r} not supported (only 'bf16')")
    x = p + residual
    q = x.astype(jnp.bfloat16)
    err = x - q.astype(jnp.float32)
    s = jax.lax.psum(q, _DATA,
                     axis_index_groups=[list(g) for g in
                                        topo.cross_groups])
    return s.astype(jnp.float32), err


# ---------------------------------------------------------------------------
# hier_psum: the public custom-vjp composition (registry + tests)
# ---------------------------------------------------------------------------

def hier_psum(x, topo: SliceTopology, *, mode: str = "hier",
              dim: Optional[int] = None):
    """Slice-staged psum of ``x`` over the {data x fsdp} group, as a
    custom-vjp op: ``mode="flat"`` sends the full payload over DCN
    (:func:`staged_psum`), ``mode="hier"`` the scattered shard
    (:func:`hier_reduce_full`) — bitwise-identical values, ``1/ici``
    of the DCN bytes. The VJP passes the cotangent through unchanged
    (each participant's partial contributes linearly to the replicated
    sum) — pinned so AD can never transpose the scatter/gather chain
    into a differently-grouped reduction that costs the bits."""
    if mode not in ("flat", "hier"):
        raise HierSyncUnsupported(f"mode={mode!r} not in ('flat','hier')")

    @jax.custom_vjp
    def red(v):
        if mode == "flat":
            return staged_psum(v, topo)
        return hier_reduce_full(v, topo, dim)

    def fwd(v):
        return red(v), None

    def bwd(_, ct):
        return (ct,)

    red.defvjp(fwd, bwd)
    return red(x)


def leaf_payload_split(shapes: List[Tuple[int, ...]],
                       topo: SliceTopology) -> Tuple[int, int]:
    """(flat_dcn_elems, hier_dcn_elems) a gradient tree of the given
    leaf shapes sends across DCN per reduction — the static arithmetic
    behind the ``dcn_bytes(hier) <= (1/ici_size + eps) x
    dcn_bytes(flat)`` budget pin (tests use it as the oracle)."""
    flat = 0
    hier = 0
    for shape in shapes:
        n = 1
        for s in shape:
            n *= s
        flat += n
        plan = _scatter_axes(shape, topo)
        if plan is None:
            hier += n
        else:
            d, scatter_intra = plan
            denom = topo.fsdp * (topo.data_intra if scatter_intra else 1)
            hier += n // max(denom, 1)
    return flat, hier


def peer_replication_elems(shapes: List[Tuple[int, ...]],
                           num_slices: int) -> int:
    """Elements one peer-replication round (ckpt/peer.py) sends across
    DCN: every slice streams its full state replica to its ring
    neighbor, so the round moves ``num_slices`` x the replica size —
    the static oracle behind the ``peer_dcn_bytes`` budget pin
    (tolerance 0: the live replicator's byte counter must match this
    arithmetic exactly)."""
    per_replica = 0
    for shape in shapes:
        n = 1
        for s in shape:
            n *= s
        per_replica += n
    return max(int(num_slices), 1) * per_replica
