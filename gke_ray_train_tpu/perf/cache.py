"""Persistent compilation cache + AOT train executables.

Two mechanisms make compilation a one-time cost across restarts:

1. **Persistent compilation cache** (:func:`enable_persistent_cache`):
   JAX's file cache pointed at shared storage (``COMPILE_CACHE_DIR``,
   default ``/mnt/pvc/xla_cache``) so every retry/resume — and every
   *other worker* of the slice — reuses the XLA binary instead of
   recompiling (minutes at 8B scale; the MaxText practice for GSPMD
   programs). Entries are namespaced by a topology fingerprint subdir
   so v5e and v5p slices never share a directory; JAX's own cache key
   already encodes the program + platform, the subdir adds operational
   hygiene (per-topology GC, never a correctness mechanism).

2. **AOT executables** (:func:`build_or_load_step`): the train/eval
   step is built ahead-of-time via ``jit(...).lower(...).compile()``
   and serialized (``jax.experimental.serialize_executable``) to a
   sidecar beside the checkpoint. A preempted retry deserializes the
   executable and reaches its first step with **zero retracing** —
   the persistent cache saves compile time, the sidecar saves trace
   + lowering time too.

Both paths are fail-open: an unwritable cache dir falls back to a
local directory (then to disabled), a stale/mismatched sidecar falls
back to the jitted path — a performance layer must never turn a
recoverable restart into a crash.

Gotcha this module owns so callers don't have to: JAX memoizes "is the
cache usable" at the FIRST compile of the process
(``compilation_cache.is_cache_used``). Enabling the cache after any
jit has run silently no-ops unless the check is reset —
:func:`enable_persistent_cache` always resets it.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

logger = logging.getLogger(__name__)

DEFAULT_CACHE_DIR = "/mnt/pvc/xla_cache"
_LOCAL_FALLBACK = os.path.join(
    os.path.expanduser("~"), ".cache", "gke_ray_train_tpu", "xla_cache")

# hit/miss counters fed by jax.monitoring events — the same counters
# the cache-hit tests assert on (ISSUE 4 satellite).
_STATS = {"hits": 0, "misses": 0, "compile_time_saved_s": 0.0,
          "retrieval_s": 0.0}
_LISTENER_INSTALLED = False
_ENABLED_DIR: Optional[str] = None


def _on_event(event: str, **kw) -> None:
    if event.endswith("/cache_hits"):
        _STATS["hits"] += 1
    elif event.endswith("/cache_misses"):
        _STATS["misses"] += 1


def _on_duration(event: str, duration: float, **kw) -> None:
    if event.endswith("/compile_time_saved_sec"):
        _STATS["compile_time_saved_s"] += max(duration, 0.0)
    elif event.endswith("/cache_retrieval_time_sec"):
        _STATS["retrieval_s"] += max(duration, 0.0)


def _install_listener() -> None:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _LISTENER_INSTALLED = True
    except Exception as e:  # noqa: BLE001 - private API; counters stay 0
        logger.warning("compilation-cache counters unavailable (%s: %s)",
                       type(e).__name__, e)


def cache_stats() -> Dict[str, float]:
    """Process-wide persistent-cache counters (hits/misses/seconds)."""
    return dict(_STATS)


def log_cache_summary(log: logging.Logger = logger) -> None:
    """One log line of compile-cache health — what the trainer prints
    at the end of every attempt (hit ratio ~1.0 on a warm restart)."""
    s = cache_stats()
    if _ENABLED_DIR is None:
        log.info("compile cache: disabled")
        return
    log.info(
        "compile cache %s: %d hits / %d misses, %.1fs compile time saved "
        "(retrieval %.2fs)", _ENABLED_DIR, s["hits"], s["misses"],
        s["compile_time_saved_s"], s["retrieval_s"])


def cpu_mesh_env(n_devices: int = 8, **extra: str) -> Dict[str, str]:
    """os.environ copy that forces an ``n_devices`` virtual CPU platform
    in a CHILD process (XLA_FLAGS must land before backend init, hence
    re-exec rather than in-process switching). The one canonical recipe
    shared by the bench's dead-accelerator fallback and the budget CLI —
    keep it here so the two cannot drift."""
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def _backend_initialized() -> bool:
    try:
        from jax._src import xla_bridge
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:  # noqa: BLE001 - private API; absence means unknown
        return False


def topology_fingerprint() -> Tuple[str, Dict[str, Any]]:
    """(short-hash, facts) identifying this process's compile topology.

    Device facts (kind/count) are included only when the backend is
    already up — probing them would *initialize* it, which must not
    happen before ``jax.distributed.initialize`` on multi-host. Before
    backend init the env-derived facts (``TPU_ACCELERATOR_TYPE`` on
    GKE TPU pods, ``JAX_PLATFORMS`` elsewhere) still separate v5e from
    v5p slices.
    """
    import jaxlib

    facts: Dict[str, Any] = {
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", "?"),
        "accelerator_type": os.environ.get("TPU_ACCELERATOR_TYPE", ""),
        "platforms_env": os.environ.get("JAX_PLATFORMS", ""),
    }
    if _backend_initialized():
        devs = jax.devices()
        facts.update(platform=devs[0].platform,
                     device_kind=devs[0].device_kind,
                     n_devices=len(devs),
                     n_processes=jax.process_count())
    digest = hashlib.sha256(
        json.dumps(facts, sort_keys=True).encode()).hexdigest()[:16]
    return digest, facts


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            plan=None,
                            surface: str = "train") -> Optional[str]:
    """Point JAX's persistent compilation cache at shared storage.

    Resolution: explicit arg → ``plan.compile_cache_dir`` →
    ``$COMPILE_CACHE_DIR`` → the PVC default ``/mnt/pvc/xla_cache``;
    the actual cache lives in a topology-fingerprint subdir (suffixed
    with the ExecutionPlan's COMPILE fingerprint when a plan is given —
    the plan identity subsumes the bare topology fingerprint, so two
    runs share a subdir only when both the hardware AND the declared
    compiled program agree; operational knobs like prefetch depth or a
    guard do not split the cache).
    ``COMPILE_CACHE=0`` (or ``plan.compile_cache=False``) disables.
    Unwritable dirs fall back to ``~/.cache/gke_ray_train_tpu`` and
    then to disabled — never raise.

    Safe to call more than once: the entry scripts re-enable after
    ``distributed_init`` so the fingerprint gains real device facts;
    a repeat call that resolves to the current dir is a no-op.

    Returns the resolved cache dir, or None when disabled.
    """
    global _ENABLED_DIR
    if plan is not None and not plan.compile_cache:
        logger.info("compile cache disabled by the execution plan "
                    "(COMPILE_CACHE=0)")
        return None
    if os.environ.get("COMPILE_CACHE", "1").lower() in ("0", "false"):
        logger.info("compile cache disabled via COMPILE_CACHE=0")
        return None
    base = cache_dir \
        or (plan.compile_cache_dir if plan is not None else None) \
        or os.environ.get("COMPILE_CACHE_DIR", DEFAULT_CACHE_DIR)
    digest, facts = topology_fingerprint()
    if plan is not None:
        # per-surface compile identity (plan.py): a serving replica's
        # cache subdir is keyed on the serve fields, a trainer's on the
        # train fields — retuning one surface's knobs never cold-starts
        # the other's cache
        digest = f"{digest}-{plan.compile_fingerprint(surface)[:8]}"
    resolved = None
    for candidate in (os.path.join(base, digest),
                      os.path.join(_LOCAL_FALLBACK, digest)):
        try:
            os.makedirs(candidate, exist_ok=True)
            probe = os.path.join(candidate, ".writable")
            with open(probe, "w") as f:
                f.write("1")
            os.remove(probe)
            resolved = candidate
            break
        except OSError as e:
            logger.warning("compile cache dir %s unusable (%s); %s",
                           candidate, e,
                           "falling back to local cache"
                           if candidate.startswith(base) else "disabling")
    if resolved is None:
        return None
    if resolved == _ENABLED_DIR:
        return resolved

    jax.config.update("jax_compilation_cache_dir", resolved)
    # persist everything: the whole point is that the NEXT process
    # skips the compile, so entry-size/compile-time floors are off
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(os.environ.get("COMPILE_CACHE_MIN_COMPILE_S",
                                           "0")))
    _install_listener()
    try:
        # un-memoize is_cache_used: any compile that already ran this
        # process (state init, a probe) froze the "no cache dir"
        # verdict; without this reset, late enabling silently no-ops
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except Exception as e:  # noqa: BLE001 - private API drift
        logger.warning("compilation_cache.reset_cache unavailable (%s); "
                       "cache may stay off if jit already ran", e)
    _ENABLED_DIR = resolved
    logger.info("persistent compile cache at %s (topology %s)",
                resolved, facts.get("device_kind")
                or facts.get("accelerator_type") or "pre-init")
    return resolved


# ---------------------------------------------------------------------------
# AOT executables: serialize beside the checkpoint, deserialize on retry
# ---------------------------------------------------------------------------

def aot_enabled(config: Optional[dict] = None) -> bool:
    """Legacy parse of the AOT_TRAIN_STEP knob (config key wins over
    env; default on). The entry scripts now read it from
    ``ExecutionPlan.aot_train_step`` (plan.py) via
    ``compile_step_with_plan`` — kept for ad-hoc callers."""
    if config is not None and "AOT_TRAIN_STEP" in config:
        raw = config["AOT_TRAIN_STEP"]
    else:
        raw = os.environ.get("AOT_TRAIN_STEP", "1")
    return str(raw).lower() not in ("0", "false")


def make_abstract_batch(mesh, n_rows: int, seq_len: int, *,
                        packed: bool = False,
                        context_sharded: bool = False) -> Dict[str, Any]:
    """The abstract [n_rows, seq_len] batch both entry scripts lower
    against: inputs/targets int32 + weights float32 (+ segment_ids/
    positions int32 when packed), sharded per the train step's
    batch_shardings contract."""
    import jax.numpy as jnp

    from gke_ray_train_tpu.train.step import batch_shardings
    keys = ("inputs", "targets", "weights") + (
        ("segment_ids", "positions") if packed else ())
    shard = batch_shardings(mesh, keys, context_sharded=context_sharded)
    return {
        k: jax.ShapeDtypeStruct(
            (n_rows, seq_len),
            jnp.float32 if k == "weights" else jnp.int32,
            sharding=shard[k])
        for k in keys}


def _leaf_signature(leaf: Any) -> tuple:
    shape = tuple(getattr(leaf, "shape", ()))
    dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return (shape, dtype, repr(spec) if spec is not None else None)


def aot_signature(*args_trees: Any, plan=None,
                  surface: str = "train") -> str:
    """Digest of the abstract input signature (treedef + per-leaf
    shape/dtype/partition-spec) + topology fingerprint + (when given)
    the ExecutionPlan's per-``surface`` COMPILE fingerprint — the
    validity key of a serialized executable. A sidecar whose key
    mismatches is stale (different mesh, model size, batch layout,
    chip, or a plan that compiles a different program on THIS surface)
    and is ignored rather than loaded; operational plan knobs — and
    the other surface's fields — deliberately do NOT invalidate it."""
    leaves, treedef = jax.tree.flatten(args_trees)
    payload = (topology_fingerprint()[0],
               plan.compile_fingerprint(surface)
               if plan is not None else None,
               str(treedef),
               [_leaf_signature(x) for x in leaves])
    return hashlib.sha256(repr(payload).encode()).hexdigest()


def abstractify(tree: Any) -> Any:
    """Concrete pytree → ShapeDtypeStruct pytree, shardings preserved —
    the abstract-argument form ``jit(...).lower`` wants for AOT."""
    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    return jax.tree.map(leaf, tree)


def save_executable(compiled, path: str, key: str) -> bool:
    """Serialize an AOT-compiled executable (atomic write). Best-effort:
    returns False instead of raising — persistence failures must not
    kill a training step that already compiled fine."""
    try:
        from jax.experimental import serialize_executable
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        blob = pickle.dumps({"key": key, "payload": payload,
                             "in_tree": in_tree, "out_tree": out_tree})
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return True
    except Exception as e:  # noqa: BLE001 - persistence is best-effort
        logger.warning("AOT executable serialize to %s failed (%s: %s)",
                       path, type(e).__name__, e)
        return False


def load_executable(path: str, key: str):
    """Deserialize a sidecar executable; None when missing, stale
    (key mismatch) or undeserializable — callers fall back to compile."""
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            blob = pickle.load(f)
        if blob.get("key") != key:
            logger.info("AOT sidecar %s is stale (topology/signature "
                        "changed); recompiling", path)
            return None
        from jax.experimental import serialize_executable
        return serialize_executable.deserialize_and_load(
            blob["payload"], blob["in_tree"], blob["out_tree"])
    except Exception as e:  # noqa: BLE001 - fall back to compile
        logger.warning("AOT sidecar %s unusable (%s: %s); recompiling",
                       path, type(e).__name__, e)
        return None


class GuardedStep:
    """AOT executable with a jit fallback.

    Calls the pre-compiled executable; if a call ever fails (an input
    whose layout drifted from the recorded signature), it logs ONCE and
    permanently falls back to the jitted function — a stale sidecar
    costs one retrace, never a crash. ``info`` records the build source
    ("deserialized" | "compiled") and seconds, for the loop's
    compile-time metrics.
    """

    def __init__(self, compiled, jitted_fn: Callable, info: Dict[str, Any]):
        self._compiled = compiled
        self._jitted = jitted_fn
        self.info = info
        self._fell_back = compiled is None

    def __call__(self, *args):
        if not self._fell_back:
            try:
                return self._compiled(*args)
            except Exception as e:  # noqa: BLE001 - classified below
                # only an input-signature rejection is retryable: it
                # raises at dispatch, BEFORE any donated buffer is
                # handed to the runtime. A failure mid-execution (OOM,
                # runtime error) may have consumed donated args —
                # retrying would die with a misleading "Array has been
                # deleted" burying the real error, so re-raise it.
                if any(getattr(x, "is_deleted", lambda: False)()
                       for x in jax.tree.leaves(args)):
                    raise
                self._fell_back = True
                logger.warning(
                    "AOT executable rejected the call (%s: %s); falling "
                    "back to the jitted step (one retrace)",
                    type(e).__name__, e)
        return self._jitted(*args)

    def lower(self, *args, **kw):  # pragma: no cover - passthrough
        return self._jitted.lower(*args, **kw)


def _note_cost_report(compiled, plan) -> None:
    """Feed the obs network gauges (grt_ici_bytes / grt_dcn_bytes)
    from the StepCostReport of the executable this build already
    produced — only when a telemetry session is active (the HLO parse
    is not free), and never fatally (telemetry must not kill a
    build)."""
    from gke_ray_train_tpu.obs import runtime as obs_runtime
    if obs_runtime.active() is None:
        return
    try:
        from gke_ray_train_tpu.perf.costs import step_cost_report
        ns = getattr(plan, "num_slices", None) if plan is not None \
            else None
        obs_runtime.note_cost_report(
            step_cost_report(compiled, num_slices=ns))
    except Exception as e:  # noqa: BLE001 - telemetry is best-effort
        logger.warning("obs cost-report note skipped: %s", e)


def build_or_load_step(jitted_fn: Callable, *abstract_args: Any,
                       sidecar: Optional[str] = None,
                       label: str = "train_step",
                       plan=None, surface: str = "train") -> GuardedStep:
    """AOT-build a jitted step (or deserialize its sidecar) and return a
    :class:`GuardedStep`.

    - sidecar present + key matches → deserialize (no trace, no
      compile); a preempted retry reaches its first step in the time it
      takes to read the file.
    - otherwise → ``lower(*abstract_args).compile()`` (the compile
      itself hits the persistent cache when warm) and, when ``sidecar``
      is set, serialize for the next restart. Only process 0 writes —
      every host of a slice lowers the same program and the sidecar
      lives on shared storage.
    """
    args = tuple(abstractify(a) for a in abstract_args)
    key = aot_signature(*args, plan=plan, surface=surface)
    info: Dict[str, Any] = {"label": label, "sidecar": sidecar}
    if plan is not None:
        info["plan_fingerprint"] = plan.fingerprint()
    if sidecar:
        t0 = time.perf_counter()
        loaded = load_executable(sidecar, key)
        if loaded is not None:
            info.update(source="deserialized",
                        build_s=time.perf_counter() - t0)
            logger.info("%s: deserialized AOT executable in %.2fs (%s)",
                        label, info["build_s"], sidecar)
            # a warm-restart attempt must feed the obs network gauges
            # too — the note guards internally against a deserialized
            # executable that cannot re-serve its analyses
            _note_cost_report(loaded, plan)
            return GuardedStep(loaded, jitted_fn, info)
    t0 = time.perf_counter()
    try:
        compiled = jitted_fn.lower(*args).compile()
    except Exception as e:  # noqa: BLE001 - abstract args may mismatch
        logger.warning("%s: AOT build failed (%s: %s); using the plain "
                       "jitted step", label, type(e).__name__, e)
        info.update(source="jit-fallback", build_s=0.0)
        return GuardedStep(None, jitted_fn, info)
    info.update(source="compiled", build_s=time.perf_counter() - t0)
    logger.info("%s: AOT compiled in %.2fs", label, info["build_s"])
    _note_cost_report(compiled, plan)
    if sidecar:
        is_writer = True
        if _backend_initialized():
            try:
                is_writer = jax.process_index() == 0
            except Exception:  # noqa: BLE001
                pass
        if is_writer:
            t0 = time.perf_counter()
            if save_executable(compiled, sidecar, key):
                # validate the round-trip NOW: a compile that was itself
                # a persistent-cache hit can serialize to a blob the
                # backend refuses to deserialize (observed on XLA:CPU,
                # "Symbols not found") — a sidecar that will fail every
                # future restart must not be left behind
                if load_executable(sidecar, key) is None:
                    try:
                        os.remove(sidecar)
                    except OSError:
                        pass
                    logger.info(
                        "%s: sidecar failed its deserialize check; "
                        "removed (restarts will use the persistent "
                        "compile cache instead)", label)
                else:
                    info["serialize_s"] = time.perf_counter() - t0
                    logger.info(
                        "%s: AOT executable persisted to %s (%.2fs)",
                        label, sidecar, info["serialize_s"])
    return GuardedStep(compiled, jitted_fn, info)
