"""The two-sided budget comparator core — stdlib-only (ISSUE 14).

Extracted from ``perf/budget.py`` (which re-exports it; one
implementation, zero forks) so consumers that must run without jax can
reuse it: ``perf/budget.py`` pulls in ``perf/costs.py`` → jax at
import, but the comparison itself is pure float/dict work.
``obs/diff.py`` — the cross-run regression gate over telemetry reports
— is exactly such a consumer: runtime goodput/latency numbers are
gated by the SAME comparator shape (two-sided relative tolerances,
per-field overrides recorded in the checked-in JSON, offending-term
delta printed on a trip) that already gates HLO cost numbers.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional


def rel_diff(a: float, b: float) -> float:
    if b == 0:
        return 0.0 if a == 0 else float("inf")
    return abs(a - b) / abs(b)


def hlo_delta(have_lines: List[str], want_lines: List[str],
              cap: int = 8) -> List[str]:
    """The offending HLO delta: collective lines present on one side
    only (multiset diff, op names normalized away so textual id drift
    between compiles does not flood the report)."""
    def norm(line):
        return re.sub(r"%[\w.\-]+", "%_", line)

    have = [norm(x) for x in have_lines]
    want = [norm(x) for x in want_lines]
    out: List[str] = []
    added = list(have)
    for w in want:
        if w in added:
            added.remove(w)
    removed = list(want)
    for h in have:
        if h in removed:
            removed.remove(h)
    for tag, lines in (("+", added), ("-", removed)):
        for ln in lines[:cap]:
            out.append(f"  HLO {tag} {ln}")
        if len(lines) > cap:
            out.append(f"  HLO {tag} ... {len(lines) - cap} more")
    return out


def compare_dicts(report: Dict[str, Any], budget: Dict[str, Any],
                  tolerances: Optional[Dict[str, float]] = None, *,
                  default_tolerances: Optional[Dict[str, float]] = None,
                  collective_kinds: Optional[Iterable[str]] = None
                  ) -> List[str]:
    """Violation strings (empty = within budget). Scalar fields use
    two-sided relative tolerances (``default_tolerances`` overlaid by
    the budget's own ``tolerances`` key overlaid by the argument);
    collective counts — when both sides carry them — are exact, and a
    count mismatch carries the HLO-line delta so the offending op is
    named, not just counted."""
    tol = dict(default_tolerances or {})
    tol.update(budget.get("tolerances", {}))
    tol.update(tolerances or {})
    viols: List[str] = []
    overlap_tripped = False
    dcn_tripped = False
    for field, t in tol.items():
        if field not in budget or field not in report:
            continue
        have, want = float(report[field]), float(budget[field])
        d = rel_diff(have, want)
        if d > t:
            viols.append(
                f"{field}: {have:.4g} vs budget {want:.4g} "
                f"({'+' if have > want else '-'}{d:.1%}, tolerance "
                f"{t:.0%})")
            if field in ("exposed_collective_bytes", "overlap_frac"):
                overlap_tripped = True
            if field == "dcn_bytes":
                dcn_tripped = True
    if overlap_tripped:
        # the offending schedule region: which collectives changed
        # exposure state (hidden <-> EXPOSED) or appeared/vanished
        viols.extend(hlo_delta(report.get("exposure_lines", []),
                               budget.get("exposure_lines", [])))
    if dcn_tripped:
        # which collectives changed their slice-crossing byte load —
        # the reshard-fattened-the-DCN-hop signal, named per op
        viols.extend(hlo_delta(report.get("dcn_lines", []),
                               budget.get("dcn_lines", [])))

    want_counts = budget.get("collective_counts")
    if want_counts is not None:
        have_counts = report.get("collective_counts", {})
        kinds = (list(collective_kinds) if collective_kinds is not None
                 else sorted(set(have_counts) | set(want_counts)))
        mismatched = [
            k for k in kinds
            if int(have_counts.get(k, 0)) != int(want_counts.get(k, 0))]
        if mismatched:
            detail = ", ".join(
                f"{k}: {have_counts.get(k, 0)} vs budget "
                f"{want_counts.get(k, 0)}" for k in mismatched)
            viols.append(f"collective counts changed ({detail})")
            viols.extend(hlo_delta(report.get("collective_lines", []),
                                   budget.get("collective_lines", [])))
    return viols
