"""Compile-once performance layer (VERDICT r5: hardware-independent
compile-level guarantees).

Three modules, one goal — compilation is a one-time cost and per-step
cost/memory/collective footprints are asserted quantities:

- :mod:`cache` — JAX persistent compilation cache on shared storage
  (``COMPILE_CACHE_DIR``) with topology hygiene, plus AOT
  ``jit(...).lower(...).compile()`` builds persisted beside the
  checkpoint so a preempted retry deserializes the executable instead
  of retracing.
- :mod:`costs` — ``StepCostReport``: flops/step, HBM bytes, peak
  temp/argument/output memory, collective count & bytes, analytic MFU
  ceiling — all computed from the AOT lowering, no accelerator needed.
- :mod:`budget` — checked-in per-preset budget JSONs + a comparator
  with tolerances; a budget miss (remat silently off, an extra
  all-reduce in the grad path, peak-memory growth) fails tier-1 tests
  and prints the offending HLO delta.
"""

from gke_ray_train_tpu.perf.cache import (  # noqa: F401
    aot_signature, build_or_load_step, cache_stats, enable_persistent_cache,
    load_executable, log_cache_summary, save_executable,
    topology_fingerprint)
from gke_ray_train_tpu.perf.costs import (  # noqa: F401
    ChipSpec, StepCostReport, chip_spec_for_devices, collective_stats,
    step_cost_report)

# perf.budget is NOT imported eagerly: it doubles as the re-baseline CLI
# (`python -m gke_ray_train_tpu.perf.budget`), and runpy warns when the
# target module was already materialized by its package __init__

