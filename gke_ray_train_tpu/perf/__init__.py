"""Compile-once performance layer (VERDICT r5: hardware-independent
compile-level guarantees).

Four modules, one goal — compilation is a one-time cost and per-step
cost/memory/collective footprints are asserted quantities:

- :mod:`cache` — JAX persistent compilation cache on shared storage
  (``COMPILE_CACHE_DIR``) with topology hygiene, plus AOT
  ``jit(...).lower(...).compile()`` builds persisted beside the
  checkpoint so a preempted retry deserializes the executable instead
  of retracing.
- :mod:`costs` — ``StepCostReport``: flops/step, HBM bytes, peak
  temp/argument/output memory, collective count & bytes, analytic MFU
  ceiling — all computed from the AOT lowering, no accelerator needed.
- :mod:`budget` — checked-in per-preset budget JSONs + a comparator
  with tolerances; a budget miss (remat silently off, an extra
  all-reduce in the grad path, peak-memory growth) fails tier-1 tests
  and prints the offending HLO delta.
- :mod:`compare` — the stdlib-only comparator core budget.py binds its
  defaults to; ``obs/diff.py`` (the cross-run telemetry regression
  gate) reuses it on machines with no jax.

The package re-exports are LAZY (PEP 562): ``perf.cache``/``perf.costs``
import jax at module level, but ``perf.compare`` must stay importable
from the jax-free obs CLI path — materializing this ``__init__`` must
not drag the backend in. (``perf.budget`` additionally stays un-imported
here because it doubles as a ``python -m`` CLI and runpy warns when the
target was already materialized by its package init.)
"""

_LAZY_EXPORTS = {
    # cache
    "aot_signature": "cache", "build_or_load_step": "cache",
    "cache_stats": "cache", "enable_persistent_cache": "cache",
    "load_executable": "cache", "log_cache_summary": "cache",
    "save_executable": "cache", "topology_fingerprint": "cache",
    # costs
    "ChipSpec": "costs", "StepCostReport": "costs",
    "chip_spec_for_devices": "costs", "collective_stats": "costs",
    "step_cost_report": "costs",
}

__all__ = sorted(_LAZY_EXPORTS)


def __getattr__(name):
    mod = _LAZY_EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)
