"""Per-preset compile-cost budgets — regression gates, not benchmarks.

A budget JSON (checked in under ``tests/budgets/``) pins the
:class:`~gke_ray_train_tpu.perf.costs.StepCostReport` of a named preset
(model + mesh + batch shape) as recorded on the 8-fake-device CPU mesh —
the same mesh tier-1 CI runs on, so the comparator needs no hardware.
The comparator flags, with tolerances:

- **flops / bytes drift** (two-sided: a remat policy silently turning
  OFF *drops* flops while blowing up peak memory);
- **peak temp-memory growth** (the remat / activation-liveness signal);
- **any change in collective count by kind** — an extra all-reduce in
  the grad path is exactly the class of silent perf bug GSPMD can
  introduce; the violation message prints the offending HLO lines
  (the delta against the lines recorded in the budget).

Re-baselining after an INTENTIONAL change:
``python -m gke_ray_train_tpu.perf.budget record`` rewrites the files
(it re-execs itself onto the canonical CPU mesh), or run the tier-1
budget test with ``BUDGET_UPDATE=1``. Review the JSON diff like code —
that diff *is* the perf review.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Optional, Union

from gke_ray_train_tpu.perf.compare import compare_dicts, hlo_delta
from gke_ray_train_tpu.perf.costs import (
    COLLECTIVE_KINDS, StepCostReport, step_cost_report)

# two-sided relative tolerances; collective COUNTS are exact by design.
# exposed_collective_bytes / overlap_frac are the overlap-analysis
# fields (perf.costs.overlap_stats): a pinned 0 stays exactly 0 under
# relative tolerances, so the first collective a schedule EXPOSES (or
# the first one it newly hides) is a budget event, not drift noise —
# the asserted metric the ROADMAP #3 overlap work moves.
DEFAULT_TOLERANCES = {
    "flops": 0.05,
    "bytes_accessed": 0.25,
    "temp_bytes": 0.25,
    "argument_bytes": 0.05,
    "output_bytes": 0.05,
    "collective_bytes": 0.25,
    "exposed_collective_bytes": 0.25,
    "overlap_frac": 0.05,
    # network attribution (perf.costs.collective_axis_stats): dcn_bytes
    # is deliberately TIGHT — the cross-slice hop is the number the
    # hierarchical sync exists to shrink, and a reshard that silently
    # fattens it by 10% is exactly the regression the hybrid budgets
    # gate (a pinned 0 stays exactly 0 on single-slice presets)
    "ici_bytes": 0.25,
    "dcn_bytes": 0.10,
    # peer hot-state replication (ckpt/peer.py): bytes ONE snapshot's
    # replication round streams across DCN on a hybrid preset. EXACT —
    # the number is a pure function of the train-state tree (shapes x
    # dtypes x num_slices, via jax.eval_shape), so any drift means the
    # replicated tree itself changed and the pin must be re-reviewed
    "peer_dcn_bytes": 0.0,
    # serve-preset modeled latency/throughput (serve_modeled_fields):
    # deterministic functions of the compile analyses + the declared
    # ChipSpec, so the same relative band as flops applies — a decode
    # step that got 10% more expensive moves p50 by the same 10%
    "serve_tenant_p50_s": 0.05,
    "serve_tenant_p99_s": 0.05,
    "serve_tokens_per_s_per_chip": 0.05,
}

BUDGET_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "budgets")


class BudgetViolation(AssertionError):
    """A compiled step broke its checked-in cost/memory budget."""


def load_budget(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def write_budget(report: Union[StepCostReport, Dict[str, Any]], path: str,
                 *, preset: str = "", note: str = "",
                 plan=None) -> Dict[str, Any]:
    if isinstance(report, StepCostReport):
        report = report.to_dict()
    import jax
    if plan is None and (preset in PRESETS or preset in SERVE_PRESETS):
        plan = plan_for_preset(preset)
    doc = {
        "_preset": preset,
        "_note": note or ("re-baseline with: python -m "
                          "gke_ray_train_tpu.perf.budget record"),
        # the ExecutionPlan identity this budget was recorded under
        # (plan.py): plancheck PLAN004 fails the build when the preset
        # plan no longer resolves to this fingerprint (stale budget)
        "_plan_fingerprint": plan.fingerprint() if plan is not None
        else None,
        "_recorded_with": {"jax": jax.__version__,
                           "platform": jax.devices()[0].platform,
                           "n_devices": len(jax.devices())},
        **{k: v for k, v in report.items() if not k.startswith("_")},
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def compare_to_budget(report: Union[StepCostReport, Dict[str, Any]],
                      budget: Dict[str, Any],
                      tolerances: Optional[Dict[str, float]] = None
                      ) -> List[str]:
    """Violation strings (empty = within budget) — the stdlib-only
    comparator core (``perf/compare.py``; ``obs diff`` reuses it over
    telemetry reports) bound to this module's cost-report defaults:
    :data:`DEFAULT_TOLERANCES` and exact per-kind collective counts."""
    if isinstance(report, StepCostReport):
        report = report.to_dict()
    return compare_dicts(report, budget, tolerances,
                         default_tolerances=DEFAULT_TOLERANCES,
                         collective_kinds=COLLECTIVE_KINDS)


# jaxprcheck (and older call sites) import the delta printer from here
_hlo_delta = hlo_delta


def assert_within_budget(report: Union[StepCostReport, Dict[str, Any]],
                         budget_path: str, *, plan=None, **kw) -> None:
    """Raise :class:`BudgetViolation` on any comparator finding. The
    failure names the preset AND the plan fingerprint the budget was
    recorded under (plus the current plan's, when given) — a mismatched
    budget used to print only HLO deltas, leaving WHICH declared plan
    drifted to archaeology."""
    budget = load_budget(budget_path)
    viols = compare_to_budget(report, budget, **kw)
    if viols:
        preset = budget.get("_preset") or os.path.splitext(
            os.path.basename(budget_path))[0]
        recorded_fp = budget.get("_plan_fingerprint") or "<unrecorded>"
        ident = f"preset {preset!r} (recorded under plan {recorded_fp}"
        if plan is not None:
            ident += f"; current plan {plan.fingerprint()}"
        ident += ")"
        raise BudgetViolation(
            f"compiled step broke the budget {budget_path} — {ident}:"
            "\n  " + "\n  ".join(viols)
            + "\nIf the change is INTENTIONAL, re-baseline: python -m "
              "gke_ray_train_tpu.perf.budget record")


# ---------------------------------------------------------------------------
# Presets — the shapes whose budgets are checked in under tests/budgets/
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Preset:
    name: str
    mesh: Dict[str, int]
    batch: int = 8
    seq: int = 64
    remat: bool = True
    # the overlap mode the budget measures (plan.OVERLAP_MODES): train
    # presets pin the manual shard_map pipeline — the overlap_frac /
    # exposed_collective_bytes numbers ROADMAP #3 moves live here
    overlap: str = "manual"
    # DCN topology + cross-slice sync arm (parallel/hierarchical.py):
    # hybrid presets emulate num_slices>1 on the fake-8 mesh and pin
    # ici_bytes/dcn_bytes per DCN_SYNC arm — the budgeted claim that
    # hier sends 1/ici_size of flat's bytes over the slow link
    num_slices: int = 1
    dcn_sync: str = "flat"


PRESETS = {
    # fsdp grad path: the per-layer weight all-gathers are the overlap
    # target — double-buffered behind compute by the manual pipeline
    "tiny_fsdp8": Preset("tiny_fsdp8", {"data": 2, "fsdp": 4}),
    # pure data-parallel grad path: the classic gradient all-reduce
    # (no param gathers to hide — the manual path pins the same
    # program shape so the two presets stay comparable)
    "tiny_dp8": Preset("tiny_dp8", {"data": 8, "fsdp": 1}),
    # emulated 2-slice hybrid mesh (2 data x 4 fsdp, data spans the
    # slices — the PR-5 contract, fake-8 emulation pinned in
    # test_mesh.py): the flat arm's budget pins the full gradient
    # payload crossing DCN, the hier arm pins the 1/ici_size scattered
    # hop — the pair IS the recorded evidence for the DCN_SYNC claim,
    # and test_dcn.py asserts the ratio between the two JSONs
    "tiny_hybrid_2x4_flat": Preset(
        "tiny_hybrid_2x4_flat", {"data": 2, "fsdp": 4},
        num_slices=2, dcn_sync="flat"),
    "tiny_hybrid_2x4_hier": Preset(
        "tiny_hybrid_2x4_hier", {"data": 2, "fsdp": 4},
        num_slices=2, dcn_sync="hier"),
}


@dataclasses.dataclass(frozen=True)
class ServePreset:
    """A serving-decode budget shape: the ``[max_batch, 1]`` continuous-
    batching decode step of ``serve/engine.py`` at one bucket width.
    Mesh-local by design (a serving replica's decode carries no
    collectives — an all-gather showing up here IS the regression the
    exact-count check exists to catch)."""
    name: str
    max_batch: int = 8
    bucket: int = 128
    quant: str = "none"
    # multi-tenant shape (ISSUE 17): n_adapters > 0 budgets the POOLED
    # decode — the batched-LoRA gather+BGMV path over an AdapterPool of
    # n_adapters tenant slots (+ the reserved zero slot), the one
    # executable every mixed-tenant batch shares
    n_adapters: int = 0
    lora_r: int = 4


SERVE_PRESETS = {
    "serve_tiny8": ServePreset("serve_tiny8"),
    # the multi-tenant arm: same model/bucket as serve_tiny8, decode
    # compiled WITH the stacked adapter pool — the flops/bytes delta
    # between the two JSONs is the recorded cost of multi-LoRA, and the
    # zero-collective pin still holds (the gather is mesh-local)
    "serve_multilora8": ServePreset("serve_multilora8", n_adapters=8),
}


def all_preset_names() -> List[str]:
    """Every budget-bearing preset (train + serve) — the CLI default
    and the repo-level PLAN004 sweep iterate exactly this list."""
    return sorted(PRESETS) + sorted(SERVE_PRESETS)


def _serve_model_cfg(p: ServePreset):
    """The deterministic tiny model a serve preset decodes (same dims
    the train presets use, max_seq_len = the bucket width)."""
    from gke_ray_train_tpu.models import tiny
    return tiny(d_model=64, n_layers=2, n_heads=2, n_kv_heads=2,
                d_ff=128, vocab_size=256, max_seq_len=p.bucket,
                remat=False)


def plan_for_serve_preset(preset: Union[str, ServePreset]):
    """The serving ExecutionPlan a serve budget measures under — one
    plan fingerprint shared by the budget JSON, plancheck PLAN004 and
    ``analysis check`` (mirror of :func:`plan_for_preset`)."""
    from gke_ray_train_tpu.plan import ExecutionPlan
    p = SERVE_PRESETS[preset] if isinstance(preset, str) else preset
    return ExecutionPlan.from_kwargs(
        data=1, fsdp=1, max_seq_len=p.bucket,
        max_batch=p.max_batch, decode_buckets=str(p.bucket),
        serve_quant=p.quant, max_adapters=(p.n_adapters or 8),
        donate_state=False, donate_batch=False, prefetch=0,
        compile_cache=False, aot_train_step=False,
        topology="cpu-8", budget_preset=p.name)


def build_serve_preset_step(preset: Union[str, ServePreset], *,
                            with_jitted: bool = False):
    """(compiled_decode, params, serve_state) for a serve preset — the
    deterministic decode compile whose StepCostReport the budget pins.
    ``with_jitted`` additionally returns the jitted (un-AOT) decode fn
    and the lora argument it was lowered with (the stacked pool blocks
    on a multi-adapter preset, else None) for the analysis
    compile-once probe."""
    import jax

    from gke_ray_train_tpu.models import init_params
    from gke_ray_train_tpu.ops.quant import quantize_for_serving
    from gke_ray_train_tpu.serve.engine import (
        init_serve_state, make_decode_fn)

    p = SERVE_PRESETS[preset] if isinstance(preset, str) else preset
    cfg = _serve_model_cfg(p)
    params = quantize_for_serving(init_params(cfg, jax.random.key(0)),
                                  p.quant)
    if p.n_adapters:
        # pooled decode: the state carries per-slot adapter indices and
        # the lora argument is the stacked pool — the ONE executable a
        # mixed-tenant batch runs regardless of which tenants are in it
        from gke_ray_train_tpu.serve.adapters import AdapterPool
        from gke_ray_train_tpu.train.lora import LoraConfig, init_lora
        template = init_lora(cfg, LoraConfig(r=p.lora_r),
                             jax.random.key(1))
        pool = AdapterPool(template, max_adapters=p.n_adapters)
        state = init_serve_state(cfg, p.max_batch, p.bucket,
                                 multi_lora=True)
        jitted = jax.jit(make_decode_fn(cfg, eos_ids=(), pool=True),
                         donate_argnums=(1,))
        compiled = jitted.lower(params, state, pool.blocks).compile()
        if with_jitted:
            return compiled, params, state, jitted, pool.blocks
        return compiled, params, state
    state = init_serve_state(cfg, p.max_batch, p.bucket)
    jitted = jax.jit(make_decode_fn(cfg, eos_ids=()), donate_argnums=(1,))
    compiled = jitted.lower(params, state, None).compile()
    if with_jitted:
        return compiled, params, state, jitted, None
    return compiled, params, state


def serve_modeled_fields(preset: Union[str, ServePreset],
                         decode_report: StepCostReport
                         ) -> Dict[str, float]:
    """Modeled per-tenant latency/throughput for a serve preset —
    deterministic functions of the compile analyses, so they gate in CI
    with no wall clock (the ``autotune/score.py`` roofline model at the
    plan's declared ChipSpec):

    - ``serve_tenant_p50_s``: one decode iteration — the steady-state
      per-token latency every resident tenant sees (continuous batching
      emits one token per slot per iteration);
    - ``serve_tenant_p99_s``: decode iteration + one full-bucket
      prefill — the tail where a token waits behind a refill admission
      stalling the shared batch;
    - ``serve_tokens_per_s_per_chip``: max_batch tokens per modeled
      iteration, over the plan's chip count.
    """
    from gke_ray_train_tpu.autotune.score import (
        chip_for_plan, modeled_step_time)

    p = SERVE_PRESETS[preset] if isinstance(preset, str) else preset
    plan = plan_for_serve_preset(p)
    chip = chip_for_plan(plan)
    t_decode = modeled_step_time(decode_report, chip)["modeled_step_s"]
    t_prefill = modeled_step_time(_serve_prefill_report(p),
                                  chip)["modeled_step_s"]
    return {
        "serve_tenant_p50_s": t_decode,
        "serve_tenant_p99_s": t_decode + t_prefill,
        "serve_tokens_per_s_per_chip":
            p.max_batch / t_decode / max(plan.chips, 1),
    }


def _serve_prefill_report(p: ServePreset) -> StepCostReport:
    """Cost report of the preset's [1, bucket] prefill — the refill
    executable whose modeled time is the p99 stall term."""
    import jax
    import jax.numpy as jnp

    from gke_ray_train_tpu.models import init_params
    from gke_ray_train_tpu.ops.quant import quantize_for_serving
    from gke_ray_train_tpu.serve.engine import make_prefill_fn

    cfg = _serve_model_cfg(p)
    params = quantize_for_serving(init_params(cfg, jax.random.key(0)),
                                  p.quant)
    prompt = jnp.zeros((1, p.bucket), jnp.int32)
    plen = jnp.ones((1,), jnp.int32)
    compiled = jax.jit(make_prefill_fn(cfg)).lower(
        params, prompt, plen, None).compile()
    return step_cost_report(compiled, tokens_per_step=p.bucket)


def build_budget_doc(preset: Union[str, Preset, ServePreset],
                     *, remat=None) -> Dict[str, Any]:
    """The full dict a budget records/checks: the StepCostReport plus,
    on serve presets, the modeled per-tenant fields — the one builder
    the CLI and the tier-1 budget tests share, so the recorded and the
    checked documents can never diverge in shape."""
    report = build_preset_report(preset, remat=remat)
    doc = report.to_dict()
    name = preset if isinstance(preset, str) else preset.name
    if isinstance(preset, ServePreset) or name in SERVE_PRESETS:
        doc.update(serve_modeled_fields(preset, report))
    else:
        p = PRESETS[name] if isinstance(preset, str) else preset
        if p.num_slices > 1:
            doc["peer_dcn_bytes"] = peer_replication_bytes(p)
    return doc


def peer_replication_bytes(preset: Union[str, Preset]) -> int:
    """DCN bytes ONE peer hot-state replication round moves on a hybrid
    preset (``ckpt/peer.py``: every slice streams its full state replica
    to its ring neighbor). Computed from the ABSTRACT train-state tree —
    ``jax.eval_shape`` over the same model/optimizer the preset budgets,
    no arrays materialized — so recording it costs no device memory and
    the live replicator counter can be pinned against it exactly."""
    import jax

    from gke_ray_train_tpu.ckpt.peer import round_dcn_bytes
    from gke_ray_train_tpu.train import make_optimizer, make_train_state

    p = PRESETS[preset] if isinstance(preset, str) else preset
    cfg = preset_model_cfg(p)
    opt = make_optimizer(1e-3)
    abstract = jax.eval_shape(
        lambda key: make_train_state(cfg, opt, key), jax.random.key(0))
    return round_dcn_bytes(abstract, p.num_slices)


def preset_model_cfg(preset: Union[str, Preset, ServePreset]):
    """The deterministic tiny ModelConfig a preset measures — the ONE
    model shared by the budget compile, ``analysis check`` and the
    autotune search (whose registry entries are keyed by this model's
    digest, so a tuned plan provably describes the budget model)."""
    from gke_ray_train_tpu.models import tiny
    if isinstance(preset, ServePreset) or (
            isinstance(preset, str) and preset in SERVE_PRESETS):
        p = SERVE_PRESETS[preset] if isinstance(preset, str) else preset
        return _serve_model_cfg(p)
    p = PRESETS[preset] if isinstance(preset, str) else preset
    return tiny(d_model=64, n_layers=2, n_heads=2, n_kv_heads=2,
                d_ff=128, vocab_size=256, max_seq_len=p.seq,
                remat=p.remat)


def plan_for_preset(preset: Union[str, "Preset"]):
    """The ExecutionPlan a budget preset measures under — the SAME plan
    object ``analysis check`` and the budget CLI consume, so one
    fingerprint identifies the preset across budget JSONs, plancheck,
    and the comparator's failure output.

    Measurement policy is part of the identity: budgets are recorded
    donate=False (backend-independent numbers) with no input pipeline
    or guards, on the canonical 8-fake-device CPU mesh. Serve presets
    (``SERVE_PRESETS``) route to :func:`plan_for_serve_preset`."""
    from gke_ray_train_tpu.plan import ExecutionPlan
    if isinstance(preset, ServePreset) or (
            isinstance(preset, str) and preset in SERVE_PRESETS):
        return plan_for_serve_preset(preset)
    p = PRESETS[preset] if isinstance(preset, str) else preset
    mesh = {axis: p.mesh.get(axis, 1)
            for axis in ("data", "fsdp", "model", "context", "pipe")}
    dp = mesh["data"] * mesh["fsdp"]
    return ExecutionPlan.from_kwargs(
        **mesh,
        num_slices=p.num_slices, dcn_sync=p.dcn_sync,
        per_device_batch=max(p.batch // max(dp, 1), 1),
        grad_accum=1, max_seq_len=p.seq, packing=False,
        donate_state=False, donate_batch=False,
        prefetch=0, compile_cache=False, aot_train_step=False,
        # the overlap path IS the measured program (ROADMAP #3): the
        # manual shard_map pipeline's double-buffered fsdp gathers are
        # what moves overlap_frac/exposed_collective_bytes off the
        # PR-9 zero baseline — and the budget comparator is what keeps
        # a de-overlap regression (a gather resharded back next to its
        # consumer) from landing silently. Losses are bitwise-equal to
        # overlap="off" by construction (tests/test_overlap.py).
        overlap=p.overlap,
        topology="cpu-8", budget_preset=p.name)


def build_preset_step(preset: Union[str, Preset], *, remat=None,
                      wrap=None, donate: bool = False,
                      with_jitted: bool = False):
    """(compiled, state, batch) for a preset on the current devices —
    the deterministic compile whose report the budget pins.

    ``wrap(unjitted_step) -> fn``: transform the step before jit — the
    regression tests use it to deliberately smuggle an extra collective
    into the grad path and prove the comparator catches it.
    ``donate``: budgets stay donate=False (backend-independent); the
    analysis CLI's donation check builds the donated twin.
    ``with_jitted``: return (compiled, state, batch, jitted_step) — the
    analysis compile-once check dispatches the JITTED fn (the compiled
    executable can trivially never recompile)."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from gke_ray_train_tpu.train import (
        make_optimizer, make_train_state, make_train_step)

    p = PRESETS[preset] if isinstance(preset, str) else preset
    # ONE ExecutionPlan drives mesh, batch shardings and donation — the
    # same plan object whose fingerprint the budget JSON records
    plan = _dc.replace(plan_for_preset(p), donate_state=donate)
    mesh = plan.build_mesh(jax.devices())
    cfg = preset_model_cfg(p)
    if remat is not None:
        cfg = _dc.replace(cfg, remat=remat)
    opt = make_optimizer(1e-3)
    state = make_train_state(cfg, opt, jax.random.key(0), mesh=mesh)
    # donate_state=False default: budgets must not vary with backend
    # donation support (the analysis donation check opts in explicitly)
    step = make_train_step(cfg, opt, mesh=mesh, plan=plan)
    if wrap is not None:
        step = jax.jit(wrap(step.__wrapped__))
    batch = jax.device_put(
        {"inputs": jnp.zeros((p.batch, p.seq), jnp.int32),
         "targets": jnp.zeros((p.batch, p.seq), jnp.int32),
         "weights": jnp.ones((p.batch, p.seq), jnp.float32)},
        plan.batch_shardings(mesh))
    compiled = step.lower(state, batch).compile()
    if with_jitted:
        return compiled, state, batch, step
    return compiled, state, batch


def build_preset_report(preset: Union[str, Preset, ServePreset],
                        *, remat=None) -> StepCostReport:
    if isinstance(preset, ServePreset) or (
            isinstance(preset, str) and preset in SERVE_PRESETS):
        p = SERVE_PRESETS[preset] if isinstance(preset, str) else preset
        compiled, _, _ = build_serve_preset_step(p)
        # one decode iteration emits one token per slot
        return step_cost_report(compiled, tokens_per_step=p.max_batch)
    p = PRESETS[preset] if isinstance(preset, str) else preset
    compiled, _, _ = build_preset_step(p, remat=remat)
    # the DCN byte attribution runs against the preset's DECLARED slice
    # layout (the fake-8 devices carry no slice_index; num_slices is
    # what maps replica-group positions onto slices)
    return step_cost_report(compiled, tokens_per_step=p.batch * p.seq,
                            num_slices=p.num_slices)


def budget_path(name: str, budget_dir: Optional[str] = None) -> str:
    return os.path.join(budget_dir or BUDGET_DIR, f"{name}.json")


# ---------------------------------------------------------------------------
# CLI: record / check on the canonical 8-fake-device CPU mesh
# ---------------------------------------------------------------------------

def _reexec_on_cpu_mesh(argv) -> int:
    """Budgets are only comparable on the canonical mesh; re-exec this
    CLI in a child whose backend is forced to 8 CPU devices."""
    from gke_ray_train_tpu.perf.cache import cpu_mesh_env
    return subprocess.run(
        [sys.executable, "-m", "gke_ray_train_tpu.perf.budget"] + argv,
        env=cpu_mesh_env(_BUDGET_CLI_NATIVE="1")).returncode


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m gke_ray_train_tpu.perf.budget",
        description="record/check compile-cost budgets on the canonical "
                    "8-fake-device CPU mesh")
    parser.add_argument("command", choices=("record", "check"))
    parser.add_argument("names", nargs="*",
                        help=f"presets (default: all of "
                             f"{all_preset_names()})")
    parser.add_argument("--all", action="store_true", dest="sweep_all",
                        help="sweep EVERY checked-in preset (train + "
                             "hybrid + serve) in one invocation — the "
                             "explicit spelling record_baselines.sh and "
                             "the CI budget step use, so the gate can "
                             "never silently narrow to a hand-kept "
                             "preset list")
    parser.add_argument("--dir", default=BUDGET_DIR,
                        help="budget directory (default tests/budgets)")
    args = parser.parse_args(argv)
    if args.sweep_all and args.names:
        parser.error("--all and explicit preset names are mutually "
                     "exclusive")
    if os.environ.get("_BUDGET_CLI_NATIVE") != "1":
        return _reexec_on_cpu_mesh(
            [args.command] + args.names
            + (["--all"] if args.sweep_all else [])
            + ["--dir", args.dir])

    import jax
    assert jax.devices()[0].platform == "cpu" and len(jax.devices()) == 8, \
        "budget CLI must run on the 8-fake-device CPU mesh"
    names = args.names or all_preset_names()
    rc = 0
    for name in names:
        plan = plan_for_preset(name)
        report = build_budget_doc(name)
        path = budget_path(name, args.dir)
        if args.command == "record":
            write_budget(report, path, preset=name, plan=plan)
            print(f"recorded {path} (plan {plan.fingerprint()})")
        else:
            try:
                assert_within_budget(report, path, plan=plan)
                print(f"{name}: within budget "
                      f"(plan {plan.fingerprint()})")
            except BudgetViolation as e:
                print(e)
                rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
